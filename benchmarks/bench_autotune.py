"""Closed-loop autotuner A/B (ISSUE 14 acceptance): cold defaults vs
the controller vs the hand-benched static optimum.

Three legs over the bench_e2e profile shape (config 1, in-process
cluster, real ordered traffic):

  * ``static-cold`` — a deliberately UNBENCHED knob configuration: the
    kind of generic defaults a deployment on unknown hardware ships
    with (long flush windows sized for a device none may exist, batch
    caps sized for the wrong host, accumulation off). Autotuner off.
  * ``static-best`` — the repo's hand-benched defaults (the operating
    point RESULTS.md rows were measured at on this container).
    Autotuner off: this is the target the controller must reach.
  * ``autotune``   — the SAME cold knobs, autotuner on with a fast
    cadence. The controller must walk the knobs from the cold start
    toward this host's optimum from live telemetry alone.

The acceptance gate: ``autotune_over_best >= 0.9`` — from cold
defaults, the closed loop recovers at least 90% of the hand-benched
configuration's goodput. (On a noisy shared container the ratio is
REPORTED per run; RESULTS.md records the measured samples with the
usual pairing discipline.)

Usage: python -m benchmarks.bench_autotune [--secs 12] [--clients 3]
           [--smoke]
Prints one JSON line per leg plus a summary line.
"""
from __future__ import annotations

import argparse
import json
from typing import Dict

from benchmarks.bench_e2e import run_config

# the "shipped for unknown hardware" cold start: every knob off the
# hand-benched point in the pessimal direction for THIS shape (long
# windows that buy latency with nothing to amortize, no coalescing)
COLD_KNOBS = {
    "verify_batch_flush_us": 2000,
    "verify_batch_size": 32,
    "combine_flush_us": 2500,
    "combine_batch_max": 4,
    "execution_max_accumulation": 1,
}

FAST_TUNER = {
    "autotune_enabled": True,
    "autotune_interval_ms": 100,
    "autotune_cooldown_ms": 250,
}


def _tuning_summary(row: Dict) -> Dict:
    """Fold the tuned leg's controller state (attached by run_config's
    profile hook while the cluster was live) into a compact shape."""
    knobs: Dict[str, Dict] = {}
    steps = flips = 0
    for state in row.pop("tuning_state", {}).values():
        if not isinstance(state, dict):
            continue
        for kname, k in state.get("knobs", {}).items():
            cur = knobs.setdefault(kname, {"values": [], "flips": 0})
            cur["values"].append(k["value"])
            cur["flips"] = max(cur["flips"], k["direction_flips"])
            flips = max(flips, k["direction_flips"])
        steps += sum(1 for d in state.get("decisions", [])
                     if d.get("source") == "policy")
    return {"knobs": knobs, "policy_steps": steps,
            "max_direction_flips": flips}


def run_ab(secs: float, clients: int, profile: bool = False) -> int:
    legs = (
        ("static-cold", {**COLD_KNOBS, "autotune_enabled": False}),
        ("static-best", {"autotune_enabled": False}),
        ("autotune", {**COLD_KNOBS, **FAST_TUNER}),
    )
    rows = {}
    for label, overrides in legs:
        from tpubft.crypto import tpu
        tpu.set_ecdsa_crossover(None)    # leg isolation: process-wide
        row = run_config(1, "cpu", secs, clients,
                         extra_overrides=overrides,
                         profile=profile or label == "autotune")
        row["leg"] = label
        if label == "autotune":
            row["tuning"] = _tuning_summary(row)
            if not profile:
                row.pop("stage_breakdown", None)
                row.pop("kernel_profile", None)
        rows[label] = row
        print(json.dumps(row), flush=True)
    best = rows["static-best"]["ops_per_sec"] or 1.0
    summary = {
        "bench": "autotune_ab", "secs": secs, "clients": clients,
        "cold_ops_per_sec": rows["static-cold"]["ops_per_sec"],
        "best_ops_per_sec": rows["static-best"]["ops_per_sec"],
        "autotune_ops_per_sec": rows["autotune"]["ops_per_sec"],
        "autotune_over_best": round(
            rows["autotune"]["ops_per_sec"] / best, 2),
        "autotune_over_cold": round(
            rows["autotune"]["ops_per_sec"]
            / (rows["static-cold"]["ops_per_sec"] or 1.0), 2),
        "gate_0p9": rows["autotune"]["ops_per_sec"] >= 0.9 * best,
    }
    print(json.dumps(summary), flush=True)
    return 0


def smoke() -> Dict:
    """Tier-1 shape (run under TPUBFT_THREADCHECK=1 by
    tests/test_bench_autotune_smoke.py): every leg orders real traffic,
    the tuned leg's controllers run at full cadence against the live
    cluster, knobs stay in bounds, and nothing oscillates. Timing
    gates stay out of tier-1 (host noise)."""
    from tpubft.utils.racecheck import get_watchdog
    out = {}
    for label, overrides in (
            ("cold", {**COLD_KNOBS, "autotune_enabled": False}),
            ("autotune", {**COLD_KNOBS, **FAST_TUNER,
                          "autotune_interval_ms": 50,
                          "autotune_cooldown_ms": 100})):
        row = run_config(1, "cpu", 2.0, 2, extra_overrides=overrides)
        out[label] = {"ok": row["ops"] > 0, "ops": row["ops"],
                      "ops_per_sec": row["ops_per_sec"]}
    out["stall_reports"] = get_watchdog().stall_reports
    return out


def main(argv=None) -> int:
    from benchmarks.common import setup_cache
    setup_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--secs", type=float, default=12.0,
                    help="measurement window per leg")
    ap.add_argument("--clients", type=int, default=3)
    ap.add_argument("--profile", action="store_true",
                    help="attach stage breakdown + kernel profile per leg")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 shape: short legs, liveness gates only")
    args = ap.parse_args(argv)
    if args.smoke:
        print(json.dumps(smoke()), flush=True)
        return 0
    return run_ab(args.secs, args.clients, profile=args.profile)


if __name__ == "__main__":
    raise SystemExit(main())
