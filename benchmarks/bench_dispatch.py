"""Dispatcher throughput under synthetic verified-traffic flood:
admission plane ON vs OFF.

The measured pipeline is one BACKUP replica's full ingest path — the
transport upcall (`on_new_message`) through parse, client-signature
verification, and the dispatcher handler that arms the dead-primary
liveness clock — with a null transport (sends dropped), so the number
is the replica's message-processing rate, not the network's.

Two flood shapes per mode, back-to-back A/B pairs:

  * distinct   — M individually-signed, never-repeated ClientRequests:
    every message pays a real signature verification. Admission ON
    coalesces them into per-drain `verify_batch` calls on the worker
    pool; OFF runs the legacy dispatcher-unpack + req_batcher path.
  * storm      — K distinct requests replayed to M total (the
    retransmit-flood shape): admission's header peek + within-drain
    duplicate collapse + the SigManager memo shed the repeats before
    the dispatcher pays a full unpack for each.

Completion is observed on the CONSUMER side (admission `processed`
marker / dispatcher `handled_external`, empty queues, no in-flight
verifies), so elapsed time covers the whole pipeline drain.

A third scenario, `--principals N` (ISSUE 19), measures the
million-principal client plane: a backup replica configured with an
N-client universe is flooded from principals strided across the whole
range, then the flood is replayed (the retransmit pass). The client
pubkey table is VIRTUAL (derived on demand from the cluster seed, never
materialized), the client table is the bounded LRU, and the leg asserts
the structural claims — resident records stay under `client_table_max`,
RSS stays under an absolute ceiling, and the verified-signature memo
hit-rate on the replay pass holds at N relative to the 10k baseline leg
run first in the same process. At full scale the leg runs a
sharded-vs-unsharded admission A/B (admission_key_sharding on/off).

Usage: python -m benchmarks.bench_dispatch [--msgs 1200] [--distinct 64]
       [--samples 2] [--workers 2] [--smoke]
       [--principals 1000000 [--table-max 2048] [--rss-ceiling-mb 4096]]
Prints one JSON line per (shape, mode, sample) plus a summary line with
the per-shape median speedups. --smoke runs a tiny fixed shape for
tier-1 (tests/test_bench_dispatch_smoke.py).
"""
from __future__ import annotations

import argparse
import json
import statistics
import time
from collections import OrderedDict
from typing import Iterator, List, Mapping, Optional

from tpubft.comm.interfaces import (ConnectionStatus, ICommunication,
                                    IReceiver, NodeNum)
from tpubft.consensus import messages as m
from tpubft.consensus.keys import ClusterKeys
from tpubft.consensus.replica import Replica
from tpubft.utils.config import ReplicaConfig

F = 1
CLIENTS = 2
SEED = b"bench-dispatch"


class NullComm(ICommunication):
    """Counts sends, delivers nothing: the replica under flood must not
    spend the measurement window on real sockets."""

    def __init__(self) -> None:
        self.sent = 0
        self._running = False

    def start(self, receiver: IReceiver) -> None:
        self._running = True

    def stop(self) -> None:
        self._running = False

    def is_running(self) -> bool:
        return self._running

    def send(self, dest: NodeNum, data: bytes) -> None:
        self.sent += 1

    def get_connection_status(self, node: NodeNum) -> ConnectionStatus:
        return ConnectionStatus.CONNECTED


def _make_replica(workers: int, **cfg_overrides):
    """One backup replica (id 1 of n=4, view 0) with a null transport.
    The view-change timer is parked: a flood bench must not complain its
    way into a view change mid-measurement."""
    from tpubft.apps.counter import CounterHandler
    cfg = ReplicaConfig(replica_id=1, f_val=F,
                        num_of_client_proxies=CLIENTS,
                        admission_workers=workers,
                        view_change_timer_ms=3_600_000,
                        **cfg_overrides)
    keys = ClusterKeys.generate(cfg, CLIENTS, seed=SEED)
    rep = Replica(cfg, keys.for_node(1), NullComm(), CounterHandler())
    rep.start()
    return rep, keys, cfg.n_val + cfg.num_ro_replicas


def _signed_requests(keys, first_client: int, count: int,
                     base_seq: int) -> List[tuple]:
    """`count` distinct signed requests round-robined over the client
    principals; returns [(client_id, packed bytes)]."""
    signers = {c: keys.for_node(c).my_signer()
               for c in range(first_client, first_client + CLIENTS)}
    out = []
    for i in range(count):
        cid = first_client + i % CLIENTS
        req = m.ClientRequestMsg(sender_id=cid,
                                 req_seq_num=base_seq + i // CLIENTS,
                                 flags=0, request=b"flood-%d" % i,
                                 cid="", signature=b"")
        req.signature = signers[cid].sign(req.signed_payload())
        out.append((cid, req.pack()))
    return out


def _drain_done(rep, injected: int, distinct: int) -> bool:
    if rep.admission is not None:
        ingested = rep.admission.processed >= injected
    else:
        ingested = rep.dispatcher.handled_external >= injected
    return (ingested
            and rep.incoming.external_depth == 0
            and rep.incoming.internal_depth == 0
            and not rep._req_verifying
            and len(rep._forwarded) >= distinct)


def _run_flood(rep, flood: List[tuple], distinct: int,
               timeout_s: float = 300.0,
               injected_before: int = 0) -> Optional[float]:
    """`injected_before`: messages this replica already consumed in a
    prior pass (the ingest markers are cumulative — a replay pass must
    wait for ITS messages, not return on the first pass's count)."""
    t0 = time.perf_counter()
    for cid, raw in flood:
        rep.on_new_message(cid, raw)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if _drain_done(rep, injected_before + len(flood), distinct):
            return time.perf_counter() - t0
        time.sleep(0.002)
    return None


def run_pair(shape: str, msgs: int, distinct: int, workers: int,
             sample: int) -> List[dict]:
    """One back-to-back A/B pair (fresh replica per mode, same flood
    content) — the host-noise-pairing convention of RESULTS.md."""
    rows = []
    for mode, w in (("admission", workers), ("inline", 0)):
        rep, keys, first_client = _make_replica(w)
        try:
            base_seq = int(time.time() * 1e6)
            uniq = _signed_requests(keys, first_client,
                                    distinct if shape == "storm" else msgs,
                                    base_seq)
            flood = (uniq * (msgs // len(uniq) + 1))[:msgs] \
                if shape == "storm" else uniq
            dt = _run_flood(rep, flood, min(distinct, msgs)
                            if shape == "storm" else msgs)
            row = {
                "bench": "dispatch_flood", "shape": shape, "mode": mode,
                "sample": sample, "msgs": msgs,
                "distinct": len(uniq), "admission_workers": w,
                "secs": round(dt, 3) if dt else None,
                "msgs_per_sec": round(msgs / dt, 1) if dt else None,
            }
            if rep.admission is not None:
                c = rep.admission.metrics.counters
                row["adm"] = {k: v.value for k, v in c.items()}
            sm = rep.sig.metrics.counters
            row["sig"] = {k: sm[k].value for k in
                          ("memo_hits", "batched_verifies",
                           "scalar_fallbacks")}
            rows.append(row)
        finally:
            rep.stop()
    return rows


def run(msgs: int, distinct: int, samples: int, workers: int,
        shapes=("distinct", "storm"), profile: bool = False) -> List[dict]:
    if profile:
        from tpubft.utils import flight
        flight.reset()
    rows = []
    for shape in shapes:
        for s in range(samples):
            pair = run_pair(shape, msgs, distinct, workers, s)
            rows.extend(pair)
            for r in pair:
                print(json.dumps(r), flush=True)
    # summary: per-shape median speedup over the recorded pairs
    summary = {"bench": "dispatch_flood_summary", "msgs": msgs,
               "workers": workers}
    if profile:
        # the backup-flood shape orders no slots, so the interesting
        # profile here is the ingest plane + kernels; stage_breakdown
        # is attached for symmetry with bench_e2e --profile (it fills
        # up when a shape does order traffic)
        from tpubft.utils import flight
        summary["recorder_enabled"] = flight.enabled()
        summary["stage_breakdown"] = flight.stage_summary()
        summary["kernel_profile"] = flight.kernel_profiler().snapshot()
    for shape in shapes:
        ons = [r["msgs_per_sec"] for r in rows
               if r["shape"] == shape and r["mode"] == "admission"
               and r["msgs_per_sec"]]
        offs = [r["msgs_per_sec"] for r in rows
                if r["shape"] == shape and r["mode"] == "inline"
                and r["msgs_per_sec"]]
        if ons and offs and len(ons) == len(offs):
            ratios = [a / b for a, b in zip(ons, offs)]
            summary[f"{shape}_speedup_median"] = round(
                statistics.median(ratios), 2)
            summary[f"{shape}_speedups"] = [round(x, 2) for x in ratios]
    print(json.dumps(summary), flush=True)
    rows.append(summary)
    return rows


# ---------------------------------------------------------------------
# --principals: million-principal client plane (ISSUE 19)
# ---------------------------------------------------------------------

class LazyClientKeys(Mapping):
    """Virtual `client_pubkeys` for huge principal universes: derives a
    principal's pubkey on demand from the cluster seed (the exact bytes
    ClusterKeys.generate would have produced) instead of materializing
    N entries up front. SigManager keeps non-dict mappings by reference
    for precisely this shape; a small LRU memo keeps repeat lookups
    from the verify plane cheap without growing with the universe."""

    _MEMO_MAX = 8192

    def __init__(self, seed: bytes, scheme: str, first_client: int,
                 count: int, extra: dict) -> None:
        from tpubft.consensus.keys import _derive_seed
        from tpubft.crypto.cpu import make_signer
        self._derive = lambda cl: make_signer(
            scheme, seed=_derive_seed(seed, "client", cl)).public_bytes()
        self._range = range(first_client, first_client + count)
        self._extra = dict(extra)      # operator principal
        self._memo: "OrderedDict[int, bytes]" = OrderedDict()

    def __getitem__(self, cl: int) -> bytes:
        pk = self._extra.get(cl)
        if pk is not None:
            return pk
        if cl not in self._range:
            raise KeyError(cl)
        pk = self._memo.get(cl)
        if pk is None:
            pk = self._memo[cl] = self._derive(cl)
            while len(self._memo) > self._MEMO_MAX:
                self._memo.popitem(last=False)
        return pk

    def __len__(self) -> int:
        return len(self._range) + len(self._extra)

    def __iter__(self) -> Iterator[int]:
        yield from self._range
        yield from (k for k in self._extra if k not in self._range)


def _rss_mb() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) // 1024
    return -1


def _make_principals_replica(scale: int, workers: int, **cfg_overrides):
    """Backup replica fronting a `scale`-principal client universe.
    Client key material is virtual (LazyClientKeys) and the client table
    is the bounded pager (client_table_max must stay > 0 here — the
    legacy eager table would materialize `scale` records at boot)."""
    from tpubft.apps.counter import CounterHandler
    cfg = ReplicaConfig(replica_id=1, f_val=F,
                        num_of_client_proxies=scale,
                        admission_workers=workers,
                        view_change_timer_ms=3_600_000,
                        **cfg_overrides)
    assert cfg.client_table_max > 0, "principals bench needs paged table"
    keys = ClusterKeys.generate(cfg, 0, seed=SEED)   # 0 eager client keys
    first_client = cfg.n_val + cfg.num_ro_replicas
    keys.client_pubkeys = LazyClientKeys(
        SEED, keys.client_sig_scheme, first_client, scale,
        extra=keys.client_pubkeys)
    rep = Replica(cfg, keys.for_node(1), NullComm(), CounterHandler())
    rep.start()
    return rep, first_client


def _principal_flood(scheme: str, first_client: int, scale: int,
                     distinct: int, base_seq: int) -> List[tuple]:
    """`distinct` signed requests from principals strided across the
    whole universe (each principal sends once — the cold-contact shape
    that exercises demand paging, not per-client request streams)."""
    from tpubft.consensus.keys import _derive_seed
    from tpubft.crypto.cpu import make_signer
    stride = max(1, scale // distinct)
    out = []
    for i in range(min(distinct, scale)):
        cid = first_client + i * stride
        signer = make_signer(scheme, seed=_derive_seed(SEED, "client", cid))
        req = m.ClientRequestMsg(sender_id=cid, req_seq_num=base_seq,
                                 flags=0, request=b"p-%d" % i,
                                 cid="", signature=b"")
        req.signature = signer.sign(req.signed_payload())
        out.append((cid, req.pack()))
    return out


def _principals_leg(scale: int, distinct: int, workers: int,
                    table_max: int, sharded: bool) -> dict:
    """One leg: cold flood from `distinct` principals out of a `scale`
    universe, then a replay of the same bytes (the retransmit pass the
    verify memo and client-table LRU exist for)."""
    # autotuning off: the client_table_max knob would (correctly) GROW
    # under a 100%-cold-miss flood, but this leg measures the FIXED
    # bound — the knob's reactions are unit-test/bench_autotune scope
    rep, first_client = _make_principals_replica(
        scale, workers, client_table_max=table_max,
        admission_key_sharding=sharded, autotune_enabled=False)
    try:
        base_seq = int(time.time() * 1e6)
        flood = _principal_flood(rep.keys.client_sig_scheme, first_client,
                                 scale, distinct, base_seq)
        t0 = time.perf_counter()
        dt_cold = _run_flood(rep, flood, len(flood))
        dt_replay = _run_flood(rep, flood, len(flood),
                               injected_before=len(flood)) \
            if dt_cold is not None else None
        total = time.perf_counter() - t0
        sm = rep.sig.metrics.counters
        memo_hits = sm["memo_hits"].value
        row = {
            "bench": "dispatch_principals", "principals": scale,
            "distinct": len(flood), "workers": workers,
            "mode": "sharded" if sharded and workers > 1 else "unsharded",
            "client_table_max": table_max,
            "cold_secs": round(dt_cold, 3) if dt_cold else None,
            "replay_secs": round(dt_replay, 3) if dt_replay else None,
            "msgs_per_sec": round(2 * len(flood) / total, 1)
            if dt_replay else None,
            "rss_mb": _rss_mb(),
            "resident_clients": rep.clients.resident_count,
            "client_table": {"hits": rep.clients.table_hits,
                             "misses": rep.clients.table_misses,
                             "evictions": rep.clients.table_evictions},
            # replay-pass memo hit-rate: of the replayed signatures, how
            # many were shed by the verified-signature memo
            "memo_hits": memo_hits,
            "memo_hit_rate": round(memo_hits / len(flood), 3),
            "sig": {k: sm[k].value for k in
                    ("batched_verifies", "scalar_fallbacks",
                     "verifier_evictions")},
        }
        if rep.admission is not None:
            row["adm"] = {k: v.value
                          for k, v in rep.admission.metrics.counters.items()}
        return row
    finally:
        rep.stop()


def run_principals(principals: int, distinct: int, workers: int,
                   table_max: int, rss_ceiling_mb: int,
                   baseline: int = 10_000) -> List[dict]:
    """The ISSUE 19 scenario: 10k-principal baseline leg, then the full-
    scale leg(s). At full scale, sharded-vs-unsharded admission A/B.
    Asserts the structural claims (bounded residency, RSS ceiling, memo
    hit-rate holding vs the baseline) — a regression fails the bench,
    not just a number in a row."""
    # the flood must outrun the table or the leg never proves eviction
    distinct = max(distinct, table_max + table_max // 2)
    legs = [(min(baseline, principals), True)]
    if principals > baseline:
        legs += [(principals, True)]
        if workers > 1:
            legs += [(principals, False)]
    rows = []
    for scale, sharded in legs:
        row = _principals_leg(scale, distinct, workers, table_max, sharded)
        rows.append(row)
        print(json.dumps(row), flush=True)
    base, tail = rows[0], rows[1:]
    summary = {"bench": "dispatch_principals_summary",
               "principals": principals, "distinct": distinct,
               "workers": workers, "client_table_max": table_max,
               "rss_ceiling_mb": rss_ceiling_mb}
    if len(tail) == 2:      # sharded + unsharded full-scale pair
        a, b = tail[0]["msgs_per_sec"], tail[1]["msgs_per_sec"]
        if a and b:
            summary["sharded_speedup"] = round(a / b, 2)
    for row in rows:
        assert row["replay_secs"] is not None, f"leg did not drain: {row}"
        # bounded residency: the LRU held (the pinned-burst slack is
        # _EVICT_SCAN_MAX, tiny next to the bound)
        assert row["resident_clients"] <= table_max + 8, row
        assert row["rss_mb"] < rss_ceiling_mb, \
            f"RSS {row['rss_mb']}MB over {rss_ceiling_mb}MB ceiling"
    for row in tail:
        # the replay-pass memo hit-rate must hold at full scale: the
        # memo is keyed by (principal, digest, sig), so universe size
        # must not dilute it
        assert row["memo_hit_rate"] >= 0.9 * base["memo_hit_rate"], \
            (row["memo_hit_rate"], base["memo_hit_rate"])
    summary["ok"] = True
    print(json.dumps(summary), flush=True)
    rows.append(summary)
    return rows


def smoke_principals() -> dict:
    """Tier-1 shape: a 10k-principal universe, a flood wider than the
    client table, replayed — asserts bounded residency, real evictions,
    demand re-paging, and the replay memo shed (structure, not speed)."""
    rows = run_principals(principals=10_000, distinct=96, workers=1,
                          table_max=64, rss_ceiling_mb=8192)
    leg = rows[0]
    return {
        "ok": bool(rows[-1].get("ok")),
        "drained": leg["replay_secs"] is not None,
        "bounded": leg["resident_clients"] <= 64 + 8,
        "evicted": leg["client_table"]["evictions"] > 0,
        "repaged": leg["client_table"]["misses"] > leg["distinct"] // 2,
        "memo_shed": leg["memo_hits"] > 0,
        "leg": leg,
    }


def smoke() -> dict:
    """Tier-1 shape: tiny flood through both modes; asserts both drain
    and that the admission plane actually shed the storm repeats before
    the dispatcher (the structural property, not a perf number —
    wall-clock ratios are not asserted in CI)."""
    rows = run(msgs=300, distinct=16, samples=1, workers=1,
               shapes=("storm",))
    on = next(r for r in rows if r.get("mode") == "admission")
    off = next(r for r in rows if r.get("mode") == "inline")
    adm = on["adm"]
    return {
        "ok": bool(on["secs"] and off["secs"]),
        "admission_drained": on["secs"] is not None,
        "inline_drained": off["secs"] is not None,
        # the dispatcher saw only the admitted survivors, not the flood
        "shed": adm["adm_drops_pre_parse"] > 0,
        "adm": adm,
    }


def device_fault(msgs: int = 360, warmup: int = 64,
                 drain_max: int = 16) -> dict:
    """Kill-the-device scenario (degradation plane): the replica runs
    the REAL device verify ride (crypto_backend=tpu on whatever jax
    backend this host has — the breaker's reaction is what's measured,
    not kernel speed). Mid-flood the ed25519 kernel is replaced with a
    raiser ("the accelerator transport died"); recorded:

      * time-to-degraded  — kill → breaker OPEN (consensus ingest keeps
        draining on the scalar engines throughout);
      * time-to-restored  — kernel restored → breaker CLOSED via the
        half-open probe batch, device path hot again.
    """
    import os

    from tpubft.ops import ed25519 as ops_ed
    from tpubft.ops.dispatch import device_breaker

    # persistent compile cache: the windowed verify kernel is a large
    # XLA program; repeat bench runs should not re-pay the compile
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", os.path.abspath(
            os.path.join(os.path.dirname(__file__), "..", ".jax_cache")))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          2.0)
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass

    b = device_breaker()
    rep, keys, first_client = _make_replica(
        1, crypto_backend="tpu", device_min_verify_batch=1,
        admission_drain_max=drain_max,
        breaker_failure_threshold=3, breaker_cooldown_ms=500)
    # bound probe-failure escalation so time-to-restored reflects the
    # configured cooldown, not however long the kill window lasted
    b.configure(max_cooldown_s=1.0)
    b.reset()
    row = {"bench": "dispatch_device_fault", "msgs": msgs,
           "warmup": warmup, "drain_max": drain_max}
    real_kernel = ops_ed.verify_kernel

    def boom(*a, **kw):
        raise RuntimeError("injected device loss")

    try:
        base_seq = int(time.time() * 1e6)
        flood = _signed_requests(keys, first_client, warmup, base_seq)
        dt = _run_flood(rep, flood, warmup, timeout_s=600.0)
        row["warmup_secs"] = round(dt, 3) if dt else None
        row["device_path_proven"] = \
            rep.sig.sigs_device_dispatched.value > 0
        injected = warmup

        # ---- kill the device mid-run ----
        ops_ed.verify_kernel = boom
        t_kill = time.perf_counter()
        t_open = None
        sent = 0
        while sent < msgs:
            chunk = _signed_requests(keys, first_client, drain_max,
                                     base_seq + 10_000 + sent)
            for cid, raw in chunk:
                rep.on_new_message(cid, raw)
            sent += len(chunk)
            injected += len(chunk)
            deadline = time.monotonic() + 30
            while rep.admission.processed < injected \
                    and time.monotonic() < deadline:
                if t_open is None and b.state == "open":
                    t_open = time.perf_counter()
                time.sleep(0.001)
            if t_open is None and b.state == "open":
                t_open = time.perf_counter()
        row["time_to_degraded_ms"] = (
            round((t_open - t_kill) * 1e3, 1) if t_open else None)
        # goodput continued: everything injected after the kill fully
        # drained through the scalar engines
        row["drained_while_degraded"] = \
            rep.admission.processed >= injected
        row["degraded_verifies"] = rep.sig.degraded_verifies.value
        row["scalar_fallbacks"] = rep.sig.scalar_fallbacks.value

        # ---- restore: half-open probe re-admits the device ----
        ops_ed.verify_kernel = real_kernel
        t_restore = time.perf_counter()
        t_closed = None
        deadline = time.monotonic() + 60
        probe_seq = base_seq + 50_000
        while time.monotonic() < deadline:
            # distinct seqs each tick: a duplicate would memo-hit and
            # never reach the device, starving the half-open probe
            probe_seq += 10
            chunk = _signed_requests(keys, first_client, 4, probe_seq)
            for cid, raw in chunk:
                rep.on_new_message(cid, raw)
            injected += len(chunk)
            time.sleep(0.05)
            if b.state == "closed":
                t_closed = time.perf_counter()
                break
        row["time_to_restored_ms"] = (
            round((t_closed - t_restore) * 1e3, 1) if t_closed else None)
        row["breaker"] = b.snapshot()
        row["health"] = rep.health.verdict()["verdict"]
        row["ok"] = bool(row["device_path_proven"] and t_open
                         and t_closed and row["drained_while_degraded"])
        return row
    finally:
        ops_ed.verify_kernel = real_kernel
        rep.stop()
        b.configure(failure_threshold=3, cooldown_s=2.0,
                    max_cooldown_s=32.0)
        b.reset()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--msgs", type=int, default=1200,
                    help="flood size per sample")
    ap.add_argument("--distinct", type=int, default=64,
                    help="distinct signed requests in the storm shape")
    ap.add_argument("--samples", type=int, default=2,
                    help="back-to-back A/B pairs per shape")
    ap.add_argument("--workers", type=int, default=1,
                    help="admission_workers for the ON mode")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--principals", type=int, default=0,
                    help="million-principal client-plane scenario: "
                         "universe size for the full-scale leg")
    ap.add_argument("--table-max", type=int, default=2048,
                    help="client_table_max for the principals legs")
    ap.add_argument("--rss-ceiling-mb", type=int, default=4096,
                    help="asserted RSS ceiling for the principals legs")
    ap.add_argument("--profile", action="store_true",
                    help="attach the flight recorder's stage breakdown "
                         "and kernel profile to the summary row")
    ap.add_argument("--device-fault", action="store_true",
                    help="kill-the-device scenario: time-to-degraded / "
                         "time-to-restored through the breaker")
    args = ap.parse_args()
    if args.smoke:
        print(json.dumps(smoke()), flush=True)
        return
    if args.principals:
        run_principals(args.principals, args.distinct * 8, args.workers,
                       args.table_max, args.rss_ceiling_mb)
        return
    if args.device_fault:
        print(json.dumps(device_fault()), flush=True)
        return
    run(args.msgs, args.distinct, args.samples, args.workers,
        profile=args.profile)


if __name__ == "__main__":
    main()
