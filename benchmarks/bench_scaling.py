"""Multi-chip scaling sweep: sharded verify + MSM at 1/2/4/8 devices.

Usage:  python -m benchmarks.bench_scaling [--devices 1,2,4,8]
        [--batch 2048] [--msm-k 64]

Each width runs in a fresh SUBPROCESS (the virtual-device count is a
process-level XLA flag) and prints one JSON row:
  {"devices": D, "verify_rate": r, "msm_ms": m,
   "verify_shards": D, "shard_rows": batch/D}

What the sweep proves depends on the platform:
- on a REAL multi-chip TPU mesh the rows give the scaling slope
  (verifies/sec should grow toward linear; combine-ms should stay flat
  as the all_gather payload is tiny);
- on the virtual CPU mesh of a 1-core host every "device" multiplexes
  the same core, so wall-clock CANNOT improve — there the sweep
  validates that the sharded programs compile and execute at every
  width, that the partitioner actually splits the batch (shard_rows
  = batch/D on each device), and that going wide costs bounded
  overhead (the regression test's bound).

Reference point: the reference runs both loops on one CPU thread
(SigManager.cpp:197 verify loop; FastMultExp.cpp:27 accumulation) —
its scaling story ends at one core, which is the gap this module's
mesh design exists to beat.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def run_width(d: int, batch: int, msm_k: int,
              platform: str = "cpu") -> dict:
    """One width, current process. Assumes XLA device count already set.
    platform="cpu" pins the virtual CPU mesh (the 1-host validation
    mode); "native" leaves the backend alone so a real chip mesh
    produces the actual scaling slope."""
    import jax
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from benchmarks.common import setup_cache
    setup_cache()
    import numpy as np

    from tpubft.crypto import cpu as ccpu
    from tpubft.ops import ed25519 as ops
    from tpubft.parallel import sharding as sh

    mesh = sh.make_mesh(d)
    assert mesh.devices.size == d

    # ---- data-parallel verify ----
    signer = ccpu.Ed25519Signer.generate(seed=b"scale")
    pk = signer.public_bytes()
    msgs = [b"scale-%d" % (i % 64) for i in range(batch)]
    items = [(m, signer.sign(m), pk) for m in msgs]
    prep = ops.prepare_batch(items)
    kernel = sh.sharded_verify_ed25519(mesh)
    args = (prep.s_win, prep.h_win, prep.a_y, prep.a_sign,
            prep.r_y, prep.r_sign)
    out = kernel(*args)
    out.block_until_ready()                     # compile
    assert bool(np.asarray(out).all())
    shards = out.addressable_shards
    shard_rows = shards[0].data.shape[0]
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        out = kernel(*args)
    out.block_until_ready()
    verify_rate = batch / ((time.perf_counter() - t0) / reps)

    # ---- sharded MSM (threshold-share accumulation shape) ----
    from tpubft.crypto import bls12381 as bls
    pts = [bls.g1_mul(bls.G1_GEN, i + 1) for i in range(msm_k)]
    scalars = [(7 * i + 3) % bls.R for i in range(msm_k)]
    t0 = time.perf_counter()
    acc = sh.sharded_msm(pts, scalars, mesh)
    compile_and_first_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    acc = sh.sharded_msm(pts, scalars, mesh)
    msm_ms = (time.perf_counter() - t0) * 1e3
    # correctness anchor vs the host golden model
    assert acc == bls.g1_msm(pts, scalars), "sharded MSM result mismatch"

    return {"devices": d, "batch": batch,
            "platform": jax.default_backend(),
            "verify_rate": round(verify_rate, 1),
            "verify_shards": len(shards), "shard_rows": int(shard_rows),
            "msm_k": msm_k, "msm_ms": round(msm_ms, 1),
            "msm_first_s": round(compile_and_first_s, 1)}


def run_dispatch_ab(d: int, batch: int, platform: str = "cpu") -> dict:
    """Sharded-vs-single A/B through the PRODUCTION dispatch plane
    (ISSUE 16): the same ed25519 flood routed twice by the live mesh
    tier — once with the CryptoMesh capped at one chip (the pre-mesh
    single-device path) and once at full width. Correctness-gated: the
    two verdict vectors must be byte-identical before any rate is
    reported. On a real mesh the acceptance bar is >= 1.6x at 2 shards;
    on the virtual CPU host mesh every shard multiplexes one core, so
    the row is annotated degraded and only the byte-identity + the
    bounded sharding overhead are the signal."""
    import jax
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from benchmarks.common import setup_cache
    setup_cache()
    import numpy as np

    from tpubft.crypto import cpu as ccpu
    from tpubft.ops import dispatch
    from tpubft.ops import ed25519 as ops

    signer = ccpu.Ed25519Signer.generate(seed=b"scale-ab")
    pk = signer.public_bytes()
    items = [(b"ab-%d" % i, signer.sign(b"ab-%d" % i), pk)
             for i in range(batch)]
    mgr = dispatch.crypto_mesh()
    mgr.reset()

    def leg(cap: int):
        mgr.set_shard_count(cap)
        out = np.asarray(ops.verify_batch(items))       # compile + warm
        reps = 3
        t0 = time.perf_counter()
        for _ in range(reps):
            out = np.asarray(ops.verify_batch(items))
        return out, batch / ((time.perf_counter() - t0) / reps)

    single, single_rate = leg(1)
    shards = 0
    try:
        mgr.set_shard_count(0)
        shards = dispatch.mesh_shards()
        sharded, sharded_rate = leg(0)
    finally:
        mgr.set_shard_count(0)
    assert single.tobytes() == sharded.tobytes(), \
        "A/B verdict vectors diverged between shard widths"
    assert bool(single.all()), "valid flood failed to verify"
    return {"mode": "dispatch-ab", "devices": d, "batch": batch,
            "platform": jax.default_backend(), "shards": shards,
            "single_rate": round(single_rate, 1),
            "sharded_rate": round(sharded_rate, 1),
            "speedup": round(sharded_rate / max(single_rate, 1e-9), 3),
            "verdicts_identical": True}


def run_agg_ab(f: int = 10, fanout: int = 4, writes: int = 10,
               mode: str = "tree", min_reduction: float = 4.0,
               min_goodput_ratio: float = 0.9) -> dict:
    """Aggregation-gossip on/off A/B through a full in-process cluster
    (ISSUE 17): the same skvbc write flood ordered twice by n = 3f+1
    replicas — once with every Prepare/Commit share sent direct to the
    collector (the all-to-all baseline) and once climbing the
    aggregation overlay. One replica is killed in both legs so the
    optimistic fast path can never complete and every slot takes the
    aggregated share path. Gated on the facts the mode claims:

      * per-replica share-datagram reduction — the busiest replica's
        received Prepare/Commit share count drops >= `min_reduction`x
        (O(n) collector fan-in -> O(fanout) per overlay node);
      * byte-identical ledgers — every live replica in BOTH legs ends
        with the same state digest and raw block bytes (aggregation is
        transport, never semantics);
      * goodput — the aggregated leg sustains >= `min_goodput_ratio`
        of baseline write throughput (asserted on real accelerator
        rows; CPU rows report it and carry the degraded annotation).
    """
    import jax

    from tpubft.apps import skvbc
    from tpubft.kvbc import KeyValueBlockchain
    from tpubft.storage.memorydb import MemoryDB
    from tpubft.testing.cluster import InProcessCluster

    def leg(agg_mode: str) -> dict:
        def handler_factory(_r):
            return skvbc.SkvbcHandler(
                KeyValueBlockchain(MemoryDB(), use_device_hashing=False))

        overrides = dict(threshold_scheme="multisig-bls",
                         share_aggregation=agg_mode,
                         # 50ms quiescence window: on a CPU host child
                         # shares trickle in with >10ms gaps, and every
                         # premature flush is an extra datagram up the
                         # tree — the A/B wants ~one flush per subtree
                         # per slot
                         agg_fanout=fanout, agg_flush_ms=50,
                         # sized per the OPERATIONS.md guidance: above
                         # the full CPU-host slow-path slot latency
                         # INCLUDING the first slot's JAX compile stall,
                         # so the A/B measures the overlay, not fallback
                         # churn from a timeout tuned for device hosts
                         agg_parent_timeout_ms=10000,
                         fast_path_timeout_ms=80,
                         view_change_timer_ms=60000)
        cluster = InProcessCluster(f=f, num_clients=1,
                                   handler_factory=handler_factory,
                                   cfg_overrides=overrides)
        n = cluster.n
        try:
            cluster.start()
            cluster.kill(n - 1)
            live = range(n - 1)
            cl = cluster.client(0)
            cl._req_seq = 1_000_000
            kv = skvbc.SkvbcClient(cl)
            t0 = time.perf_counter()
            for i in range(writes):
                assert kv.write([(b"k%d" % i, b"v%d" % i)],
                                timeout_ms=120000).success
            elapsed = time.perf_counter() - t0
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and not all(
                    cluster.handlers[r].blockchain.last_block_id == writes
                    for r in live):
                time.sleep(0.05)
            digests = {cluster.handlers[r].blockchain.state_digest()
                       for r in live}
            assert len(digests) == 1, "live replicas diverged in-leg"
            return {
                "rate": writes / elapsed,
                "rcvd": [cluster.metric(r, "counters",
                                        "share_msgs_received")
                         for r in live],
                "absorbed": cluster.metric(0, "counters",
                                           "agg_partials_absorbed"),
                "fallbacks": sum(
                    cluster.metric(r, "counters", "agg_fallbacks")
                    for r in live),
                "digest": digests.pop(),
                "blocks": [cluster.handlers[0].blockchain.get_raw_block(i)
                           for i in range(1, writes + 1)],
            }
        finally:
            cluster.stop()

    off = leg("off")
    on = leg(mode)
    assert on["digest"] == off["digest"] and on["blocks"] == off["blocks"], \
        "aggregation changed ledger BYTES; it may only change transport"
    assert on["absorbed"] > 0, "overlay never delivered a partial"
    reduction = max(off["rcvd"]) / max(max(on["rcvd"]), 1)
    assert reduction >= min_reduction, (
        f"per-replica share fan-in reduction {reduction:.2f}x under the "
        f"{min_reduction}x bar (off={max(off['rcvd'])}, "
        f"on={max(on['rcvd'])})")
    goodput_ratio = on["rate"] / max(off["rate"], 1e-9)
    platform = jax.default_backend()
    if platform != "cpu":
        assert goodput_ratio >= min_goodput_ratio, (
            f"aggregated goodput ratio {goodput_ratio:.3f} under "
            f"{min_goodput_ratio}")
    n = 3 * f + 1
    return {"mode": "agg-ab", "agg_mode": mode, "n": n, "f": f,
            "fanout": fanout, "writes": writes, "platform": platform,
            "off_rate": round(off["rate"], 2),
            "on_rate": round(on["rate"], 2),
            "goodput_ratio": round(goodput_ratio, 3),
            "off_max_rcvd": max(off["rcvd"]),
            "on_max_rcvd": max(on["rcvd"]),
            "off_collector_rcvd": off["rcvd"][0],
            "on_collector_rcvd": on["rcvd"][0],
            "reduction": round(reduction, 2),
            "fallbacks": on["fallbacks"],
            "ledgers_identical": True}


def agg_ab_smoke(writes: int = 4) -> dict:
    """Tier-1 shape: the smallest overlay whose interior nodes survive
    the fast-path-disabling kill (n=7, fanout 2 — at n=4 the seeded
    permutation seats the killed replica at the only non-root interior
    slot and no partial can ever flow). At this size the reduction is
    marginal by construction — the gates that matter are ledger
    byte-identity and that the overlay actually carried partials."""
    return run_agg_ab(f=2, fanout=2, writes=writes, mode="tree",
                      min_reduction=1.0, min_goodput_ratio=0.0)


def _annotate_degraded(row: dict, probe_error, stderr_tail: str) -> dict:
    """bench.py's artifact convention (PR 4): a row produced on the CPU
    backend is not comparable to a real-chip row and must say so in a
    machine-readable way — `degraded: true` plus a `probe_error`
    explaining WHY, instead of burying XLA warnings in a raw log tail
    (the old MULTICHIP_r0*.json failure mode)."""
    if row.get("platform") != "cpu":
        return row
    row["degraded"] = True          # CPU mesh: validates sharding only
    detail = probe_error or ("virtual CPU host mesh: every 'device' "
                             "multiplexes the same core, so rates are "
                             "not a scaling slope")
    warn = "\n".join(ln for ln in stderr_tail.splitlines()
                     if "WARNING" in ln or ln.startswith("E"))[-400:]
    row["probe_error"] = detail + (f"; stderr: {warn}" if warn else "")
    return row


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", default="1,2,4,8")
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--msm-k", type=int, default=64)
    ap.add_argument("--one-width", type=int, default=0,
                    help="internal: run this width in-process")
    ap.add_argument("--dispatch-ab", action="store_true",
                    help="sharded-vs-single A/B through the production "
                         "dispatch plane (mesh cap 1 vs full width), "
                         "correctness-gated on byte-identical verdicts")
    ap.add_argument("--agg-ab", action="store_true",
                    help="share-aggregation on/off A/B through a full "
                         "in-process cluster: per-replica share fan-in "
                         "reduction + byte-identical ledgers (ISSUE 17)")
    ap.add_argument("--agg-f", type=int, default=10,
                    help="f for the --agg-ab cluster (n = 3f+1; the "
                         "default is the 'n=32' row: f=10 -> n=31, the "
                         "closest n=3f+1 size)")
    ap.add_argument("--agg-fanout", type=int, default=4)
    ap.add_argument("--agg-writes", type=int, default=10)
    ap.add_argument("--agg-mode", default="tree",
                    choices=("tree", "gossip"))
    ap.add_argument("--platform", default="cpu",
                    choices=("cpu", "native"),
                    help="cpu = virtual host-device mesh (1-host "
                         "validation); native = real accelerator mesh "
                         "(the actual scaling slope)")
    args = ap.parse_args()
    if args.agg_ab:
        if args.platform == "cpu":
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
        row = run_agg_ab(f=args.agg_f, fanout=args.agg_fanout,
                         writes=args.agg_writes, mode=args.agg_mode)
        print(json.dumps(_annotate_degraded(row, None, "")))
        return
    if args.one_width:
        if args.dispatch_ab:
            print(json.dumps(run_dispatch_ab(args.one_width, args.batch,
                                             platform=args.platform)))
        else:
            print(json.dumps(run_width(args.one_width, args.batch,
                                       args.msm_k,
                                       platform=args.platform)))
        return
    probe_error = None
    if args.platform == "native":
        # same probe bench.py uses: jax silently falls back to CPU when
        # the accelerator plugin is absent or broken, and a "native" row
        # that actually ran on the CPU must carry the reason
        from bench import _device_probe_once
        ok, probe_error = _device_probe_once()
        if ok:
            probe_error = None
    for d in [int(x) for x in args.devices.split(",")]:
        env = dict(os.environ)
        if args.platform == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={d}").strip()
        cmd = [sys.executable, "-m", "benchmarks.bench_scaling",
               "--one-width", str(d), "--batch", str(args.batch),
               "--msm-k", str(args.msm_k), "--platform", args.platform]
        if args.dispatch_ab:
            cmd.append("--dispatch-ab")
        r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=1800)
        if r.returncode != 0:
            print(json.dumps({"devices": d, "degraded": True,
                              "probe_error": "width subprocess exited "
                              f"rc={r.returncode}",
                              "error": r.stderr[-400:]}))
            continue
        row = json.loads(r.stdout.strip().splitlines()[-1])
        print(json.dumps(_annotate_degraded(row, probe_error, r.stderr)))


if __name__ == "__main__":
    main()
