"""Multi-chip scaling sweep: sharded verify + MSM at 1/2/4/8 devices.

Usage:  python -m benchmarks.bench_scaling [--devices 1,2,4,8]
        [--batch 2048] [--msm-k 64]

Each width runs in a fresh SUBPROCESS (the virtual-device count is a
process-level XLA flag) and prints one JSON row:
  {"devices": D, "verify_rate": r, "msm_ms": m,
   "verify_shards": D, "shard_rows": batch/D}

What the sweep proves depends on the platform:
- on a REAL multi-chip TPU mesh the rows give the scaling slope
  (verifies/sec should grow toward linear; combine-ms should stay flat
  as the all_gather payload is tiny);
- on the virtual CPU mesh of a 1-core host every "device" multiplexes
  the same core, so wall-clock CANNOT improve — there the sweep
  validates that the sharded programs compile and execute at every
  width, that the partitioner actually splits the batch (shard_rows
  = batch/D on each device), and that going wide costs bounded
  overhead (the regression test's bound).

Reference point: the reference runs both loops on one CPU thread
(SigManager.cpp:197 verify loop; FastMultExp.cpp:27 accumulation) —
its scaling story ends at one core, which is the gap this module's
mesh design exists to beat.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def run_width(d: int, batch: int, msm_k: int,
              platform: str = "cpu") -> dict:
    """One width, current process. Assumes XLA device count already set.
    platform="cpu" pins the virtual CPU mesh (the 1-host validation
    mode); "native" leaves the backend alone so a real chip mesh
    produces the actual scaling slope."""
    import jax
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from benchmarks.common import setup_cache
    setup_cache()
    import numpy as np

    from tpubft.crypto import cpu as ccpu
    from tpubft.ops import ed25519 as ops
    from tpubft.parallel import sharding as sh

    mesh = sh.make_mesh(d)
    assert mesh.devices.size == d

    # ---- data-parallel verify ----
    signer = ccpu.Ed25519Signer.generate(seed=b"scale")
    pk = signer.public_bytes()
    msgs = [b"scale-%d" % (i % 64) for i in range(batch)]
    items = [(m, signer.sign(m), pk) for m in msgs]
    prep = ops.prepare_batch(items)
    kernel = sh.sharded_verify_ed25519(mesh)
    args = (prep.s_win, prep.h_win, prep.a_y, prep.a_sign,
            prep.r_y, prep.r_sign)
    out = kernel(*args)
    out.block_until_ready()                     # compile
    assert bool(np.asarray(out).all())
    shards = out.addressable_shards
    shard_rows = shards[0].data.shape[0]
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        out = kernel(*args)
    out.block_until_ready()
    verify_rate = batch / ((time.perf_counter() - t0) / reps)

    # ---- sharded MSM (threshold-share accumulation shape) ----
    from tpubft.crypto import bls12381 as bls
    pts = [bls.g1_mul(bls.G1_GEN, i + 1) for i in range(msm_k)]
    scalars = [(7 * i + 3) % bls.R for i in range(msm_k)]
    t0 = time.perf_counter()
    acc = sh.sharded_msm(pts, scalars, mesh)
    compile_and_first_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    acc = sh.sharded_msm(pts, scalars, mesh)
    msm_ms = (time.perf_counter() - t0) * 1e3
    # correctness anchor vs the host golden model
    assert acc == bls.g1_msm(pts, scalars), "sharded MSM result mismatch"

    return {"devices": d, "batch": batch,
            "platform": jax.default_backend(),
            "verify_rate": round(verify_rate, 1),
            "verify_shards": len(shards), "shard_rows": int(shard_rows),
            "msm_k": msm_k, "msm_ms": round(msm_ms, 1),
            "msm_first_s": round(compile_and_first_s, 1)}


def run_dispatch_ab(d: int, batch: int, platform: str = "cpu") -> dict:
    """Sharded-vs-single A/B through the PRODUCTION dispatch plane
    (ISSUE 16): the same ed25519 flood routed twice by the live mesh
    tier — once with the CryptoMesh capped at one chip (the pre-mesh
    single-device path) and once at full width. Correctness-gated: the
    two verdict vectors must be byte-identical before any rate is
    reported. On a real mesh the acceptance bar is >= 1.6x at 2 shards;
    on the virtual CPU host mesh every shard multiplexes one core, so
    the row is annotated degraded and only the byte-identity + the
    bounded sharding overhead are the signal."""
    import jax
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from benchmarks.common import setup_cache
    setup_cache()
    import numpy as np

    from tpubft.crypto import cpu as ccpu
    from tpubft.ops import dispatch
    from tpubft.ops import ed25519 as ops

    signer = ccpu.Ed25519Signer.generate(seed=b"scale-ab")
    pk = signer.public_bytes()
    items = [(b"ab-%d" % i, signer.sign(b"ab-%d" % i), pk)
             for i in range(batch)]
    mgr = dispatch.crypto_mesh()
    mgr.reset()

    def leg(cap: int):
        mgr.set_shard_count(cap)
        out = np.asarray(ops.verify_batch(items))       # compile + warm
        reps = 3
        t0 = time.perf_counter()
        for _ in range(reps):
            out = np.asarray(ops.verify_batch(items))
        return out, batch / ((time.perf_counter() - t0) / reps)

    single, single_rate = leg(1)
    shards = 0
    try:
        mgr.set_shard_count(0)
        shards = dispatch.mesh_shards()
        sharded, sharded_rate = leg(0)
    finally:
        mgr.set_shard_count(0)
    assert single.tobytes() == sharded.tobytes(), \
        "A/B verdict vectors diverged between shard widths"
    assert bool(single.all()), "valid flood failed to verify"
    return {"mode": "dispatch-ab", "devices": d, "batch": batch,
            "platform": jax.default_backend(), "shards": shards,
            "single_rate": round(single_rate, 1),
            "sharded_rate": round(sharded_rate, 1),
            "speedup": round(sharded_rate / max(single_rate, 1e-9), 3),
            "verdicts_identical": True}


def _annotate_degraded(row: dict, probe_error, stderr_tail: str) -> dict:
    """bench.py's artifact convention (PR 4): a row produced on the CPU
    backend is not comparable to a real-chip row and must say so in a
    machine-readable way — `degraded: true` plus a `probe_error`
    explaining WHY, instead of burying XLA warnings in a raw log tail
    (the old MULTICHIP_r0*.json failure mode)."""
    if row.get("platform") != "cpu":
        return row
    row["degraded"] = True          # CPU mesh: validates sharding only
    detail = probe_error or ("virtual CPU host mesh: every 'device' "
                             "multiplexes the same core, so rates are "
                             "not a scaling slope")
    warn = "\n".join(ln for ln in stderr_tail.splitlines()
                     if "WARNING" in ln or ln.startswith("E"))[-400:]
    row["probe_error"] = detail + (f"; stderr: {warn}" if warn else "")
    return row


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", default="1,2,4,8")
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--msm-k", type=int, default=64)
    ap.add_argument("--one-width", type=int, default=0,
                    help="internal: run this width in-process")
    ap.add_argument("--dispatch-ab", action="store_true",
                    help="sharded-vs-single A/B through the production "
                         "dispatch plane (mesh cap 1 vs full width), "
                         "correctness-gated on byte-identical verdicts")
    ap.add_argument("--platform", default="cpu",
                    choices=("cpu", "native"),
                    help="cpu = virtual host-device mesh (1-host "
                         "validation); native = real accelerator mesh "
                         "(the actual scaling slope)")
    args = ap.parse_args()
    if args.one_width:
        if args.dispatch_ab:
            print(json.dumps(run_dispatch_ab(args.one_width, args.batch,
                                             platform=args.platform)))
        else:
            print(json.dumps(run_width(args.one_width, args.batch,
                                       args.msm_k,
                                       platform=args.platform)))
        return
    probe_error = None
    if args.platform == "native":
        # same probe bench.py uses: jax silently falls back to CPU when
        # the accelerator plugin is absent or broken, and a "native" row
        # that actually ran on the CPU must carry the reason
        from bench import _device_probe_once
        ok, probe_error = _device_probe_once()
        if ok:
            probe_error = None
    for d in [int(x) for x in args.devices.split(",")]:
        env = dict(os.environ)
        if args.platform == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={d}").strip()
        cmd = [sys.executable, "-m", "benchmarks.bench_scaling",
               "--one-width", str(d), "--batch", str(args.batch),
               "--msm-k", str(args.msm_k), "--platform", args.platform]
        if args.dispatch_ab:
            cmd.append("--dispatch-ab")
        r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=1800)
        if r.returncode != 0:
            print(json.dumps({"devices": d, "degraded": True,
                              "probe_error": "width subprocess exited "
                              f"rc={r.returncode}",
                              "error": r.stderr[-400:]}))
            continue
        row = json.loads(r.stdout.strip().splitlines()[-1])
        print(json.dumps(_annotate_degraded(row, probe_error, r.stderr)))


if __name__ == "__main__":
    main()
