"""BLS threshold-signature microbenchmark.

Rebuild of the reference's threshsign bench harness
(/root/reference/threshsign/bench/BenchThresholdBls.cpp:36,208 +
bench/lib/IThresholdSchemeBenchmark.h): per-op latency for share signing,
share verification, accumulation+combine (Lagrange + MSM — the TPU-target
op, FastMultExp.cpp:27), combined-signature pairing verification, and the
batch-verification tree (BlsBatchVerifier.cpp:44) at SBFT cluster sizes
n ∈ {4, 7, 31, 501, 1000} (reference cases stop at 501; 1000 is the
BASELINE.json north-star scale).

Usage: python -m benchmarks.bench_bls [--cases 4,7,31] [--json]
Each case prints one JSON line; paste into BASELINE.md.
"""
from __future__ import annotations

import argparse
import json
import time
from typing import Dict

from tpubft.crypto import bls12381 as bls
from tpubft.crypto.digest import digest as sha256
from tpubft.crypto.interfaces import Cryptosystem

# (n, threshold): threshold = 2f+c+1 slow-path quorum of the largest f
# with n = 3f+2c+1, c=0 (SBFT; ReplicasInfo quorum arithmetic)
CASES = {4: 3, 7: 5, 31: 21, 501: 335, 1000: 667}


def _timeit(fn, reps: int) -> float:
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    return (time.perf_counter() - t0) / reps


def bench_case(n: int, k: int, seed: bytes = b"bls-bench") -> Dict:
    t0 = time.perf_counter()
    system = Cryptosystem("threshold-bls", k, n, seed=seed)
    keygen_s = time.perf_counter() - t0
    digest = sha256(b"bls-bench-message")

    signers = [system.create_threshold_signer(i) for i in range(1, k + 1)]
    verifier = system.create_threshold_verifier()

    # share signing (hash-to-G1 + one G1 mul)
    sign_s = _timeit(lambda: signers[0].sign_share(digest),
                     reps=8 if n >= 501 else 32)
    t0 = time.perf_counter()
    shares = [s.sign_share(digest) for s in signers]
    all_sign_s = time.perf_counter() - t0

    # single share verification (2 pairings)
    share_verify_s = _timeit(
        lambda: verifier.verify_share(1, digest, shares[0]), reps=4)

    # accumulate + combine (Lagrange coefficients + k-point G1 MSM)
    def combine():
        acc = verifier.new_accumulator(with_share_verification=False)
        acc.set_expected_digest(digest)
        for sid, share in enumerate(shares, start=1):
            acc.add(sid, share)
        return acc.get_full_signed_data()

    combine_s = _timeit(combine, reps=2 if n >= 501 else 8)
    combined = combine()

    # combined-signature verification (2 pairings)
    verify_s = _timeit(lambda: verifier.verify(digest, combined), reps=4)
    assert verifier.verify(digest, combined)

    # batch share verification: all-good root check, then isolation cost
    # with one bad share (O(log k) pairing checks)
    h = bls.hash_to_g1(digest)
    pks = [verifier.share_pk(i) for i in range(1, k + 1)]
    pts = [bls.g1_decompress(s) for s in shares]
    tree = bls.BlsBatchVerifier(pks, h)
    t0 = time.perf_counter()
    verdicts = tree.batch_verify(pts)
    batch_good_s = time.perf_counter() - t0
    assert all(verdicts)
    good_checks = tree.checks

    bad = list(pts)
    bad[k // 2] = bls.G1_GEN                    # forged share
    tree = bls.BlsBatchVerifier(pks, h)
    t0 = time.perf_counter()
    verdicts = tree.batch_verify(bad)
    batch_onebad_s = time.perf_counter() - t0
    assert verdicts.count(False) == 1
    return {
        "n": n, "k": k, "native": bls.bls_native.available()
        if hasattr(bls, "bls_native") else None,
        "keygen_s": round(keygen_s, 4),
        "sign_share_us": round(sign_s * 1e6, 1),
        "sign_all_k_s": round(all_sign_s, 4),
        "verify_share_us": round(share_verify_s * 1e6, 1),
        "accumulate_combine_ms": round(combine_s * 1e3, 2),
        "verify_combined_us": round(verify_s * 1e6, 1),
        "batch_verify_all_good_ms": round(batch_good_s * 1e3, 2),
        "batch_good_pairing_checks": good_checks,
        "batch_verify_one_bad_ms": round(batch_onebad_s * 1e3, 2),
        "batch_onebad_pairing_checks": tree.checks,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cases", default="4,7,31,501,1000")
    args = ap.parse_args()
    from tpubft.crypto import bls_native
    for n in [int(x) for x in args.cases.split(",")]:
        row = bench_case(n, CASES[n])
        row["native"] = bls_native.available()
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
