"""Measure CPU↔device crossovers (TPUBFT_MSM_CROSSOVER_K and
TPUBFT_ECDSA_CROSSOVER_B).

Default mode — BLS combine: for each quorum size k, build a
threshold-BLS certificate through both accumulators — the CPU native
path (Lagrange + Pippenger MSM, tpubft/native/bls12381.cpp) and the
device path (host Lagrange + the batched curve MSM kernel,
ops/bls12_381.combine_shares) — and report ms per combine. The
crossover is the smallest k where the device wins; export it as
TPUBFT_MSM_CROSSOVER_K (consumed by
crypto/tpu.TpuBlsThresholdAccumulator). Reference counterpart:
threshsign/bench/BenchThresholdBls.cpp:208 + FastMultExp.cpp:27.

`--ecdsa` mode: for each batch size B, A/B three ECDSA verification
tiers over a realistic multi-principal corpus — the per-item
`scalar.ecdsa_verify` loop (the 30-34/s-class degraded cliff BENCH_r05
recorded), the batched host engine (`scalar.ecdsa_verify_batch`:
Montgomery batch inversion + comb tables + lockstep affine walk), and
the device RLC kernel (`ops/ecdsa.rlc_verify_batch`: one MSM-shaped
launch per batch). The crossover is the smallest B where the device
beats the batched host; export it as TPUBFT_ECDSA_CROSSOVER_B
(consumed by crypto/tpu.verify_batch_mixed, i.e. the SigManager device
ride). Rows carry the `degraded`/`probe_error` convention: on the
XLA-CPU fallback the "device" column is not a device number and says
so machine-readably.

Usage: python -m benchmarks.bench_msm_crossover [--ks 8,32,128,512,667]
       python -m benchmarks.bench_msm_crossover --ecdsa \
           [--batches 16,64,256,1024] [--curve secp256k1] [--principals 8]
"""
from __future__ import annotations

import argparse
import json
import time


def bench_k(n: int, k: int, reps: int) -> dict:
    from tpubft.crypto.interfaces import Cryptosystem
    from tpubft.crypto.tpu import make_threshold_verifier
    cs = Cryptosystem("threshold-bls", k, n, seed=b"xover-%d" % k)
    digest = b"x" * 32
    shares = [(i, cs.create_threshold_signer(i).sign_share(digest))
              for i in range(1, k + 1)]
    cpu_v = cs.create_threshold_verifier()
    dev_v = make_threshold_verifier("threshold-bls", k, n, cs.public_key,
                                    cs.share_public_keys)

    def combine(v):
        acc = v.new_accumulator(with_share_verification=False)
        acc.set_expected_digest(digest)
        for i, s in shares:
            acc.add(i, s)
        return acc.get_full_signed_data()

    import os
    os.environ["TPUBFT_MSM_CROSSOVER_K"] = "1"   # force device path
    try:
        assert combine(dev_v) == combine(cpu_v)
        best_cpu = best_dev = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            combine(cpu_v)
            best_cpu = min(best_cpu, time.perf_counter() - t0)
            t0 = time.perf_counter()
            combine(dev_v)
            best_dev = min(best_dev, time.perf_counter() - t0)
    finally:
        del os.environ["TPUBFT_MSM_CROSSOVER_K"]
    return {"k": k, "cpu_ms": round(best_cpu * 1e3, 1),
            "device_ms": round(best_dev * 1e3, 1),
            "device_wins": best_dev < best_cpu}


def _ecdsa_corpus(curve: str, batch: int, principals: int):
    from tpubft.crypto import cpu
    # fresh principals PER ROW (seed includes the batch size): the
    # scalar engine's pubkey/comb caches are module-level, so reusing
    # keys across rows would turn every later row's "cold" column into
    # a warm measurement
    signers = [cpu.EcdsaSigner.generate(
        curve, seed=b"xover-ec-%d-%d" % (batch, j))
               for j in range(max(1, min(principals, batch)))]
    items = []
    for i in range(batch):
        s = signers[i % len(signers)]
        msg = b"xover-msg-%d" % i
        items.append((s.public_bytes(), msg, s.sign(msg)))
    return items


def bench_ecdsa_batch(curve: str, batch: int, principals: int,
                      reps: int) -> dict:
    """One row of the three-tier A/B at a fixed batch size. The batched
    host is measured WARM (per-principal combs hot): BFT principals are
    long-lived, so steady state is the honest number — the one-time
    comb build cost is reported separately."""
    from tpubft.crypto import scalar
    from tpubft.ops import ecdsa as ops_ecdsa
    # fresh cache per row: earlier rows' principals must not hold the
    # TPUBFT_ECDSA_HOT_COMBS slots (a sweep wide enough to exhaust the
    # cap would silently measure the cold tier as "warm")
    scalar.reset_ecdsa_caches()
    items = _ecdsa_corpus(curve, batch, principals)
    kernel_items = [(m, s, pk) for pk, m, s in items]

    # per-item scalar loop — the degraded-mode baseline being rescued
    loop_n = min(batch, 32)
    t0 = time.perf_counter()
    for pk, m, s in items[:loop_n]:
        assert scalar.ecdsa_verify(pk, m, s, curve)
    loop_s = (time.perf_counter() - t0) / loop_n

    # batched host: first call builds cold combs; heat to the hot tier
    t0 = time.perf_counter()
    assert all(scalar.ecdsa_verify_batch(items, curve))
    cold_s = time.perf_counter() - t0
    for _ in range(max(1, (scalar._COMB_HOT_AFTER * len(
            {pk for pk, _, _ in items}) // max(1, batch)) + 1)):
        scalar.ecdsa_verify_batch(items, curve)
    best_host = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        assert all(scalar.ecdsa_verify_batch(items, curve))
        best_host = min(best_host, time.perf_counter() - t0)

    # device RLC kernel (one launch per batch; compile excluded)
    assert ops_ecdsa.rlc_verify_batch(curve, kernel_items).all()
    best_dev = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        ops_ecdsa.rlc_verify_batch(curve, kernel_items)
        best_dev = min(best_dev, time.perf_counter() - t0)

    return {"curve": curve, "batch": batch,
            "principals": len({pk for pk, _, _ in items}),
            "scalar_loop_per_s": round(1.0 / loop_s, 1),
            "host_batch_per_s": round(batch / best_host, 1),
            "host_cold_first_ms": round(cold_s * 1e3, 1),
            "device_rlc_per_s": round(batch / best_dev, 1),
            "host_vs_loop": round(loop_s * batch / best_host, 1),
            "device_wins": best_dev < best_host}


def main_ecdsa(args) -> None:
    import jax
    probe_error = None
    platform = jax.devices()[0].platform
    if platform == "cpu":
        from bench import _device_probe_once
        ok, probe_error = _device_probe_once()
        if ok:
            probe_error = None
    rows = []
    for batch in [int(x) for x in args.batches.split(",")]:
        row = bench_ecdsa_batch(args.curve, batch, args.principals,
                                args.reps)
        row["platform"] = platform
        if platform == "cpu":
            row["degraded"] = True      # "device" column = XLA-CPU
            row["probe_error"] = probe_error or (
                "default backend is cpu: the device_rlc column measures "
                "the XLA-CPU fallback, not an accelerator")
        rows.append(row)
        print(json.dumps(row), flush=True)
    crossover = min((r["batch"] for r in rows if r["device_wins"]),
                    default=None)
    summary = {"crossover_b": crossover}
    if args.seed_out:
        # knob-registry seed file, the ISSUE-14 handoff: the autotuner
        # loads it at replica wiring (ReplicaConfig.autotune_seed_file)
        # and re-baselines the knob's default to the measured value —
        # replacing the old copy-an-env-export workflow. No measured
        # crossover (host always wins, this container's XLA-CPU case)
        # seeds the always-host sentinel instead of omitting the knob,
        # so the seed still overrides a stale env export.
        from tpubft.tuning.knobs import write_seed
        value = crossover if crossover is not None else 1 << 20
        summary["seed_file"] = write_seed(
            args.seed_out, {"ecdsa_crossover_b": value},
            note="bench_msm_crossover --ecdsa (%s): device RLC vs "
                 "batched host, batches %s" % (args.curve, args.batches))
        summary["recommend"] = (
            "--config-override autotune_seed_file=%s" % args.seed_out)
    else:
        summary["recommend"] = (
            "rerun with --seed-out <path> to emit a knob-registry seed "
            "file (autotune_seed_file)"
            if crossover is not None
            else "batched host always wins here; SigManager routes "
                 "ECDSA to ecdsa_verify_batch (--seed-out pins it)")
    print(json.dumps(summary), flush=True)


def main() -> None:
    from benchmarks.common import setup_cache
    setup_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--ks", default="8,32,128,512,667")
    ap.add_argument("--n", type=int, default=1000)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--ecdsa", action="store_true",
                    help="measure the ECDSA device-vs-batched-host "
                         "crossover instead of the BLS combine")
    ap.add_argument("--batches", default="16,64,256,1024")
    ap.add_argument("--curve", default="secp256k1",
                    choices=("secp256k1", "secp256r1"))
    ap.add_argument("--principals", type=int, default=8)
    ap.add_argument("--seed-out", default=None,
                    help="with --ecdsa: write the measured crossover as "
                         "a knob-registry seed file (load via "
                         "ReplicaConfig.autotune_seed_file) instead of "
                         "an env-export line")
    args = ap.parse_args()
    if args.ecdsa:
        main_ecdsa(args)
        return
    import jax
    rows = []
    for k in [int(x) for x in args.ks.split(",")]:
        row = bench_k(max(args.n, k), k, args.reps)
        row["platform"] = jax.devices()[0].platform
        rows.append(row)
        print(json.dumps(row), flush=True)
    crossover = min((r["k"] for r in rows if r["device_wins"]),
                    default=None)
    print(json.dumps({"crossover_k": crossover,
                      "recommend": "TPUBFT_MSM_CROSSOVER_K=%s"
                      % (crossover or "unset (CPU always wins here)")}),
          flush=True)


if __name__ == "__main__":
    main()
