"""Measure the CPU↔device combine crossover (sets TPUBFT_MSM_CROSSOVER_K).

For each quorum size k: build a threshold-BLS certificate through both
accumulators — the CPU native path (Lagrange + Pippenger MSM,
tpubft/native/bls12381.cpp) and the device path (host Lagrange + the
batched curve MSM kernel, ops/bls12_381.combine_shares) — and report
ms per combine. The crossover is the smallest k where the device wins;
export it as TPUBFT_MSM_CROSSOVER_K (consumed by
crypto/tpu.TpuBlsThresholdAccumulator). Reference counterpart:
threshsign/bench/BenchThresholdBls.cpp:208 + FastMultExp.cpp:27.

Usage: python -m benchmarks.bench_msm_crossover [--ks 8,32,128,512,667]
"""
from __future__ import annotations

import argparse
import json
import time


def bench_k(n: int, k: int, reps: int) -> dict:
    from tpubft.crypto.interfaces import Cryptosystem
    from tpubft.crypto.tpu import make_threshold_verifier
    cs = Cryptosystem("threshold-bls", k, n, seed=b"xover-%d" % k)
    digest = b"x" * 32
    shares = [(i, cs.create_threshold_signer(i).sign_share(digest))
              for i in range(1, k + 1)]
    cpu_v = cs.create_threshold_verifier()
    dev_v = make_threshold_verifier("threshold-bls", k, n, cs.public_key,
                                    cs.share_public_keys)

    def combine(v):
        acc = v.new_accumulator(with_share_verification=False)
        acc.set_expected_digest(digest)
        for i, s in shares:
            acc.add(i, s)
        return acc.get_full_signed_data()

    import os
    os.environ["TPUBFT_MSM_CROSSOVER_K"] = "1"   # force device path
    try:
        assert combine(dev_v) == combine(cpu_v)
        best_cpu = best_dev = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            combine(cpu_v)
            best_cpu = min(best_cpu, time.perf_counter() - t0)
            t0 = time.perf_counter()
            combine(dev_v)
            best_dev = min(best_dev, time.perf_counter() - t0)
    finally:
        del os.environ["TPUBFT_MSM_CROSSOVER_K"]
    return {"k": k, "cpu_ms": round(best_cpu * 1e3, 1),
            "device_ms": round(best_dev * 1e3, 1),
            "device_wins": best_dev < best_cpu}


def main() -> None:
    from benchmarks.common import setup_cache
    setup_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--ks", default="8,32,128,512,667")
    ap.add_argument("--n", type=int, default=1000)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()
    import jax
    rows = []
    for k in [int(x) for x in args.ks.split(",")]:
        row = bench_k(max(args.n, k), k, args.reps)
        row["platform"] = jax.devices()[0].platform
        rows.append(row)
        print(json.dumps(row), flush=True)
    crossover = min((r["k"] for r in rows if r["device_wins"]),
                    default=None)
    print(json.dumps({"crossover_k": crossover,
                      "recommend": "TPUBFT_MSM_CROSSOVER_K=%s"
                      % (crossover or "unset (CPU always wins here)")}),
          flush=True)


if __name__ == "__main__":
    main()
