"""kvbcbench — ledger write/read throughput per engine.

Rebuild of the reference's kvbc benchmark harness
(/root/reference/kvbc/benchmark/kvbcbench/main.cpp): block-add throughput
with mixed category types, latest/versioned read rates, and the
pre-execution conflict-detection cost (readset validation against the
latest index), for both the categorized and v4 engines over both the
memory and native log-structured DBs.

Usage: python -m benchmarks.bench_kvbc [--blocks 2000] [--keys-per-block 8]
Prints one JSON line per (engine, db) combination.
"""
from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

from tpubft.kvbc import BLOCK_MERKLE, VERSIONED_KV, BlockUpdates, \
    create_blockchain
from tpubft.storage.memorydb import MemoryDB


def _db(kind: str, tmp: str):
    if kind == "memory":
        return MemoryDB()
    from tpubft.storage.native import NativeDB
    return NativeDB(os.path.join(tmp, f"bench-{time.time_ns()}.kvlog"))


def bench(engine: str, db_kind: str, blocks: int, keys_per_block: int,
          tmp: str) -> dict:
    db = _db(db_kind, tmp)
    # the categorized engine pays Merkle maintenance only for
    # block_merkle categories — benchmark the mixed-shape block the
    # reference's kvbcbench writes (merkle + versioned)
    bc = create_blockchain(db, version=engine, use_device_hashing=False)
    t0 = time.perf_counter()
    for b in range(blocks):
        up = BlockUpdates()
        for i in range(keys_per_block):
            k = b"k-%d" % ((b * keys_per_block + i) % (blocks * 2))
            up.put("bench", k, b"v-%d-%d" % (b, i), VERSIONED_KV)
        if engine != "v4":
            up.put("proven", b"m-%d" % (b % 64), b"mv-%d" % b, BLOCK_MERKLE)
        else:
            up.put("proven", b"m-%d" % (b % 64), b"mv-%d" % b, VERSIONED_KV)
        bc.add_block(up)
    add_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    reads = blocks
    for b in range(reads):
        k = b"k-%d" % ((b * keys_per_block) % (blocks * 2))
        bc.get_latest("bench", k)
    latest_s = time.perf_counter() - t0

    # pre-execution conflict detection: validate a readset of
    # keys-per-block keys against the latest index (skvbc conflict rule)
    t0 = time.perf_counter()
    checks = blocks
    conflicts = 0
    for b in range(checks):
        rv = bc.last_block_id // 2
        for i in range(keys_per_block):
            k = b"k-%d" % ((b * keys_per_block + i) % (blocks * 2))
            got = bc.get_latest("bench", k)
            if got is not None and got[0] > rv:
                conflicts += 1
                break
    conflict_s = time.perf_counter() - t0
    db.close()
    return {
        "engine": engine, "db": db_kind, "blocks": blocks,
        "keys_per_block": keys_per_block,
        "add_blocks_per_sec": round(blocks / add_s, 1),
        "latest_reads_per_sec": round(reads / latest_s, 1),
        "conflict_checks_per_sec": round(checks / conflict_s, 1),
    }


def bench_group_commit(tmp: str, runs: int = 256,
                       ops_per_run: int = 16) -> list:
    """The durability pipeline's storage seam in isolation (ISSUE 15):
    `runs` run-shaped WriteBatches made durable per-run (one apply +
    one fsync each — the pre-pipeline durable path) vs group-committed
    (`NativeDB.write_group` concatenated apply + ONE `sync()` per
    group) at growing group sizes. Measures exactly what the pipeline
    amortizes, on THIS host's disk, independent of how hard the
    consensus plane can drive it."""
    from tpubft.storage.interfaces import WriteBatch
    from tpubft.storage.native import NativeDB
    rows = []
    for group in (1, 4, 8, 16):
        path = os.path.join(tmp, f"gc-{group}-{time.time_ns()}.kvlog")
        db = NativeDB(path, sync_writes=False)
        batches = []
        for r in range(runs):
            wb = WriteBatch()
            for i in range(ops_per_run):
                wb.put(b"k-%d-%d" % (r, i), b"v" * 64, b"blk")
            batches.append(wb)
        t0 = time.perf_counter()
        fsyncs = 0
        for start in range(0, runs, group):
            chunk = batches[start:start + group]
            if group == 1:
                db.write(chunk[0])          # the per-run durable path
            else:
                db.write_group(chunk)
            db.sync()
            fsyncs += 1
        dt = time.perf_counter() - t0
        db.close()
        rows.append({"mode": "group-commit", "group": group,
                     "runs": runs, "ops_per_run": ops_per_run,
                     "fsyncs": fsyncs,
                     "durable_runs_per_sec": round(runs / dt, 1),
                     "fsync_ms_per_run": round(dt / runs * 1e3, 3)})
    return rows


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--blocks", type=int, default=2000)
    ap.add_argument("--keys-per-block", type=int, default=8)
    ap.add_argument("--group-commit", action="store_true",
                    help="durability-seam A/B: per-run fsync vs "
                         "write_group + one fsync per group")
    args = ap.parse_args()
    with tempfile.TemporaryDirectory() as tmp:
        if args.group_commit:
            for row in bench_group_commit(tmp):
                print(json.dumps(row), flush=True)
            return
        for engine in ("categorized", "v4"):
            for db_kind in ("memory", "native"):
                print(json.dumps(bench(engine, db_kind, args.blocks,
                                       args.keys_per_block, tmp)),
                      flush=True)


if __name__ == "__main__":
    main()
