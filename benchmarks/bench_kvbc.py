"""kvbcbench — ledger write/read throughput per engine.

Rebuild of the reference's kvbc benchmark harness
(/root/reference/kvbc/benchmark/kvbcbench/main.cpp): block-add throughput
with mixed category types, latest/versioned read rates, and the
pre-execution conflict-detection cost (readset validation against the
latest index), for both the categorized and v4 engines over both the
memory and native log-structured DBs.

Usage: python -m benchmarks.bench_kvbc [--blocks 2000] [--keys-per-block 8]
Prints one JSON line per (engine, db) combination.
"""
from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

from tpubft.kvbc import BLOCK_MERKLE, VERSIONED_KV, BlockUpdates, \
    create_blockchain
from tpubft.storage.memorydb import MemoryDB


def _db(kind: str, tmp: str):
    if kind == "memory":
        return MemoryDB()
    from tpubft.storage.native import NativeDB
    return NativeDB(os.path.join(tmp, f"bench-{time.time_ns()}.kvlog"))


def bench(engine: str, db_kind: str, blocks: int, keys_per_block: int,
          tmp: str) -> dict:
    db = _db(db_kind, tmp)
    # the categorized engine pays Merkle maintenance only for
    # block_merkle categories — benchmark the mixed-shape block the
    # reference's kvbcbench writes (merkle + versioned)
    bc = create_blockchain(db, version=engine, use_device_hashing=False)
    t0 = time.perf_counter()
    for b in range(blocks):
        up = BlockUpdates()
        for i in range(keys_per_block):
            k = b"k-%d" % ((b * keys_per_block + i) % (blocks * 2))
            up.put("bench", k, b"v-%d-%d" % (b, i), VERSIONED_KV)
        if engine != "v4":
            up.put("proven", b"m-%d" % (b % 64), b"mv-%d" % b, BLOCK_MERKLE)
        else:
            up.put("proven", b"m-%d" % (b % 64), b"mv-%d" % b, VERSIONED_KV)
        bc.add_block(up)
    add_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    reads = blocks
    for b in range(reads):
        k = b"k-%d" % ((b * keys_per_block) % (blocks * 2))
        bc.get_latest("bench", k)
    latest_s = time.perf_counter() - t0

    # pre-execution conflict detection: validate a readset of
    # keys-per-block keys against the latest index (skvbc conflict rule)
    t0 = time.perf_counter()
    checks = blocks
    conflicts = 0
    for b in range(checks):
        rv = bc.last_block_id // 2
        for i in range(keys_per_block):
            k = b"k-%d" % ((b * keys_per_block + i) % (blocks * 2))
            got = bc.get_latest("bench", k)
            if got is not None and got[0] > rv:
                conflicts += 1
                break
    conflict_s = time.perf_counter() - t0
    db.close()
    return {
        "engine": engine, "db": db_kind, "blocks": blocks,
        "keys_per_block": keys_per_block,
        "add_blocks_per_sec": round(blocks / add_s, 1),
        "latest_reads_per_sec": round(reads / latest_s, 1),
        "conflict_checks_per_sec": round(checks / conflict_s, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--blocks", type=int, default=2000)
    ap.add_argument("--keys-per-block", type=int, default=8)
    args = ap.parse_args()
    with tempfile.TemporaryDirectory() as tmp:
        for engine in ("categorized", "v4"):
            for db_kind in ("memory", "native"):
                print(json.dumps(bench(engine, db_kind, args.blocks,
                                       args.keys_per_block, tmp)),
                      flush=True)


if __name__ == "__main__":
    main()
