"""Mixed read/write serving-plane benchmark (the read-scaling story).

Writes order through full consensus; reads are served either

  * ``thin``      — off the consensus path by the thin-replica tier:
    single-server digest-authenticated reads, each one verified against
    the f+1-signed checkpoint anchor (sparse-merkle audit path against
    the anchored root, value bound to the proven hash); or
  * ``consensus`` — the control: the same reads ride ClientRequest
    admission + the read-only quorum path on the replicas.

The A/B pairing discipline (same writers/readers/duration, one knob
flipped) shows whether read traffic scales independently of the write
pipeline: the thin rows must hold write goodput while adding read
throughput the consensus rows can't.

Every thin read in the bench is proof-verified; a row records
``reads_verified`` == ``read_ops``. A corrupted-server drill (a server
that bit-flips served values) runs alongside: the row reports
``corrupt_server_detected`` — a forged read must raise, never serve.

Usage: python -m benchmarks.bench_reads [--secs 10] [--writers 2]
       [--readers 4] [--modes thin,consensus] [--preexec]
Prints one JSON line per (mode,) row.
"""
from __future__ import annotations

import argparse
import json
import statistics
import threading
import time
from typing import List

from tpubft.apps import skvbc
from tpubft.kvbc import KeyValueBlockchain
from tpubft.storage import MemoryDB
from tpubft.testing.cluster import InProcessCluster
from tpubft.thinreplica import ThinReplicaClient, keys_cert_verifier

KEYS = 32                      # hot working set the writers churn
COLD_KEYS = 64                 # read-mostly set seeded once at warmup
HOT_READ_EVERY = 8             # 1-in-8 reads hit the hot (churning) set
ANCHOR_REFRESH_EVERY = 16      # reads between anchor roll-forwards

_OVERRIDES = dict(
    thin_replica_enabled=True,
    # small checkpoint window so the signed anchor rolls forward at
    # bench timescales (the anchor is the read tier's staleness bound)
    checkpoint_window_size=16, work_window_size=32)


def _handler_factory(_r=None):
    return skvbc.SkvbcHandler(
        KeyValueBlockchain(MemoryDB(), use_device_hashing=False),
        merkle=True)


def _pct(vals: List[float], q: float) -> float:
    return round(vals[min(len(vals) - 1, int(len(vals) * q))] * 1e3, 2) \
        if vals else 0.0


def run_mixed(mode: str, secs: float, writers: int, readers: int,
              f: int = 1, preexec: bool = False,
              op_timeout_ms: int = 8000) -> dict:
    """One row: `writers` write threads through consensus + `readers`
    read threads via `mode` ('thin' | 'consensus'), concurrently."""
    assert mode in ("thin", "consensus"), mode
    overrides = dict(_OVERRIDES)
    if preexec:
        overrides["pre_execution_enabled"] = True
    stop_at = [0.0]
    w_counts = [0] * writers
    w_lats: List[List[float]] = [[] for _ in range(writers)]
    r_counts = [0] * max(1, readers)
    r_lats: List[List[float]] = [[] for _ in range(max(1, readers))]
    verified = [0] * max(1, readers)
    stale = [0] * max(1, readers)
    refreshes = [0] * max(1, readers)
    errors: List[str] = []

    with InProcessCluster(f=f, num_clients=writers + 1,
                          handler_factory=_handler_factory,
                          cfg_overrides=overrides) as cluster:
        n = cluster.n
        eps = [("127.0.0.1", cluster.replicas[r].thin_replica.port)
               for r in range(n)]
        verifier = keys_cert_verifier(cluster.keys)
        kv0 = skvbc.SkvbcClient(cluster.client(0))

        # warmup: seed the read-mostly COLD set (batched — few slots)
        # and cross the first checkpoint window so the f+1-signed
        # anchor exists before the clock starts. The cold/hot split is
        # the serving-tier shape: most reads hit keys nobody is
        # actively overwriting; 1-in-HOT_READ_EVERY hits the churning
        # set and exercises the staleness-bound retry path.
        for base in range(0, COLD_KEYS, 8):
            rs = kv0.write_batch(
                [[(b"cold-%02d" % k, b"c%d" % k)]
                 for k in range(base, min(base + 8, COLD_KEYS))],
                timeout_ms=30000)
            assert all(r.success for r in rs), "cold seed failed"
        for i in range(_OVERRIDES["checkpoint_window_size"] + 2):
            assert kv0.write([(b"key-%02d" % (i % KEYS), b"w%d" % i)],
                             pre_process=preexec,
                             timeout_ms=30000).success, "warmup failed"
        probe = ThinReplicaClient(eps, f_val=f, cert_verifier=verifier)
        deadline = time.monotonic() + 20
        anchor = None
        while time.monotonic() < deadline and not anchor:
            anchor = probe.fetch_anchor()
            if not anchor:
                time.sleep(0.25)
        if not anchor:
            # PR 4's degraded-artifact convention: a row that could not
            # exercise the plane says WHY instead of posing as a number
            return {"bench": "reads", "read_mode": mode,
                    "degraded": True,
                    "probe_error": "checkpoint anchor never formed"}

        def writer(idx: int) -> None:
            kv = skvbc.SkvbcClient(cluster.client(idx))
            i = 0
            while time.monotonic() < stop_at[0]:
                t0 = time.monotonic()
                try:
                    r = kv.write([(b"key-%02d" % (i % KEYS),
                                   b"v-%d-%d" % (idx, i))],
                                 pre_process=preexec,
                                 timeout_ms=op_timeout_ms)
                except Exception:  # noqa: BLE001 — timeout under load
                    i += 1
                    continue
                if r.success:
                    w_counts[idx] += 1
                    w_lats[idx].append(time.monotonic() - t0)
                i += 1

        def thin_reader(idx: int) -> None:
            trc = ThinReplicaClient(eps[idx % n:] + eps[:idx % n],
                                    f_val=f, cert_verifier=verifier)
            try:
                trc.fetch_anchor()
            except ValueError as e:
                errors.append(f"anchor: {e}")
                return
            i = 0
            while time.monotonic() < stop_at[0]:
                key = (b"key-%02d" % (i % KEYS)
                       if i % HOT_READ_EVERY == 0
                       else b"cold-%02d" % (i % COLD_KEYS))
                t0 = time.monotonic()
                try:
                    if i % ANCHOR_REFRESH_EVERY == 0:
                        trc.fetch_anchor()
                        refreshes[idx] += 1
                    trc.verified_read("kv", key)
                    verified[idx] += 1
                    r_counts[idx] += 1
                    r_lats[idx].append(time.monotonic() - t0)
                except LookupError:
                    # key overwritten since the anchored block: roll the
                    # anchor forward and retry on the next loop — the
                    # read tier's staleness bound at work
                    stale[idx] += 1
                    try:
                        trc.fetch_anchor()
                        refreshes[idx] += 1
                    except ValueError as e:
                        errors.append(f"refresh: {e}")
                        return
                except ValueError as e:
                    errors.append(f"verify: {e}")
                    return
                except OSError:
                    pass             # server churn; retry next loop
                i += 1

        def consensus_reader(idx: int) -> None:
            kv = skvbc.SkvbcClient(cluster.client(writers))
            i = 0
            while time.monotonic() < stop_at[0]:
                key = (b"key-%02d" % (i % KEYS)
                       if i % HOT_READ_EVERY == 0
                       else b"cold-%02d" % (i % COLD_KEYS))
                t0 = time.monotonic()
                try:
                    kv.read([key], timeout_ms=op_timeout_ms)
                except Exception:  # noqa: BLE001 — timeout under load
                    i += 1
                    continue
                r_counts[idx] += 1
                r_lats[idx].append(time.monotonic() - t0)
                i += 1

        # clients pre-created on THIS thread: cluster.client() mutates
        # shared dicts and must not race the worker threads
        for i in range(writers + 1):
            cluster.client(i).start()
        reader = thin_reader if mode == "thin" else consensus_reader
        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(writers)]
        threads += [threading.Thread(target=reader, args=(i,))
                    for i in range(readers)]
        stop_at[0] = time.monotonic() + secs
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        trs_proofs = sum(
            cluster.aggregators[r].get("thinreplica", "counters",
                                       "trs_proofs") or 0
            for r in range(n))
        trs_runs = sum(
            cluster.aggregators[r].get("thinreplica", "counters",
                                       "trs_pushed_runs") or 0
            for r in range(n))

    w_all = sorted(x for ls in w_lats for x in ls)
    r_all = sorted(x for ls in r_lats for x in ls)
    row = {
        "bench": "reads", "read_mode": mode, "n": 3 * f + 1, "f": f,
        "writers": writers, "readers": readers,
        "preexec": preexec, "secs": round(wall, 2),
        "write_ops": sum(w_counts),
        "write_ops_per_sec": round(sum(w_counts) / wall, 1),
        "read_ops": sum(r_counts),
        "read_ops_per_sec": round(sum(r_counts) / wall, 1),
        "write_p50_ms": _pct(w_all, 0.5), "write_p90_ms": _pct(w_all, 0.9),
        "read_p50_ms": _pct(r_all, 0.5), "read_p90_ms": _pct(r_all, 0.9),
        "read_mean_ms": round(statistics.mean(r_all) * 1e3, 2)
        if r_all else None,
    }
    if mode == "thin":
        row.update({
            "reads_verified": sum(verified),
            "stale_retries": sum(stale),
            "anchor_refreshes": sum(refreshes),
            "trs_proofs_served": trs_proofs,
            "trs_pushed_runs": trs_runs,
        })
        if errors:
            row["degraded"] = True
            row["probe_error"] = "; ".join(errors[:3])
    return row


# ----------------------------------------------------------------------
# corrupted-server drill: a forged value must be DETECTED, not served
# ----------------------------------------------------------------------

def corrupt_server_drill() -> dict:
    """Standalone (no cluster): an honest and a corrupting thin-replica
    server over identical chains, a hand-signed f+1 cert anchor. The
    corrupting server bit-flips every served value; the client's hash
    binding must reject it while the honest server's reads verify."""
    from tpubft.consensus import messages as cm
    from tpubft.crypto.cpu import Ed25519Signer, Ed25519Verifier
    from tpubft.kvbc import BLOCK_MERKLE, BlockUpdates
    from tpubft.thinreplica import messages as tm
    from tpubft.thinreplica.server import ThinReplicaServer

    def chain():
        bc = KeyValueBlockchain(MemoryDB(), use_device_hashing=False)
        for i in range(4):
            bc.add_block(BlockUpdates().put(
                "kv", b"k%d" % i, b"v%d" % i, cat_type=BLOCK_MERKLE))
        return bc

    honest_bc, corrupt_bc = chain(), chain()
    signers = {i: Ed25519Signer.generate(seed=bytes([i]) * 32)
               for i in (0, 1)}
    head = honest_bc.last_block_id
    digest = honest_bc.block_digest(head)
    certs = []
    for i, s in signers.items():
        ck = cm.CheckpointMsg(sender_id=i, seq_num=16,
                              state_digest=digest, is_stable=False,
                              res_pages_digest=b"", signature=b"")
        ck.signature = s.sign(ck.signed_payload())
        certs.append(ck.pack())
    anchor = (16, head, tuple(certs))

    class _CorruptingServer(ThinReplicaServer):
        def _serve_proof(self, conn, req):
            class _Tap:
                def __init__(self, inner):
                    self.inner = inner

                def sendall(self, data):
                    msg = tm.unpack_body(data[4:])
                    if isinstance(msg, tm.ProofReply) and msg.value:
                        msg.value = bytes([msg.value[0] ^ 1]) \
                            + msg.value[1:]
                    self.inner.sendall(tm.pack(msg))
            super()._serve_proof(_Tap(conn), req)

    honest = ThinReplicaServer(honest_bc, anchor_fn=lambda: anchor)
    corrupt = _CorruptingServer(corrupt_bc, anchor_fn=lambda: anchor)
    honest.start()
    corrupt.start()
    verifiers = {i: Ed25519Verifier(s.public_bytes())
                 for i, s in signers.items()}
    try:
        def cert_verifier(rid, payload, sig):
            v = verifiers.get(rid)
            return v is not None and v.verify(payload, sig)

        ok = ThinReplicaClient(
            [("127.0.0.1", honest.port), ("127.0.0.1", corrupt.port)],
            f_val=1, cert_verifier=cert_verifier)
        assert ok.fetch_anchor() == head
        assert ok.verified_read("kv", b"k0") == b"v0"
        bad = ThinReplicaClient(
            [("127.0.0.1", corrupt.port), ("127.0.0.1", honest.port)],
            f_val=1, cert_verifier=cert_verifier)
        assert bad.fetch_anchor() == head
        detected = False
        try:
            bad.verified_read("kv", b"k0")
        except ValueError:
            detected = True
        return {"corrupt_server_detected": detected,
                "honest_read_ok": True}
    finally:
        honest.stop()
        corrupt.stop()


def smoke(secs: float = 2.0) -> dict:
    """Tier-1 shape: a thin row and a consensus control row (1 writer +
    1 reader each), writes through the PRE-EXECUTION plane on the thin
    row (the serving plane's both halves under THREADCHECK), plus the
    corrupted-server drill. Every thin read must have verified."""
    from tpubft.utils.racecheck import get_watchdog
    out = {}
    for mode, preexec in (("thin", True), ("consensus", False)):
        row = run_mixed(mode, secs, writers=1, readers=1,
                        preexec=preexec)
        entry = {"ok": not row.get("degraded")
                 and row.get("read_ops", 0) > 0
                 and row.get("write_ops", 0) > 0,
                 "read_ops": row.get("read_ops", 0),
                 "write_ops": row.get("write_ops", 0)}
        if row.get("degraded"):
            entry["probe_error"] = row.get("probe_error", "")
        if mode == "thin":
            entry["all_verified"] = (row.get("reads_verified", -1)
                                     == row.get("read_ops", 0))
        out[mode] = entry
    out.update(corrupt_server_drill())
    out["stall_reports"] = get_watchdog().stall_reports
    return out


def main(argv=None) -> None:
    from benchmarks.common import setup_cache
    setup_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--secs", type=float, default=10.0)
    ap.add_argument("--writers", type=int, default=2)
    ap.add_argument("--readers", type=int, default=4)
    ap.add_argument("--modes", default="thin,consensus")
    ap.add_argument("--preexec", action="store_true",
                    help="route the writes through the pre-execution "
                         "plane (PRE_PROCESS flag)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny fixed shape for CI")
    args = ap.parse_args(argv)
    if args.smoke:
        print(json.dumps(smoke()), flush=True)
        return
    for mode in args.modes.split(","):
        row = run_mixed(mode, args.secs, args.writers, args.readers,
                        preexec=args.preexec)
        print(json.dumps(row), flush=True)
    print(json.dumps(corrupt_server_drill()), flush=True)


if __name__ == "__main__":
    main()
