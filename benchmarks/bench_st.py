"""State-transfer catch-up benchmark: stop-and-wait vs pipelined.

Measures destination catch-up blocks/sec over the in-process transport
with INJECTED PER-MESSAGE LATENCY — the regime that motivated the
pipelined fetch loop: with one range in flight (window=1, the old
behavior) catch-up is bounded by a single source's RTT; with a sliding
window of ranges striped across several sources the RTTs overlap and
throughput approaches aggregate-link speed (the aggregated-gossip
insight of arXiv 1911.04698 applied to block dissemination).

Topology: `--sources` source replicas share one pre-built chain; one
empty destination transfers the whole thing. Every message (request,
chunk, reject) is delayed `--latency-ms` by a scheduler thread; all
protocol handling is serialized under one dispatch lock, emulating each
node's single consensus dispatcher (and keeping the comparison honest on
a 1-core host: the pipeline may only overlap LATENCY, not compute).

Rows land in benchmarks/RESULTS.md. `--smoke` runs a small shape for the
tier-1 wiring test (tests/test_bench_st_smoke.py).

Usage:
  python -m benchmarks.bench_st [--blocks 256] [--range 16] [--window 4]
      [--sources 4] [--latency-ms 10] [--device] [--smoke] [--json]
"""
from __future__ import annotations

import argparse
import heapq
import json
import threading
import time
from typing import Dict, Optional

from tpubft.kvbc import BlockUpdates, KeyValueBlockchain
from tpubft.statetransfer import StateTransferManager
from tpubft.statetransfer.manager import StConfig
from tpubft.storage import MemoryDB


class LatencyNet:
    """In-process message router with a fixed per-message delivery delay.
    One scheduler thread pops messages in deliver-time order; every
    handle_message runs under a single dispatch lock."""

    def __init__(self, latency_s: float) -> None:
        self.latency = latency_s
        self.nodes: Dict[int, StateTransferManager] = {}
        self._q: list = []
        self._cv = threading.Condition()
        self._seq = 0
        self._stop = False
        self.dispatch_lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="latency-net")

    def add(self, node_id: int, mgr) -> None:
        self.nodes[node_id] = mgr

    def sender(self, from_id: int):
        def send(dest: int, payload: bytes) -> None:
            with self._cv:
                self._seq += 1
                heapq.heappush(self._q, (time.monotonic() + self.latency,
                                         self._seq, from_id, dest, payload))
                self._cv.notify()
        return send

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=5)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._stop and (
                        not self._q
                        or self._q[0][0] > time.monotonic()):
                    timeout = None
                    if self._q:
                        timeout = max(self._q[0][0] - time.monotonic(), 0)
                    self._cv.wait(timeout=timeout if timeout != 0 else 1e-4)
                if self._stop:
                    return
                _, _, sender, dest, payload = heapq.heappop(self._q)
            mgr = self.nodes.get(dest)
            if mgr is not None:
                with self.dispatch_lock:
                    mgr.handle_message(sender, payload)


def _build_chain(n_blocks: int, value_bytes: int) -> KeyValueBlockchain:
    bc = KeyValueBlockchain(MemoryDB(), use_device_hashing=False)
    payload = b"v" * value_bytes
    for i in range(n_blocks):
        bc.add_block(BlockUpdates()
                     .put("ver", f"k{i}".encode(), payload)
                     .put("ver", b"seq", str(i).encode()))
    return bc


def run(n_blocks: int, range_blocks: int, window: int, n_sources: int,
        latency_s: float, device: bool = False,
        value_bytes: int = 256, timeout_s: float = 120.0) -> dict:
    """One catch-up transfer; returns blocks/sec + manager counters."""
    chain = _build_chain(n_blocks, value_bytes)
    net = LatencyNet(latency_s)
    dest_id = n_sources
    for r in range(n_sources):
        src = StateTransferManager(r, chain)
        net.add(r, src)
        src.bind(net.sender(r), lambda s, d: None,
                 replica_ids=list(range(n_sources)) + [dest_id], f_val=1)
        src.on_checkpoint_stable(10, chain.state_digest())
    dest_bc = KeyValueBlockchain(MemoryDB(), use_device_hashing=False)
    dest = StateTransferManager(
        dest_id, dest_bc,
        StConfig(fetch_batch_blocks=range_blocks, window_ranges=window,
                 retry_timeout_s=5.0,
                 device_digest_threshold=(range_blocks if device
                                          else 10 ** 9),
                 use_device_digests=device))
    net.add(dest_id, dest)
    done = threading.Event()
    dest.bind(net.sender(dest_id), lambda s, d: done.set(),
              replica_ids=list(range(n_sources)), f_val=n_sources - 1)

    if device:
        # warm the XLA sha256 program so compile time doesn't pollute the
        # measured transfer
        from tpubft.ops.sha256 import sha256_batch_mixed
        sha256_batch_mixed([b"x" * value_bytes] * range_blocks)

    net.start()
    t0 = time.monotonic()
    with net.dispatch_lock:
        dest.start_collecting(10, {10: (chain.state_digest(), b"")})
    while not done.is_set() and time.monotonic() - t0 < timeout_s:
        done.wait(0.02)
        with net.dispatch_lock:
            dest.tick()
    elapsed = time.monotonic() - t0
    net.stop()
    ok = done.is_set() and dest_bc.last_block_id == n_blocks
    snap = dest.metrics.snapshot()["counters"]
    return {
        "ok": ok,
        "blocks": n_blocks,
        "range_blocks": range_blocks,
        "window": window,
        "sources": n_sources,
        "latency_ms": latency_s * 1000,
        "elapsed_s": round(elapsed, 4),
        "blocks_per_sec": round(n_blocks / elapsed, 1) if elapsed else 0.0,
        "device": device,
        "device_digest_batches": snap["device_digest_batches"],
        "scalar_digests": snap["scalar_digests"],
        "source_failovers": snap["source_failovers"],
    }


def compare(n_blocks: int, range_blocks: int, window: int, n_sources: int,
            latency_s: float, device: bool = False) -> dict:
    base = run(n_blocks, range_blocks, 1, n_sources, latency_s,
               device=device)
    piped = run(n_blocks, range_blocks, window, n_sources, latency_s,
                device=device)
    speedup = (piped["blocks_per_sec"] / base["blocks_per_sec"]
               if base["blocks_per_sec"] else 0.0)
    return {"baseline": base, "pipelined": piped,
            "speedup": round(speedup, 2)}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--blocks", type=int, default=256)
    ap.add_argument("--range", type=int, default=16, dest="range_blocks")
    ap.add_argument("--window", type=int, default=4)
    ap.add_argument("--sources", type=int, default=4)
    ap.add_argument("--latency-ms", type=float, default=20.0)
    ap.add_argument("--device", action="store_true",
                    help="route window digests through the batched "
                         "device SHA-256 kernel (counter-visible)")
    ap.add_argument("--smoke", action="store_true",
                    help="small fast shape for the tier-1 wiring test")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.blocks, args.range_blocks = 64, 8
        args.latency_ms = 5.0
    out = compare(args.blocks, args.range_blocks, args.window,
                  args.sources, args.latency_ms / 1000.0,
                  device=args.device)
    if args.json:
        print(json.dumps(out))
    else:
        for name in ("baseline", "pipelined"):
            r = out[name]
            print(f"{name:9s} window={r['window']} sources={r['sources']} "
                  f"latency={r['latency_ms']:.0f}ms "
                  f"blocks={r['blocks']} range={r['range_blocks']} -> "
                  f"{r['blocks_per_sec']:.1f} blocks/sec "
                  f"({r['elapsed_s']:.3f}s, ok={r['ok']}, "
                  f"device_batches={r['device_digest_batches']})")
        print(f"speedup: {out['speedup']}x")
    ok = out["baseline"]["ok"] and out["pipelined"]["ok"]
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
