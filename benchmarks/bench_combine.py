"""Fused cross-slot combine plane + certificate-scheme crossover bench.

Two questions from ISSUE 11 / ROADMAP item 3 ("kill the
threshold-combine tax"):

  1. `--sweep` — combines/sec of the FUSED plane
     (`IThresholdVerifier.combine_batch`: one segmented MSM + one RLC
     pairing check per flush for BLS, one batched ed25519 verify for the
     multisig vector) vs the per-slot reference loop, across in-flight
     slot counts. This is the microbench of what
     consensus/collectors.CombineBatcher drains per flush.
  2. `--crossover` — per-combine cost of the Ed25519 multisig vector vs
     BLS threshold at committee sizes n ∈ {4, 7, 16, 32}: the measured
     basis for `crypto/systems.ADAPTIVE_SCHEME_CROSSOVER_N` (the
     "adaptive" certificate scheme's configure-time pick; EdDSA-vs-BLS
     committee framing: arXiv 2302.00418).

Every row re-checks that fused and per-slot verdicts (combined bytes,
ok flags, bad-share ids) are identical (`verdicts_match`) — a speed row
from a wrong combine would be worse than no row. Rows produced through
the device backend on a CPU/XLA host carry the `degraded` +
`probe_error` convention (PR 4): they validate plumbing, not speed.

Usage: python -m benchmarks.bench_combine [--sweep] [--crossover]
           [--backend cpu|tpu] [--slots 1,2,4,8,16] [--secs 0.5]
           [--smoke]
Prints one JSON line per row; paste into benchmarks/RESULTS.md.
"""
from __future__ import annotations

import argparse
import json
import time
from typing import List

from benchmarks.common import setup_cache
from tpubft.crypto.interfaces import Cryptosystem, IThresholdVerifier

# slow-path quorum 2f+c+1 for c=0, f=(n-1)//3 — the preset --cases
# (4, 7, 16, 32) bracket the adaptive crossover's default boundary and
# the aggregation-gossip target size, but any n calibrates
def quorum_k(n: int) -> int:
    if n < 4:
        raise SystemExit(f"--cases: n={n} below the minimum BFT "
                         f"committee (n >= 3f+1 with f >= 1)")
    return 2 * ((n - 1) // 3) + 1


def _verifier(scheme: str, k: int, n: int, backend: str, system=None):
    system = system or Cryptosystem(scheme, k, n,
                                    seed=b"bench-combine-%d" % n)
    if backend == "tpu":
        from tpubft.crypto.tpu import make_threshold_verifier
        return system, make_threshold_verifier(
            scheme, k, n, system.public_key, system.share_public_keys)
    return system, system.create_threshold_verifier()


def _jobs(system, k: int, slots: int):
    signers = {i: system.create_threshold_signer(i)
               for i in range(1, k + 1)}
    out = []
    for s in range(slots):
        d = s.to_bytes(4, "big") * 8
        out.append((d, {i: signers[i].sign_share(d)
                        for i in range(1, k + 1)}))
    return out


def _rate(fn, secs: float) -> float:
    """Calls/sec of fn over a ~secs window (>=2 calls)."""
    fn()                                   # warmup / compile
    t0 = time.perf_counter()
    n = 0
    while True:
        fn()
        n += 1
        dt = time.perf_counter() - t0
        if dt >= secs and n >= 2:
            return n / dt


def _annotate_device(row: dict, backend: str) -> dict:
    if backend != "tpu":
        return row
    import jax
    row["platform"] = jax.default_backend()
    if row["platform"] == "cpu":
        row["degraded"] = True
        row["probe_error"] = ("device path executed on the XLA CPU "
                              "backend: validates the fused kernel "
                              "plumbing, not device speed")
    return row


def sweep_row(scheme: str, n: int, k: int, slots: int, backend: str,
              secs: float) -> dict:
    system, v = _verifier(scheme, k, n, backend)
    jobs = _jobs(system, k, slots)
    fused = v.combine_batch(jobs)
    perslot = IThresholdVerifier.combine_batch(v, jobs)
    fused_rate = _rate(lambda: v.combine_batch(jobs), secs)
    loop_rate = _rate(
        lambda: IThresholdVerifier.combine_batch(v, jobs), secs)
    row = {
        "bench": "combine_sweep", "scheme": scheme, "backend": backend,
        "n": n, "k": k, "in_flight_slots": slots,
        "fused_combines_per_sec": round(fused_rate * slots, 1),
        "per_slot_combines_per_sec": round(loop_rate * slots, 1),
        "fused_speedup": round(fused_rate / loop_rate, 2),
        "verdicts_match": fused == perslot,
    }
    return _annotate_device(row, backend)


def autotune_row(scheme: str, n: int, k: int, slots: int, backend: str,
                 secs: float) -> dict:
    """--sweep --autotune leg (ISSUE 14 satellite): the combine flush
    knobs now feed through the knob registry, so this leg drives a LIVE
    CombineBatcher end-to-end through that seam — a pipelined producer
    replays `slots` collectors per round while a measured-rate hill
    climb votes the `combine_batch_max` knob through the registry's
    hysteresis/step machinery (the in-replica controller votes from
    kernel/stage telemetry instead; the actuator path is identical).
    Reports the static-default rate vs the converged operating point,
    with verdict correctness asserted on every flush."""
    import threading
    from tpubft.consensus.collectors import CombineBatcher, ShareCollector
    from tpubft.tuning.knobs import GROW, SHRINK, Knob, KnobRegistry
    system, v = _verifier(scheme, k, n, backend)
    jobs = _jobs(system, k, slots)
    reference = IThresholdVerifier.combine_batch(v, jobs)
    collectors = [ShareCollector(0, i, "commit", d, v)
                  for i, (d, _s) in enumerate(jobs)]
    done = threading.Semaphore(0)
    bad = []

    def post(res):
        ok, combined, shares = reference[res.seq_num]
        if bool(res.ok) != bool(ok) or res.combined_sig != combined:
            bad.append(res.seq_num)
        done.release()

    batcher = CombineBatcher(post, flush_us=300, max_batch=64)
    registry = KnobRegistry("bench-combine")
    registry.register(Knob(
        name="combine_batch_max", value=64, default=64, lo=1, hi=512,
        cooldown_s=0.0, hysteresis=1,
        apply_fn=lambda val: batcher.reconfigure(max_batch=val)))
    registry.register(Knob(
        name="combine_flush_us", value=300, default=300, lo=0, hi=5000,
        cooldown_s=0.0, hysteresis=1,
        apply_fn=lambda val: batcher.reconfigure(flush_us=val)))

    def pump(window_s: float) -> float:
        t0 = time.perf_counter()
        rounds = 0
        while True:
            for c, (_d, shares) in zip(collectors, jobs):
                batcher.submit(c, shares)
            for _ in jobs:
                done.acquire()
            rounds += 1
            dt = time.perf_counter() - t0
            if dt >= window_s and rounds >= 2:
                return rounds * slots / dt

    try:
        pump(0.05)                              # warmup / compile
        default_rate = pump(secs / 2)
        best_rate, stale = default_rate, 0
        for _ in range(10):                     # bounded hill climb
            if stale >= 2:
                break
            direction = GROW if stale == 0 else SHRINK
            if registry.vote("combine_batch_max", direction):
                registry.step("combine_batch_max", direction)
            rate = pump(secs / 6)
            if rate > best_rate * 1.02:
                best_rate, stale = rate, 0
            else:
                stale += 1
        tuned_rate = max(best_rate, default_rate)
    finally:
        batcher.stop()
    row = {
        "bench": "combine_autotune", "scheme": scheme,
        "backend": backend, "n": n, "k": k, "in_flight_slots": slots,
        "default_combines_per_sec": round(default_rate, 1),
        "tuned_combines_per_sec": round(tuned_rate, 1),
        "tuned_over_default": round(tuned_rate / default_rate, 2),
        "converged_batch_max": registry.get("combine_batch_max"),
        "converged_flush_us": registry.get("combine_flush_us"),
        "verdicts_match": not bad,
    }
    return _annotate_device(row, backend)


def crossover_row(n: int, k: int, slots: int, backend: str,
                  secs: float) -> dict:
    """Per-combine µs of both certificate schemes at committee size n:
    the adaptive scheme should pick the cheaper column's scheme."""
    row = {"bench": "scheme_crossover", "backend": backend, "n": n,
           "k": k, "in_flight_slots": slots}
    rates = {}
    for scheme in ("multisig-ed25519", "threshold-bls"):
        system, v = _verifier(scheme, k, n, backend)
        jobs = _jobs(system, k, slots)
        assert v.combine_batch(jobs) \
            == IThresholdVerifier.combine_batch(v, jobs), \
            f"{scheme} fused/per-slot verdict divergence"
        r = _rate(lambda: v.combine_batch(jobs), secs)
        rates[scheme] = r * slots
        key = ("multisig_us_per_combine" if scheme == "multisig-ed25519"
               else "bls_us_per_combine")
        row[key] = round(1e6 / (r * slots), 1)
    row["winner"] = max(rates, key=rates.get)
    row["multisig_over_bls"] = round(
        rates["multisig-ed25519"] / rates["threshold-bls"], 1)
    # wire/proof size is the BLS column's compensation: the vector
    # certificate grows with k, the threshold certificate never does
    row["multisig_cert_bytes"] = 2 + 66 * k
    row["bls_cert_bytes"] = 48
    return _annotate_device(row, backend)


def main(argv: List[str] = None) -> int:
    setup_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--sweep", action="store_true")
    ap.add_argument("--crossover", action="store_true")
    ap.add_argument("--backend", default="cpu", choices=("cpu", "tpu"))
    ap.add_argument("--slots", default="1,2,4,8,16")
    ap.add_argument("--cases", default="4,7,16,32",
                    help="committee sizes for --crossover")
    ap.add_argument("--secs", type=float, default=0.5,
                    help="measurement window per point")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 shape: tiny sizes, correctness gates")
    ap.add_argument("--autotune", action="store_true",
                    help="with --sweep: add the knob-registry leg — a "
                         "live CombineBatcher hill-climbed through the "
                         "registry seam vs the static default")
    args = ap.parse_args(argv)
    if args.smoke:
        rows = [sweep_row("threshold-bls", 4, 3, 4, "cpu", 0.1),
                sweep_row("multisig-ed25519", 4, 3, 4, "cpu", 0.1),
                crossover_row(4, 3, 4, "cpu", 0.1)]
        for row in rows:
            print(json.dumps(row), flush=True)
        return 0 if all(r.get("verdicts_match", True) for r in rows) else 1
    if not args.sweep and not args.crossover:
        args.sweep = args.crossover = True
    rc = 0
    if args.sweep:
        for scheme in ("threshold-bls", "multisig-ed25519"):
            for slots in [int(x) for x in args.slots.split(",")]:
                row = sweep_row(scheme, 4, 3, slots, args.backend,
                                args.secs)
                rc |= 0 if row["verdicts_match"] else 1
                print(json.dumps(row), flush=True)
        if args.autotune:
            for scheme in ("threshold-bls", "multisig-ed25519"):
                slots = max(int(x) for x in args.slots.split(","))
                row = autotune_row(scheme, 4, 3, slots, args.backend,
                                   args.secs)
                rc |= 0 if row["verdicts_match"] else 1
                print(json.dumps(row), flush=True)
    if args.crossover:
        for n in [int(x) for x in args.cases.split(",")]:
            print(json.dumps(crossover_row(n, quorum_k(n), 8,
                                           args.backend, args.secs)),
                  flush=True)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
