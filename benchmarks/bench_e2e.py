"""End-to-end simpleKVBC ordering throughput (BASELINE configs 1-2).

The consensus-level number the reference never published: ops/sec a
client sees against a live cluster (reference measurement path:
tests/simpleKVBC TesterClient + Apollo's bft.py; kvbc add-block
throughput harness kvbc/benchmark/kvbcbench/main.cpp).

Configs (BASELINE.md):
  1. n=4 (f=1), multisig-ed25519 commit certs   — config 1
  2. n=7 (f=2), threshold-bls commit certs      — config 2
Each runs with crypto_backend cpu and (if a device is reachable) tpu.

Usage: python -m benchmarks.bench_e2e [--secs 10] [--clients 4]
       [--configs 1,2] [--backends cpu,tpu]
Prints one JSON line per (config, backend).
"""
from __future__ import annotations

import argparse
import json
import statistics
import threading
import time
from typing import List

from tpubft.apps import skvbc
from tpubft.kvbc import KeyValueBlockchain
from tpubft.storage import MemoryDB
from tpubft.testing.cluster import InProcessCluster

CONFIGS = {
    1: dict(f=1, threshold_scheme="multisig-ed25519"),
    2: dict(f=2, threshold_scheme="threshold-bls"),
}


def _handler_factory(_r=None):
    return skvbc.SkvbcHandler(KeyValueBlockchain(MemoryDB()))


def run_config(config: int, backend: str, secs: float,
               clients: int) -> dict:
    cfg = CONFIGS[config]
    overrides = {"threshold_scheme": cfg["threshold_scheme"],
                 "crypto_backend": backend}
    cluster = InProcessCluster(f=cfg["f"], num_clients=clients,
                               handler_factory=_handler_factory,
                               cfg_overrides=overrides)
    counts = [0] * clients
    lats: List[List[float]] = [[] for _ in range(clients)]
    stop_at = [0.0]

    def worker(idx: int) -> None:
        kv = skvbc.SkvbcClient(cluster.client(idx))
        i = 0
        while time.monotonic() < stop_at[0]:
            t0 = time.monotonic()
            reply = kv.write([(b"bench-%d-%d" % (idx, i % 64),
                               b"v%d" % i)])
            dt = time.monotonic() - t0
            if reply.success:
                counts[idx] += 1
                lats[idx].append(dt)
            i += 1

    with cluster:
        # warmup: first write pays kernel compiles on the tpu backend
        kv0 = skvbc.SkvbcClient(cluster.client(0))
        assert kv0.write([(b"warmup", b"w")]).success, \
            "cluster failed to order the warmup write"
        stop_at[0] = time.monotonic() + secs
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
    total = sum(counts)
    all_lats = sorted(x for ls in lats for x in ls)
    return {
        "config": config, "n": 3 * cfg["f"] + 1, "f": cfg["f"],
        "threshold_scheme": cfg["threshold_scheme"], "backend": backend,
        "clients": clients, "secs": round(wall, 2), "ops": total,
        "ops_per_sec": round(total / wall, 1),
        "mean_latency_ms": round(statistics.mean(all_lats) * 1e3, 2)
        if all_lats else None,
        "p90_latency_ms": round(all_lats[int(len(all_lats) * 0.9)] * 1e3, 2)
        if all_lats else None,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--secs", type=float, default=10.0)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--configs", default="1,2")
    ap.add_argument("--backends", default="cpu")
    args = ap.parse_args()
    for config in [int(x) for x in args.configs.split(",")]:
        for backend in args.backends.split(","):
            row = run_config(config, backend, args.secs, args.clients)
            print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
