"""End-to-end simpleKVBC ordering throughput (BASELINE configs 1-2).

The consensus-level number the reference never published: ops/sec a
client sees against a live cluster (reference measurement path:
tests/simpleKVBC TesterClient + Apollo's bft.py; kvbc add-block
throughput harness kvbc/benchmark/kvbcbench/main.cpp).

Configs (BASELINE.md):
  1. n=4 (f=1), multisig-ed25519 commit certs   — config 1
  2. n=7 (f=2), threshold-bls commit certs      — config 2
  3. n=31 (f=10), secp256k1 client sigs + threshold-bls commit certs
     (the Apollo 31-replica cluster shape)       — config 3
  5. n=4 (f=1), ECDSA-P256 clients + threshold-bls over TLS, with a
     view-change storm (primary paused every storm-period) — config 5
Each runs with crypto_backend cpu and (if a device is reachable) tpu.
(Config 4 — the n=1000 synthetic PrePrepare/share flood — is the
separate benchmarks/bench_flood.py: it measures the crypto plane at a
scale no single-host cluster can reach.)

Usage: python -m benchmarks.bench_e2e [--secs 10] [--clients 4]
       [--configs 1,2] [--backends cpu,tpu] [--processes]
Prints one JSON line per (config, backend).
"""
from __future__ import annotations

import argparse
import json
import statistics
import threading
import time
from typing import List

from tpubft.apps import skvbc
from tpubft.kvbc import KeyValueBlockchain
from tpubft.storage import MemoryDB
from tpubft.testing.cluster import InProcessCluster

def fsync_probe_ms(dir_path: str = None, samples: int = 5) -> float:
    """Median cost of one 4KiB write+fsync on the disk under
    `dir_path` (default: the tempdir the replica DBs land in) —
    machine-readable context for every row: the shared-disk fsync is
    nonstationary (2-21ms observed across rounds) and dominates
    run-to-run variance on the write path, which is exactly what the
    durability pipeline's group commit amortizes."""
    import os
    import statistics as stats
    import tempfile
    d = dir_path or tempfile.gettempdir()
    times = []
    try:
        fd, path = tempfile.mkstemp(dir=d, prefix="fsync-probe-")
        try:
            payload = b"\x5a" * 4096
            for _ in range(samples):
                t0 = time.perf_counter()
                os.write(fd, payload)
                os.fsync(fd)
                times.append((time.perf_counter() - t0) * 1e3)
        finally:
            os.close(fd)
            os.unlink(path)
    except OSError:
        return -1.0                       # unprobeable filesystem
    return round(stats.median(times), 3)


def _dur_group_len(runs, groups) -> float:
    """runs-per-group amortization factor (None until a group landed)."""
    runs, groups = runs or 0, groups or 0
    return round(runs / groups, 2) if groups else None


CONFIGS = {
    1: dict(f=1, threshold_scheme="multisig-ed25519"),
    2: dict(f=2, threshold_scheme="threshold-bls"),
    3: dict(f=10, threshold_scheme="threshold-bls",
            client_sig_scheme="ecdsa-secp256k1",
            # a 31-replica co-located cluster pays ~n pairing checks per
            # round on one host: keep the VC timer out of the measurement,
            # stop the 300ms fast-path timer from firing on >600ms
            # co-location slots (spurious slow-path crypto), and don't
            # pipeline slots (overlap amplifies the n=31 contention —
            # depth 1 measured 1.8x depth 3 on a 1-core host)
            view_change_timer_ms=30000,
            fast_path_timeout_ms=5000,
            concurrency_level=1),
    5: dict(f=1, threshold_scheme="threshold-bls",
            client_sig_scheme="ecdsa-p256", transport="tls",
            storm_period_s=4.0),
}


def _handler_factory(_r=None):
    return skvbc.SkvbcHandler(KeyValueBlockchain(MemoryDB()))


def _drive(make_kv, config: int, backend: str, secs: float,
           clients: int, mode: str = None,
           warmup_timeout_ms: int = 20000,
           client_batch: int = 1, op_timeout_ms: int = 8000) -> dict:
    """Shared workload driver: `make_kv(idx)` returns a SkvbcClient
    bound to client `idx`; one stats pipeline serves both harness
    modes (so BASELINE numbers can never drift between them).
    client_batch>1 sends that many independent transactions per wire
    message (ClientBatchRequestMsg); each counts as one op."""
    cfg = CONFIGS[config]
    counts = [0] * clients
    lats: List[List[float]] = [[] for _ in range(clients)]
    stop_at = [0.0]

    def worker(idx: int) -> None:
        kv = make_kv(idx)
        i = 0
        while time.monotonic() < stop_at[0]:
            t0 = time.monotonic()
            try:
                if client_batch > 1:
                    ws = [[(b"bench-%d-%d" % (idx, (i + j) % 64),
                            b"v%d" % (i + j))]
                          for j in range(client_batch)]
                    rs = kv.write_batch(ws, timeout_ms=op_timeout_ms)
                    dt = time.monotonic() - t0
                    ok = sum(1 for r in rs if r.success)
                    if ok:
                        counts[idx] += ok
                        lats[idx].append(dt)
                    i += client_batch
                    continue
                r = kv.write([(b"bench-%d-%d" % (idx, i % 64),
                               b"v%d" % i)], timeout_ms=op_timeout_ms)
            except Exception:  # noqa: BLE001 — lossy transports time out
                i += client_batch if client_batch > 1 else 1
                continue
            dt = time.monotonic() - t0
            if r.success:
                counts[idx] += 1
                lats[idx].append(dt)
            i += 1

    # warmup: first write pays kernel compiles on the tpu backend
    assert make_kv(0).write([(b"warmup", b"w")],
                            timeout_ms=warmup_timeout_ms).success, \
        "cluster failed to order the warmup write"
    stop_at[0] = time.monotonic() + secs
    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    total = sum(counts)
    all_lats = sorted(x for ls in lats for x in ls)
    row = {
        "config": config, "n": 3 * cfg["f"] + 1, "f": cfg["f"],
        "threshold_scheme": cfg["threshold_scheme"],
        "client_sig_scheme": cfg.get("client_sig_scheme", "ed25519"),
        "transport": cfg.get("transport", "udp/loopback"),
        "backend": backend,
        "clients": clients, "secs": round(wall, 2), "ops": total,
        **({"client_batch": client_batch} if client_batch > 1 else {}),
        "ops_per_sec": round(total / wall, 1),
        "mean_latency_ms": round(statistics.mean(all_lats) * 1e3, 2)
        if all_lats else None,
        "p90_latency_ms": round(all_lats[int(len(all_lats) * 0.9)] * 1e3, 2)
        if all_lats else None,
    }
    if mode:
        row["mode"] = mode
    return row


def run_config(config: int, backend: str, secs: float,
               clients: int, client_batch: int = 1,
               extra_overrides: dict = None,
               op_timeout_ms: int = 8000,
               profile: bool = False) -> dict:
    cfg = CONFIGS[config]
    if cfg.get("transport") or cfg.get("storm_period_s"):
        # TLS transport and the VC storm only exist on real processes; an
        # in-process row must not claim a fidelity it didn't run with
        raise SystemExit(
            f"config {config} requires --processes (tls/storm fidelity)")
    # every ReplicaConfig field in the CONFIGS entry flows through (f and
    # the process-only keys are harness-level); cherry-picking fields
    # here silently dropped new tunings
    overrides = {k: v for k, v in cfg.items()
                 if k not in ("f", "transport", "storm_period_s")}
    overrides.setdefault("client_sig_scheme", "ed25519")
    overrides["crypto_backend"] = backend
    overrides.update(extra_overrides or {})
    if profile:
        # fresh recorder so the stage breakdown covers exactly this run
        from tpubft.utils import flight
        flight.reset()
    with InProcessCluster(f=cfg["f"], num_clients=clients,
                          handler_factory=_handler_factory,
                          cfg_overrides=overrides) as cluster:
        row = _drive(lambda i: skvbc.SkvbcClient(cluster.client(i)),
                     config, backend, secs, clients,
                     warmup_timeout_ms=60000 if cfg["f"] > 2 else 20000,
                     client_batch=client_batch,
                     op_timeout_ms=op_timeout_ms)
        row["fsync_probe_ms"] = fsync_probe_ms()

        def _dur(i: int, name: str) -> int:
            try:   # pipeline-off legs have no durability component
                return cluster.metric(i, "counters", name,
                                      component="durability") or 0
            except KeyError:
                return 0

        n = 3 * cfg["f"] + 1
        row["dur_group_len"] = _dur_group_len(
            sum(_dur(i, "dur_runs") for i in range(n)),
            sum(_dur(i, "dur_groups") for i in range(n)))
        if overrides.get("optimistic_replies"):
            # the optimistic plane's own evidence: slots released to
            # the reply path before the pairing verify landed, and any
            # deferred-cert failures (must be 0 on an honest cluster)
            row["opt_releases"] = sum(
                cluster.metric(i, "counters", "optimistic_releases")
                for i in range(n))
            row["cert_async_failures"] = sum(
                cluster.metric(i, "counters", "cert_async_failures")
                for i in range(n))
        if extra_overrides:
            row["overrides"] = dict(extra_overrides)
        if profile:
            # per-slot stage breakdown (adm_wait/dispatch/prepare/
            # commit/exec/reply) + kernel profile, folded by the flight
            # recorder across every replica of the in-process cluster
            from tpubft.utils import flight
            row["stage_breakdown"] = flight.stage_summary()
            row["kernel_profile"] = flight.kernel_profiler().snapshot()
            # autotuner state (knob values + decision log per replica)
            # while the controllers are still registered — bench_autotune
            # joins this to the A/B goodput rows
            tuning = {name: state for name, state
                      in flight._provider_payloads().items()
                      if name.startswith("tuning")}
            if tuning:
                row["tuning_state"] = tuning
        return row


def _storm(net, stop_evt, period_s: float) -> None:
    """View-change storm driver (config 5): pause the CURRENT primary for
    a view-change-timeout's worth of silence, resume it, repeat — every
    cycle forces a real view change while clients keep submitting. The
    primary is read from live metrics (a spontaneous, load-induced view
    change must not desynchronize the storm into pausing backups)."""
    while not stop_evt.wait(period_s):
        views = [net.current_view(r) for r in range(net.n)]
        view = max((v for v in views if v is not None), default=0)
        r = view % net.n                 # round-robin primary assignment
        net.pause_replica(r)
        # hold past the VC timeout so the complaint quorum forms
        interrupted = stop_evt.wait(net.view_change_timeout_ms / 1000.0
                                    + 1.0)
        net.resume_replica(r)
        if interrupted:
            return


def run_config_processes(config: int, backend: str, secs: float,
                         clients: int, client_batch: int = 1,
                         extra_overrides: dict = None,
                         op_timeout_ms: int = 8000) -> dict:
    """REAL replica OS processes (BftTestNetwork) — no shared-GIL
    inflation; this is the deployment-shaped number."""
    import tempfile
    import threading as _t

    from tpubft.testing.network import BftTestNetwork
    cfg = CONFIGS[config]
    # ReplicaConfig fields without a dedicated BftTestNetwork parameter
    # ride the generic --config-override plumbing — process rows must run
    # the same tunings as the in-process rows
    flagged = ("f", "transport", "storm_period_s", "threshold_scheme",
               "client_sig_scheme", "view_change_timer_ms")
    overrides = {k: v for k, v in cfg.items() if k not in flagged}
    overrides.update(extra_overrides or {})
    with tempfile.TemporaryDirectory() as tmp, \
            BftTestNetwork(f=cfg["f"], num_clients=max(4, clients),
                           db_dir=tmp, crypto_backend=backend,
                           threshold_scheme=cfg["threshold_scheme"],
                           client_sig_scheme=cfg.get("client_sig_scheme",
                                                     "ed25519"),
                           view_change_timeout_ms=cfg.get(
                               "view_change_timer_ms", 3000),
                           transport=cfg.get("transport", "udp"),
                           cfg_overrides=overrides) as net:
        storm_stop = None
        storm_thread = None
        if cfg.get("storm_period_s"):
            storm_stop = _t.Event()
            storm_thread = _t.Thread(target=_storm,
                                     args=(net, storm_stop,
                                           cfg["storm_period_s"]),
                                     daemon=True)
            storm_thread.start()
        try:
            row = _drive(net.skvbc_client, config, backend, secs, clients,
                         mode="processes",
                         warmup_timeout_ms=60000 if cfg["f"] > 2
                         else 20000, client_batch=client_batch,
                         op_timeout_ms=op_timeout_ms)
        finally:
            if storm_stop is not None:
                storm_stop.set()
                storm_thread.join(timeout=10)
        if cfg.get("storm_period_s"):
            row["storm_period_s"] = cfg["storm_period_s"]
        # probe the filesystem the replica DBs actually live on — the
        # process rows are the ones where the ledger rides a real disk
        row["fsync_probe_ms"] = fsync_probe_ms(tmp)
        runs = groups = 0
        for r in range(net.n):
            # ONE snapshot per replica: both counters must come from
            # the same instant or the ratio can straddle a group
            # boundary mid-commit
            snap = (net.metrics(r).snapshot() or {}).get("components", {})
            counters = (snap.get("durability") or {}).get("counters", {})
            runs += counters.get("dur_runs") or 0
            groups += counters.get("dur_groups") or 0
        row["dur_group_len"] = _dur_group_len(runs, groups)
        if extra_overrides:
            row["overrides"] = dict(extra_overrides)
        return row


def smoke(secs: float = 2.0, clients: int = 2) -> dict:
    """Tier-1 shape (mirrors bench_st --smoke): order real traffic
    through config 1 with the execution lane ON (speculative — the
    default), the lane on with speculation OFF, and the legacy inline
    path, so the ordering path — including the dispatcher↔executor
    handoff and the speculative seal protocol — has a collection-time +
    runtime guard in CI. Run it under TPUBFT_THREADCHECK=1 to arm the
    lock-order checker across the handoff
    (tests/test_bench_e2e_smoke.py does)."""
    from tpubft.utils.racecheck import get_watchdog
    out = {}
    for label, overrides in (
            ("lane", {"execution_lane": True}),
            ("nospec", {"execution_lane": True,
                        "speculative_execution": False}),
            ("nodur", {"execution_lane": True,
                       "durability_pipeline": False}),
            ("inline", {"execution_lane": False})):
        # the optimistic-replies leg lives in smoke_optimistic() (its
        # own tier-1 test) — not duplicated here
        row = run_config(1, "cpu", secs, clients,
                         extra_overrides=overrides)
        out[label] = {"ok": row["ops"] > 0,
                      "ops": row["ops"],
                      "ops_per_sec": row["ops_per_sec"]}
        if "opt_releases" in row:
            out[label]["opt_releases"] = row["opt_releases"]
    out["stall_reports"] = get_watchdog().stall_reports
    return out


def smoke_optimistic(secs: float = 2.0, clients: int = 2) -> dict:
    """Tier-1 A/B shape for the optimistic reply plane (ISSUE 18): the
    same config-1 workload with `optimistic_replies` on then off, one
    JSON row with the PR 4 `degraded`/`probe_error` convention — the
    row degrades (rather than fails) when the plane never actually
    released a slot, so CI flags a silently-inert plane without
    guessing at throughput on a noisy host."""
    from tpubft.utils.racecheck import get_watchdog
    on = run_config(1, "cpu", secs, clients,
                    extra_overrides={"execution_lane": True,
                                     "optimistic_replies": True})
    off = run_config(1, "cpu", secs, clients,
                     extra_overrides={"execution_lane": True,
                                      "optimistic_replies": False})
    row = {
        "bench": "e2e-optimistic-smoke", "unit": "ops",
        "value": on["ops"],
        "on_ops": on["ops"], "off_ops": off["ops"],
        "on_ops_per_sec": on["ops_per_sec"],
        "off_ops_per_sec": off["ops_per_sec"],
        "on_p90_latency_ms": on["p90_latency_ms"],
        "off_p90_latency_ms": off["p90_latency_ms"],
        "opt_releases": on.get("opt_releases", 0),
        "cert_async_failures": on.get("cert_async_failures", 0),
        "stall_reports": get_watchdog().stall_reports,
        "degraded": False, "probe_error": "",
    }
    problems = []
    if not on["ops"] or not off["ops"]:
        problems.append("a leg ordered zero traffic")
    if not row["opt_releases"]:
        problems.append("optimistic plane never released a slot")
    if row["cert_async_failures"]:
        problems.append("deferred cert verification failed on an "
                        "honest cluster")
    if problems:
        row["degraded"] = True
        row["probe_error"] = "; ".join(problems)
    return row


def main() -> None:
    from benchmarks.common import setup_cache
    setup_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--secs", type=float, default=10.0)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--client-batch", type=int, default=1,
                    help=">1: transactions per wire message "
                         "(ClientBatchRequestMsg)")
    ap.add_argument("--configs", default="1,2")
    ap.add_argument("--backends", default="cpu")
    ap.add_argument("--processes", action="store_true",
                    help="real replica OS processes instead of the "
                         "in-process cluster")
    ap.add_argument("--override", action="append", default=[],
                    metavar="FIELD=VALUE",
                    help="extra ReplicaConfig override applied to every "
                         "replica (repeatable) — e.g. execution_lane="
                         "False or execution_max_accumulation=1 for the "
                         "lane A/B rows")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny fixed shape for CI (lane on vs off)")
    ap.add_argument("--smoke-optimistic", action="store_true",
                    help="tiny fixed optimistic-replies A/B shape for "
                         "CI: one JSON row (degraded/probe_error "
                         "convention)")
    ap.add_argument("--optimistic-off", action="store_true",
                    help="A/B control leg: run with the optimistic "
                         "reply plane OFF (replies certificate-gated). "
                         "Without this flag the bench runs the plane ON "
                         "— pair alternating on/off invocations like "
                         "the durability rows")
    ap.add_argument("--durability-off", action="store_true",
                    help="A/B control leg: run with the group-commit "
                         "durability pipeline OFF (per-run apply + "
                         "immediate completion) — pair alternating "
                         "on/off invocations like the PR 9 rows")
    ap.add_argument("--profile", action="store_true",
                    help="attach the flight recorder's per-slot stage "
                         "breakdown (adm_wait/dispatch/prepare/commit/"
                         "exec/reply) and kernel profile to each row "
                         "(in-process configs only)")
    ap.add_argument("--timeout-ms", type=int, default=8000,
                    help="per-op client timeout; raise for saturated "
                         "deep-batch shapes so a slow config degrades "
                         "gracefully instead of timing out")
    args = ap.parse_args()
    if args.smoke:
        print(json.dumps(smoke()), flush=True)
        return
    if args.smoke_optimistic:
        print(json.dumps(smoke_optimistic()), flush=True)
        return
    from tpubft.utils.config import parse_config_overrides
    extra = parse_config_overrides(args.override)
    if args.durability_off:
        extra["durability_pipeline"] = False
    if args.optimistic_off:
        extra["optimistic_replies"] = False
    else:
        # the measured configuration IS the optimistic plane (ISSUE 18);
        # --optimistic-off is the paired control leg
        extra.setdefault("optimistic_replies", True)
    if args.profile and args.processes:
        raise SystemExit("--profile reads the in-process flight "
                         "recorder; with --processes take per-replica "
                         "dumps (status get flight) and merge them "
                         "with tools/tpuprof.py instead")
    for config in [int(x) for x in args.configs.split(",")]:
        for backend in args.backends.split(","):
            if args.processes:
                row = run_config_processes(
                    config, backend, args.secs, args.clients,
                    args.client_batch, extra_overrides=extra,
                    op_timeout_ms=args.timeout_ms)
            else:
                row = run_config(
                    config, backend, args.secs, args.clients,
                    args.client_batch, extra_overrides=extra,
                    op_timeout_ms=args.timeout_ms, profile=args.profile)
            print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
