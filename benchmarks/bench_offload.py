"""Verified crypto-offload tier bench (ISSUE 20).

Four questions about renting untrusted MSM helpers:

  1. `--ab` — combines/sec of the fused combine plane with the offload
     tier OFF vs ON (one honest in-process helper): what a leased
     combine costs end-to-end INCLUDING the constant-size soundness
     check the replica runs on every response. Every row re-checks that
     the two paths' verdicts (ok flags, combined bytes, bad-share ids)
     are byte-identical — the tier's core contract.
  2. `--soundness` — the check itself: µs per 2-pairing RLC combine
     check vs µs per local combine, across flush sizes. The claim being
     measured is CONSTANT-SIZE: the check cost must stay flat while the
     combine cost grows with shares.
  3. `--kill` — liveness drill: one of two helpers crashes mid-run; the
     lease retries onto the survivor / falls local inside the same
     flush, throughput continues, NOBODY is quarantined (a crash is
     sick, not Byzantine).
  4. `--lie` — eviction drill: a helper turns Byzantine mid-run
     (wrong-but-on-curve points — the hardest lie); the soundness check
     catches it on the FIRST lying lease, the helper is quarantined,
     verdicts never diverge from the local path.

In-process helpers (no socket hop) isolate the protocol + soundness
cost from transport noise; rows produced through the device backend on
a CPU/XLA host carry the `degraded` + `probe_error` convention (PR 4):
they validate the seam's plumbing and safety, not speed.

Usage: python -m benchmarks.bench_offload [--ab] [--soundness]
           [--kill] [--lie] [--backend cpu|tpu]
           [--slots 1,4,16] [--secs 0.5] [--smoke]
Prints one JSON line per row; paste into benchmarks/RESULTS.md.
"""
from __future__ import annotations

import argparse
import json
import time
from typing import List

from benchmarks.common import setup_cache
from tpubft.crypto.interfaces import Cryptosystem

# the bench IS the external harness the offload-seam baseline speaks
# of: it instantiates helper engines directly to drive fault drills
from tpubft.offload.helper import HelperServer
from tpubft.offload.pool import (InprocHelper, get_offload_pool,
                                 reset_offload_pool)


def _verifier(k: int, n: int, backend: str):
    system = Cryptosystem("threshold-bls", k, n,
                          seed=b"bench-offload-%d" % n)
    if backend == "tpu":
        from tpubft.crypto.tpu import make_threshold_verifier
        return system, make_threshold_verifier(
            "threshold-bls", k, n, system.public_key,
            system.share_public_keys)
    return system, system.create_threshold_verifier()


def _jobs(system, k: int, slots: int):
    signers = {i: system.create_threshold_signer(i)
               for i in range(1, k + 1)}
    out = []
    for s in range(slots):
        d = s.to_bytes(4, "big") * 8
        out.append((d, {i: signers[i].sign_share(d)
                        for i in range(1, k + 1)}))
    return out


def _rate(fn, secs: float) -> float:
    fn()                                   # warmup / compile
    t0 = time.perf_counter()
    n = 0
    while True:
        fn()
        n += 1
        dt = time.perf_counter() - t0
        if dt >= secs and n >= 2:
            return n / dt


def _annotate_device(row: dict, backend: str) -> dict:
    if backend != "tpu":
        return row
    import jax
    row["platform"] = jax.default_backend()
    if row["platform"] == "cpu":
        row["degraded"] = True
        row["probe_error"] = ("device path executed on the XLA CPU "
                              "backend: validates the offload seam "
                              "and soundness plumbing, not speed")
    return row


def _pool_with(*servers, timeout_ms=30000):
    reset_offload_pool()
    pool = get_offload_pool()
    pool.configure(enabled=True, lease_timeout_ms=timeout_ms,
                   max_inflight=8)
    for s in servers:
        pool.add_helper(InprocHelper(s.helper_id, s))
    return pool


def ab_row(n: int, k: int, slots: int, backend: str,
           secs: float) -> dict:
    """Offload-off vs offload-on (honest helper) combine_batch rate;
    verdicts byte-identical; per-lease soundness cost from the pool's
    own telemetry."""
    system, v = _verifier(k, n, backend)
    jobs = _jobs(system, k, slots)
    reset_offload_pool()                       # OFF leg
    local = v.combine_batch(jobs)
    local_rate = _rate(lambda: v.combine_batch(jobs), secs)
    pool = _pool_with(HelperServer("bench-honest"))    # ON leg
    leased = v.combine_batch(jobs)
    leased_rate = _rate(lambda: v.combine_batch(jobs), secs)
    snap = pool.snapshot()
    verified = max(1, snap["counters"]["lease_verified"])
    row = {
        "bench": "offload_ab", "scheme": "threshold-bls",
        "backend": backend, "n": n, "k": k, "in_flight_slots": slots,
        "local_combines_per_sec": round(local_rate * slots, 1),
        "leased_combines_per_sec": round(leased_rate * slots, 1),
        "leased_over_local": round(leased_rate / local_rate, 2),
        "soundness_us_per_lease": round(
            snap["soundness_us_total"] / verified, 1),
        "lease_us_per_item": round(
            snap["lease_us_total"] / max(1, snap["lease_items_total"]),
            1),
        "leases_verified": snap["counters"]["lease_verified"],
        "leases_rejected": snap["counters"]["lease_rejected"],
        "verdicts_match": leased == local,
    }
    reset_offload_pool()
    return _annotate_device(row, backend)


def soundness_row(n: int, k: int, slots: int, backend: str,
                  secs: float) -> dict:
    """µs per soundness check vs µs per local combine at this flush
    size — the constant-size claim in one row: check_over_combine
    should FALL as slots grow."""
    from tpubft.crypto import bls12381 as bls
    from tpubft.offload import soundness
    system, v = _verifier(k, n, backend)
    jobs = _jobs(system, k, slots)
    digests = [d for d, _s in jobs]
    pts = [bls.g1_decompress(
        bls.g1_compress(bls.combine_shares(
            sorted(shares),
            [bls.g1_decompress(shares[i]) for i in sorted(shares)])))
        for _d, shares in jobs]
    assert soundness.check_bls_combine(system.public_key, digests, pts)
    check_rate = _rate(
        lambda: soundness.check_bls_combine(system.public_key,
                                            digests, pts), secs)
    reset_offload_pool()
    combine_rate = _rate(lambda: v.combine_batch(jobs), secs)
    row = {
        "bench": "offload_soundness", "backend": backend,
        "n": n, "k": k, "in_flight_slots": slots,
        "check_us_per_flush": round(1e6 / check_rate, 1),
        "combine_us_per_flush": round(1e6 / combine_rate, 1),
        "check_over_combine": round(combine_rate / check_rate, 2),
    }
    return _annotate_device(row, backend)


def kill_row(n: int, k: int, slots: int, backend: str,
             secs: float) -> dict:
    """Helper-kill drill: flush continuously, crash one of two helpers
    mid-window. Liveness = throughput continues, verdicts never
    diverge; the dead helper is SICK (breaker cooldown), not
    quarantined."""
    system, v = _verifier(k, n, backend)
    jobs = _jobs(system, k, slots)
    reset_offload_pool()
    want = v.combine_batch(jobs)
    victim = HelperServer("bench-victim")
    survivor = HelperServer("bench-survivor")
    pool = _pool_with(victim, survivor, timeout_ms=2000)
    flushes = [0, 0]                # [before, after] the kill
    bad = 0
    t0 = time.perf_counter()
    killed = False
    while time.perf_counter() - t0 < secs or flushes[1] < 2:
        if not killed and time.perf_counter() - t0 >= secs / 2:
            victim.set_strategy("crash")
            killed = True
        if v.combine_batch(jobs) != want:
            bad += 1
        flushes[int(killed)] += 1
    dt = time.perf_counter() - t0
    snap = pool.snapshot()
    row = {
        "bench": "offload_helper_kill", "backend": backend,
        "n": n, "k": k, "in_flight_slots": slots,
        "combines_per_sec": round(sum(flushes) * slots / dt, 1),
        "flushes_before_kill": flushes[0],
        "flushes_after_kill": flushes[1],
        "lease_timeouts": snap["counters"]["lease_timeouts"],
        "local_fallbacks": snap["counters"]["local_fallbacks"],
        "quarantined": snap["quarantined"],   # must stay empty: sick
        "verdicts_match": bad == 0,
        "liveness_held": flushes[1] >= 2 and not snap["quarantined"],
    }
    reset_offload_pool()
    return _annotate_device(row, backend)


def lie_row(n: int, k: int, slots: int, backend: str,
            secs: float) -> dict:
    """Lying-helper drill: a helper flips to wrong-but-on-curve points
    mid-window. Safety = verdicts never diverge (the lie dies at the
    soundness check, one local re-run); the liar is quarantined on its
    FIRST lying lease and never re-admitted within the window."""
    system, v = _verifier(k, n, backend)
    jobs = _jobs(system, k, slots)
    reset_offload_pool()
    want = v.combine_batch(jobs)
    liar = HelperServer("bench-liar")
    honest = HelperServer("bench-honest")
    pool = _pool_with(liar, honest)
    flushes = [0, 0]
    bad = 0
    t0 = time.perf_counter()
    flipped = False
    while time.perf_counter() - t0 < secs or flushes[1] < 2:
        if not flipped and time.perf_counter() - t0 >= secs / 2:
            liar.set_strategy("wrong-on-curve")
            flipped = True
        if v.combine_batch(jobs) != want:
            bad += 1
        flushes[int(flipped)] += 1
    dt = time.perf_counter() - t0
    snap = pool.snapshot()
    row = {
        "bench": "offload_lying_helper", "backend": backend,
        "n": n, "k": k, "in_flight_slots": slots,
        "combines_per_sec": round(sum(flushes) * slots / dt, 1),
        "flushes_before_flip": flushes[0],
        "flushes_after_flip": flushes[1],
        "leases_verified": snap["counters"]["lease_verified"],
        "leases_rejected": snap["counters"]["lease_rejected"],
        "quarantined": snap["quarantined"],
        "verdicts_match": bad == 0,
        # one lying lease, one rejection, immediate quarantine
        "caught_on_first_lie": (
            snap["quarantined"] == ["bench-liar"]
            and snap["counters"]["lease_rejected"] == 1),
    }
    reset_offload_pool()
    return _annotate_device(row, backend)


def main(argv: List[str] = None) -> int:
    setup_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--ab", action="store_true")
    ap.add_argument("--soundness", action="store_true")
    ap.add_argument("--kill", action="store_true")
    ap.add_argument("--lie", action="store_true")
    ap.add_argument("--backend", default="tpu", choices=("cpu", "tpu"),
                    help="tpu = the device-backed verifier (the only "
                         "one with the offload hook)")
    ap.add_argument("--slots", default="1,4,16")
    ap.add_argument("--n", type=int, default=4)
    ap.add_argument("--secs", type=float, default=0.5,
                    help="measurement window per point")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 shape: tiny sizes, correctness gates")
    args = ap.parse_args(argv)
    k = 2 * ((args.n - 1) // 3) + 1
    if args.smoke:
        rows = [ab_row(4, 3, 4, "tpu", 0.1),
                soundness_row(4, 3, 4, "tpu", 0.1),
                kill_row(4, 3, 2, "tpu", 0.4),
                lie_row(4, 3, 2, "tpu", 0.4)]
        ok = all(r.get("verdicts_match", True) for r in rows) \
            and rows[2]["liveness_held"] and rows[3]["caught_on_first_lie"]
        for row in rows:
            print(json.dumps(row), flush=True)
        return 0 if ok else 1
    if not (args.ab or args.soundness or args.kill or args.lie):
        args.ab = args.soundness = args.kill = args.lie = True
    rc = 0
    slot_list = [int(x) for x in args.slots.split(",")]
    if args.ab:
        for slots in slot_list:
            row = ab_row(args.n, k, slots, args.backend, args.secs)
            rc |= 0 if row["verdicts_match"] else 1
            print(json.dumps(row), flush=True)
    if args.soundness:
        for slots in slot_list:
            print(json.dumps(soundness_row(args.n, k, slots,
                                           args.backend, args.secs)),
                  flush=True)
    if args.kill:
        row = kill_row(args.n, k, max(slot_list), args.backend,
                       max(args.secs, 1.0))
        rc |= 0 if (row["verdicts_match"] and row["liveness_held"]) else 1
        print(json.dumps(row), flush=True)
    if args.lie:
        row = lie_row(args.n, k, max(slot_list), args.backend,
                      max(args.secs, 1.0))
        rc |= 0 if (row["verdicts_match"]
                    and row["caught_on_first_lie"]) else 1
        print(json.dumps(row), flush=True)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
