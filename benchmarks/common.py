"""Shared bench setup: persistent XLA compile cache.

The crypto programs are large (sharded verify at the 1024 size class
compiles for minutes on the CPU backend); every bench must hit the same
persistent cache the tests and bench.py use, or a capture pass pays the
full compile on each invocation.
"""
from __future__ import annotations

import os


def setup_cache() -> None:
    import jax

    # honor JAX_PLATFORMS=cpu RELIABLY: on this host the tunneled-TPU
    # plugin overrides the env var and device init hangs when the tunnel
    # is down — the config update before backend init is the only
    # dependable way to force the CPU backend (same as tests/conftest.py)
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass                    # backend already initialized
    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:  # noqa: BLE001 — cache is best-effort
        pass
