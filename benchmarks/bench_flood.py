"""Config 4 — the n=1000 synthetic PrePrepare/share flood.

BASELINE.json's fourth config at a scale no single-host cluster can
reach: 1000 distinct principals' signatures flooding ONE replica's
verification plane, and a 1000-signer threshold-BLS certificate built
through the product accumulator classes. This measures the actual
product path — SigManager's cross-principal batch (the role of the
reference's per-message SigManager::verifySig loop, SigManager.cpp:197,
fed by a PrePrepare flood) and IThresholdAccumulator combine (the
fastMultExp role, BlsThresholdAccumulator.cpp:42-56) — not the raw BLS
microbench (that's benchmarks/bench_bls.py).

Phases reported (one JSON line each):
  A. sigmanager-flood: verify 1000 distinct-principal ed25519 sigs
     through SigManager.verify_batch — per-principal CPU loop vs the
     cross-principal device batch (sharded verify on a mesh).
  B. threshold-1000: sign k=667 shares; accumulate+combine through the
     CPU accumulator vs the device-MSM accumulator; verify; batch
     share-verification tree root.

Usage: python -m benchmarks.bench_flood [--n 1000] [--reps 3]
"""
from __future__ import annotations

import argparse
import json
import time


def _mean_best(fn, reps: int) -> float:
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def phase_a_sigmanager_flood(n: int, reps: int) -> None:
    """PrePrepare-shaped flood: n messages from n distinct principals."""
    from tpubft.consensus.keys import ClusterKeys
    from tpubft.consensus.sig_manager import SigManager
    from tpubft.utils.config import ReplicaConfig

    f = (n - 1) // 3
    cfg = ReplicaConfig(f_val=f, num_of_client_proxies=0)
    assert cfg.n_val == 3 * f + 1
    t0 = time.perf_counter()
    keys = ClusterKeys.generate(cfg, 0, seed=b"flood")
    keygen_s = time.perf_counter() - t0

    items = []
    for r in range(cfg.n_val):
        signer = keys.for_node(r).my_signer()
        msg = b"preprepare-digest-%d" % r
        items.append((r, msg, signer.sign(msg)))

    # per-principal CPU loop (the reference's shape); memo disabled so
    # the reps loop measures the engine, not the duplicate cache
    sm_cpu = SigManager(keys.for_node(0), memo_capacity=0)
    cpu_s = _mean_best(lambda: sm_cpu.verify_batch(items), reps)
    assert all(sm_cpu.verify_batch(items))

    # cross-principal device batch (one dispatch; sharded over the mesh)
    from tpubft.crypto.tpu import verify_batch_mixed
    sm_dev = SigManager(keys.for_node(0), batch_fn=verify_batch_mixed,
                        device_min_batch=1, memo_capacity=0)
    dev_s = _mean_best(lambda: sm_dev.verify_batch(items), reps)
    assert all(sm_dev.verify_batch(items))

    import jax
    print(json.dumps({
        "phase": "sigmanager-flood", "n_principals": cfg.n_val,
        "platform": jax.devices()[0].platform,
        "keygen_s": round(keygen_s, 2),
        "cpu_loop_verifies_per_sec": round(len(items) / cpu_s, 1),
        "device_batch_verifies_per_sec": round(len(items) / dev_s, 1),
        "device_vs_cpu": round(cpu_s / dev_s, 2),
        "device_dispatched":
            sm_dev.sigs_device_dispatched.value,
    }), flush=True)


def phase_b_threshold(n: int, reps: int) -> None:
    """1000-signer threshold certificate through the product classes."""
    from tpubft.crypto.interfaces import Cryptosystem
    from tpubft.crypto.tpu import make_threshold_verifier

    k = 2 * ((n - 1) // 3) + 1
    t0 = time.perf_counter()
    cs = Cryptosystem("threshold-bls", k, n, seed=b"flood-bls")
    keygen_s = time.perf_counter() - t0
    digest = b"f" * 32

    t0 = time.perf_counter()
    shares = [(i, cs.create_threshold_signer(i).sign_share(digest))
              for i in range(1, k + 1)]
    sign_s = time.perf_counter() - t0

    cpu_v = cs.create_threshold_verifier()
    dev_v = make_threshold_verifier("threshold-bls", k, n, cs.public_key,
                                    cs.share_public_keys)

    def combine(verifier):
        acc = verifier.new_accumulator(with_share_verification=False)
        acc.set_expected_digest(digest)
        for i, s in shares:
            acc.add(i, s)
        return acc.get_full_signed_data()

    import os
    cpu_s = _mean_best(lambda: combine(cpu_v), reps)
    os.environ["TPUBFT_MSM_CROSSOVER_K"] = "1"   # force the device MSM
    try:
        dev_s = _mean_best(lambda: combine(dev_v), reps)
        combined = combine(cpu_v)
        assert combine(dev_v) == combined, "device combine != CPU combine"
    finally:
        del os.environ["TPUBFT_MSM_CROSSOVER_K"]

    t0 = time.perf_counter()
    ok = cpu_v.verify(digest, combined)
    verify_s = time.perf_counter() - t0
    assert ok

    # batch share-verification tree (root check over all k shares)
    from tpubft.crypto import bls12381 as bls
    h = bls.hash_to_g1(digest)
    pks = [cpu_v.share_pk(i) for i, _ in shares]
    pts = [bls.g1_decompress(s) for _, s in shares]
    tree_s = _mean_best(
        lambda: bls.batch_verify_shares(pks, h, pts), reps)

    import jax
    print(json.dumps({
        "phase": "threshold-1000", "n": n, "k": k,
        "platform": jax.devices()[0].platform,
        "keygen_s": round(keygen_s, 2),
        "sign_all_shares_s": round(sign_s, 2),
        "accumulate_combine_cpu_ms": round(cpu_s * 1e3, 1),
        "accumulate_combine_device_ms": round(dev_s * 1e3, 1),
        "verify_combined_ms": round(verify_s * 1e3, 1),
        "batch_share_tree_root_ms": round(tree_s * 1e3, 1),
    }), flush=True)


def phase_c_memo_coalesce(n: int, reps: int) -> None:
    """The admission-plane win this repo's PR 1 claims: retransmit /
    duplicate verifies short-circuit on the verified-signature memo, and
    cold mixed-scheme traffic coalesces into per-curve kernel calls in
    one dispatch. Reported against the pre-change shape (per-principal
    scalar loop, no memo)."""
    from tpubft.consensus.keys import ClusterKeys
    from tpubft.consensus.sig_manager import SigManager
    from tpubft.crypto.tpu import verify_batch_mixed
    from tpubft.utils.config import ReplicaConfig

    f = max((n - 1) // 3, 1)
    cfg = ReplicaConfig(f_val=f, num_of_client_proxies=0,
                        client_sig_scheme="ecdsa-secp256k1")
    keys = ClusterKeys.generate(cfg, 0, seed=b"flood-memo")
    items = []
    for r in range(cfg.n_val):
        signer = keys.for_node(r).my_signer()
        msg = b"preprepare-digest-%d" % r
        items.append((r, msg, signer.sign(msg)))

    # pre-change shape: per-principal scalar loop, memo off
    sm_loop = SigManager(keys.for_node(0), memo_capacity=0)
    loop_s = _mean_best(lambda: sm_loop.verify_batch(items), reps)

    # coalesced batch plane, memo off: cold-traffic throughput
    sm_cold = SigManager(keys.for_node(0), batch_fn=verify_batch_mixed,
                         device_min_batch=1, memo_capacity=0)
    sm_cold.verify_batch(items)                    # compile warmup
    cold_s = _mean_best(lambda: sm_cold.verify_batch(items), reps)

    # memoized plane: one cold pass, then pure retransmit traffic
    sm_memo = SigManager(keys.for_node(0), batch_fn=verify_batch_mixed,
                         device_min_batch=1, memo_capacity=4 * len(items))
    assert all(sm_memo.verify_batch(items))        # cold: fills the memo
    memo_s = _mean_best(lambda: sm_memo.verify_batch(items), reps)
    total = (sm_memo.memo_hits.value + sm_memo.batched_verifies.value
             + sm_memo.scalar_fallbacks.value)

    import jax
    print(json.dumps({
        "phase": "memo-coalesce", "n_sigs": len(items),
        "platform": jax.devices()[0].platform,
        "scalar_loop_verifies_per_sec": round(len(items) / loop_s, 1),
        "coalesced_verifies_per_sec": round(len(items) / cold_s, 1),
        "memo_hit_verifies_per_sec": round(len(items) / memo_s, 1),
        "coalesced_vs_scalar_loop": round(loop_s / cold_s, 2),
        "memo_vs_scalar_loop": round(loop_s / memo_s, 2),
        "memo_hit_rate": round(sm_memo.memo_hits.value / total, 4),
        "counters": {
            "memo_hits": sm_memo.memo_hits.value,
            "batched_verifies": sm_memo.batched_verifies.value,
            "scalar_fallbacks": sm_memo.scalar_fallbacks.value,
        },
    }), flush=True)


def main() -> None:
    from benchmarks.common import setup_cache
    setup_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1000)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--phases", default="a,b,c")
    args = ap.parse_args()
    if "a" in args.phases:
        phase_a_sigmanager_flood(args.n, args.reps)
    if "b" in args.phases:
        phase_b_threshold(args.n, args.reps)
    if "c" in args.phases:
        phase_c_memo_coalesce(args.n, args.reps)


if __name__ == "__main__":
    main()
