"""Chaos-campaign runner: seeded fault schedules over the live stack.

Runs the scenario matrix from tpubft/testing/campaign.py and prints ONE
JSON line (the repo's bench convention):

  {"metric": "chaos-scenarios-passed", "value": K, "unit": "scenarios",
   "seed": S, "event_log_digest": "...", ...}

plus writes the full campaign artifact (seed, event log + digest,
per-scenario verdicts, recovery-time stats) to CHAOS_r0N.json at the
repo root (next free round number) or to --out.

Determinism contract: the event-log digest is a pure function of
(seed, matrix) — `--replay-check` runs the campaign twice and fails
loudly if the digests differ, which is the property that makes a red
seed attachable to a bug report.

Usage:
  python -m benchmarks.bench_chaos [--seed N] [--smoke | --full]
      [--scenario NAME ...] [--out PATH] [--replay-check] [--keep-tmp]

--smoke runs the in-process matrix only (seconds; wired into tier-1 via
tests/test_chaos_campaign.py); the default/--full matrix adds the
real-subprocess scenarios (BftTestNetwork: SIGSTOP partitions, SIGKILL
crashes, env-triggered crashpoints).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _next_artifact_path() -> str:
    n = 1
    while os.path.exists(os.path.join(_REPO_ROOT, "CHAOS_r%02d.json" % n)):
        n += 1
    return os.path.join(_REPO_ROOT, "CHAOS_r%02d.json" % n)


def run_campaign(seed: int, specs, keep_tmp: bool = False) -> dict:
    from tpubft.testing.campaign import ChaosCampaign
    return ChaosCampaign(seed=seed, specs=specs, keep_tmp=keep_tmp).run()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="seeded chaos campaign")
    p.add_argument("--seed", type=int, default=None,
                   help="campaign seed (default: campaign.DEFAULT_SEED)")
    depth = p.add_mutually_exclusive_group()
    depth.add_argument("--smoke", action="store_true",
                       help="in-process matrix only (tier-1 shape)")
    depth.add_argument("--full", action="store_true",
                       help="the full matrix (the default)")
    p.add_argument("--scenario", action="append", default=[],
                   help="run only the named scenario(s); repeatable")
    p.add_argument("--out", default=None,
                   help="artifact path (default: CHAOS_r0N.json, next N)")
    p.add_argument("--no-artifact", action="store_true",
                   help="print the JSON line only")
    p.add_argument("--replay-check", action="store_true",
                   help="run twice, fail unless event-log digests match")
    p.add_argument("--keep-tmp", action="store_true")
    p.add_argument("--list", action="store_true",
                   help="list scenario names and exit")
    args = p.parse_args(argv)

    # force the CPU jax backend before anything imports the ops plane —
    # chaos campaigns measure recovery, never kernels (benchmarks.common
    # applies the same config the tests use)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from benchmarks.common import setup_cache
    setup_cache()

    from tpubft.testing import campaign as cmp
    seed = args.seed if args.seed is not None else cmp.DEFAULT_SEED
    if args.list:
        for s in cmp.full_matrix():
            print(f"{s.name:40s} {s.kind:8s} budget={s.time_budget_s:.0f}s"
                  f" tags={','.join(s.tags)}")
        return 0
    if args.scenario:
        by_name = cmp.matrix_by_name()
        missing = [n for n in args.scenario if n not in by_name]
        if missing:
            print(f"unknown scenario(s): {missing}; have "
                  f"{sorted(by_name)}", file=sys.stderr)
            return 2
        specs = [by_name[n] for n in args.scenario]
    elif args.smoke:
        specs = cmp.smoke_matrix()
    else:
        specs = cmp.full_matrix()

    artifact = run_campaign(seed, specs, keep_tmp=args.keep_tmp)
    if args.replay_check:
        second = run_campaign(seed, specs, keep_tmp=args.keep_tmp)
        match = (artifact["event_log_digest"]
                 == second["event_log_digest"])
        # verdicts live OUTSIDE the digest, so a scenario that fails
        # only on the replay pass (a nondeterministic recovery bug
        # under the identical schedule — the thing this mode exists to
        # surface) must fail the run in its own right
        second_failed = [s["name"] for s in second["scenarios"]
                         if not s["ok"]]
        artifact["replay_check"] = {
            "match": match,
            "second_digest": second["event_log_digest"],
            "second_failed": second_failed}
        if not match:
            print("REPLAY DETERMINISM BROKEN: digests differ "
                  f"({artifact['event_log_digest']} vs "
                  f"{second['event_log_digest']})", file=sys.stderr)
        if second_failed:
            print(f"replay pass went red: {second_failed} failed under "
                  f"the identical schedule", file=sys.stderr)

    out_path = None
    if not args.no_artifact:
        out_path = args.out or _next_artifact_path()
        with open(out_path, "w") as fh:
            json.dump(artifact, fh, indent=1)
        artifact_note = {"artifact": out_path}
    else:
        artifact_note = {}

    record = {
        "metric": "chaos-scenarios-passed (of %d)"
                  % len(artifact["scenarios"]),
        "value": artifact["passed"],
        "unit": "scenarios",
        "seed": artifact["seed"],
        "event_log_digest": artifact["event_log_digest"],
        "failed": [s["name"] for s in artifact["scenarios"]
                   if not s["ok"]],
        **artifact_note,
    }
    if artifact.get("degraded"):
        record["degraded"] = True
        record["probe_error"] = artifact["probe_error"]
    if args.replay_check:
        record["replay_match"] = artifact["replay_check"]["match"]
        if artifact["replay_check"]["second_failed"]:
            record["replay_failed"] = \
                artifact["replay_check"]["second_failed"]
    print(json.dumps(record))
    ok = (artifact["failed"] == 0
          and (not args.replay_check
               or (artifact["replay_check"]["match"]
                   and not artifact["replay_check"]["second_failed"])))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
