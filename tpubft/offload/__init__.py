"""Verified crypto-offload tier (ISSUE 20).

Replicas lease their hottest launches — the BLS Lagrange/MSM combine,
the multisig-BLS share sums, and the ECDSA RLC fold — to a pool of
NON-VOTING helper processes (a crypto sidecar fleet that scales
independently of the replica set), and re-verify every returned result
on-replica with a constant-size soundness check ("2G2T", arXiv
2602.23464) before it can influence a verdict:

  * a helper that lies (wrong point, wrong-but-on-curve point, stale
    lease replay, garbage bytes) fails the check, is breaker-evicted
    as BYZANTINE (quarantined — no cooldown re-admission without an
    operator reset), and its lease re-runs locally inside the same
    flush;
  * a helper that times out or drops the connection is SICK: the
    per-helper `helper.<id>` breaker applies the same cooldown + probe
    re-admission discipline the PR 16 mesh tier uses for chips;
  * with offload on or off, the verdict-producing code paths
    (`combine_batch` / `rlc_verify_batch`) return byte-identical
    results — helpers only ever donate work, never trust.

Layout: `protocol` (length-prefixed lease frames), `soundness` (the
on-replica checks), `pool` (leasing, breakers, quarantine, metrics),
`helper` (the daemon + the ByzantineHelper test strategies).
"""
