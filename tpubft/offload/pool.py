"""Helper pool: leasing, verification, eviction — the trust boundary.

The pool is PROCESS-WIDE (like the device breaker and the chip mesh:
all replicas of one process share the helper fleet). Each helper gets a
`helper.<id>` circuit breaker so the health plane enumerates the family
exactly like the mesh's `device.chip<N>` children:

  * transport fault / deadline miss  -> SICK: breaker failure, normal
    cooldown + half-open probe re-admission (PR 16 discipline);
  * failed soundness check, stale lease id, malformed bytes ->
    BYZANTINE: immediate eviction into the quarantine set and a forced
    breaker trip with an effectively-infinite cooldown — NO automatic
    re-admission; `operator_reset(helper_id)` is the only way back.

Lease semantics: deadline + single-retry-then-local. A lease that fails
(either way) re-runs on the local device/host path inside the same
flush, so callers never stall and verdict-producing code paths are
byte-identical with offload on or off.

High-level verified entry points (`combine_via_offload`,
`sum_via_offload`, `ecdsa_via_offload`) are the ONLY sanctioned seam
for crypto call sites — raw `lease()`/frame plumbing is confined to
this package by the tpulint `offload-seam` pass.
"""
from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from tpubft.offload import protocol as proto
from tpubft.offload import soundness
from tpubft.utils import flight
from tpubft.utils.breaker import BreakerOpen, get_breaker
from tpubft.utils.metrics import Component

log = logging.getLogger("tpubft.offload")

# a quarantined helper's breaker cooldown: ~forever (operator reset
# required; the pool-level quarantine set is the enforcement, the
# breaker state is how `status get health` shows it)
QUARANTINE_COOLDOWN_S = 10 * 365 * 24 * 3600.0


class _ByzantineResponse(Exception):
    """Wire-level lie (stale lease id, ST_ERR abuse, undecodable
    envelope) — distinct from transport faults."""


class HelperTransport:
    """One helper endpoint. `call` returns the raw response payload for
    OUR lease id or raises (_ByzantineResponse / OSError / timeout)."""

    def __init__(self, helper_id: str):
        self.helper_id = helper_id

    def call(self, lease_id: int, kind: int, payload: bytes,
             timeout_s: float) -> bytes:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InprocHelper(HelperTransport):
    """Direct call into a HelperServer — the test/bench/chaos
    transport. The deadline is enforced post-hoc (a synchronous call
    can't be interrupted): a slow-loris helper is detected when its
    answer comes back late, which is exactly the sick classification
    the TCP transport's socket timeout produces."""

    def __init__(self, helper_id: str, server):
        super().__init__(helper_id)
        self.server = server

    def call(self, lease_id: int, kind: int, payload: bytes,
             timeout_s: float) -> bytes:
        t0 = time.monotonic()
        req = proto.encode_request(lease_id, kind,
                                   int(timeout_s * 1000), payload)
        try:
            raw = self.server.handle(req)
        except Exception as e:
            raise OSError(f"helper {self.helper_id} dropped the lease: "
                          f"{e}") from e
        if time.monotonic() - t0 > timeout_s:
            raise socket.timeout(
                f"helper {self.helper_id} missed the lease deadline")
        return _check_envelope(raw, lease_id)


class TcpHelper(HelperTransport):
    """Frame transport to a helper daemon; connects lazily, one
    connection per pool (leases are serialized per helper by the
    breaker's perspective anyway — parallelism comes from helper
    COUNT, not per-helper pipelining)."""

    def __init__(self, helper_id: str, host: str, port: int):
        super().__init__(helper_id)
        self.host, self.port = host, port
        self._sock: Optional[socket.socket] = None
        self._mu = threading.Lock()

    def _connect(self, timeout_s: float) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=timeout_s)
            self._sock = s
        self._sock.settimeout(timeout_s)
        return self._sock

    def call(self, lease_id: int, kind: int, payload: bytes,
             timeout_s: float) -> bytes:
        with self._mu:
            try:
                s = self._connect(timeout_s)
                proto.send_frame(s, proto.encode_request(
                    lease_id, kind, int(timeout_s * 1000), payload))
                raw = proto.recv_frame(s)
            except (OSError, proto.ProtocolError):
                self.close()
                raise
            if raw is None:
                self.close()
                raise OSError(f"helper {self.helper_id} closed mid-lease")
            return _check_envelope(raw, lease_id)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


def _check_envelope(raw: bytes, lease_id: int) -> bytes:
    try:
        rid, status, body = proto.decode_response(raw)
    except proto.ProtocolError as e:
        raise _ByzantineResponse(f"undecodable response ({e})") from e
    if rid != lease_id:
        raise _ByzantineResponse(
            f"stale lease replay (got id {rid}, expected {lease_id})")
    if status != proto.ST_OK:
        # an honest helper may legitimately fail to compute (e.g. it
        # can't decode OUR payload — which would be our bug); treat as
        # transport-grade so it degrades, not convicts
        raise OSError("helper reported compute error")
    return body


class HelperPool:
    def __init__(self) -> None:
        self._mu = threading.RLock()
        self._helpers: Dict[str, HelperTransport] = {}
        self._order: List[str] = []
        self._quarantined: set = set()
        self._rr = 0
        self._lease_seq = 0
        self._inflight = 0
        self.enabled = False
        self.routing = True          # the autotuner's actuator
        self.lease_timeout_s = 0.2
        self.max_inflight = 4
        self.metrics = Component("offload")
        self.m_issued = self.metrics.register_counter("lease_issued")
        self.m_verified = self.metrics.register_counter("lease_verified")
        self.m_rejected = self.metrics.register_counter("lease_rejected")
        self.m_evicted = self.metrics.register_counter("helper_evicted")
        self.m_timeouts = self.metrics.register_counter("lease_timeouts")
        self.m_local = self.metrics.register_counter("local_fallbacks")
        self.g_admitted = self.metrics.register_gauge("helpers_admitted")
        # cumulative lease cost (µs + items) — the autotuner's routing
        # policy diffs these across telemetry snapshots to compare
        # leased per-item cost against the local kernel per-item cost
        self.lease_us_total = 0
        self.lease_items_total = 0
        self.soundness_us_total = 0
        self._h_soundness = None
        self._h_lease = None

    # ---- wiring ------------------------------------------------------

    def _hists(self):
        if self._h_soundness is None:
            from tpubft.diagnostics import get_registrar
            self._h_soundness = get_registrar().histogram(
                "off_soundness_us", unit="us")
            self._h_lease = get_registrar().histogram(
                "off_lease_us", unit="us")
        return self._h_soundness, self._h_lease

    def configure(self, enabled: Optional[bool] = None,
                  lease_timeout_ms: Optional[int] = None,
                  max_inflight: Optional[int] = None) -> None:
        with self._mu:
            if enabled is not None:
                self.enabled = bool(enabled)
            if lease_timeout_ms is not None:
                self.lease_timeout_s = max(1, int(lease_timeout_ms)) / 1000.0
            if max_inflight is not None:
                self.max_inflight = max(1, int(max_inflight))

    def add_helper(self, transport: HelperTransport) -> None:
        with self._mu:
            hid = transport.helper_id
            self._helpers[hid] = transport
            if hid not in self._order:
                self._order.append(hid)
            # materialize the breaker so the family is visible in
            # `status get health` from admission, not first failure
            get_breaker(f"helper.{hid}")
            self._refresh_admitted()

    def add_endpoint(self, helper_id: str, host: str, port: int) -> None:
        self.add_helper(TcpHelper(helper_id, host, port))

    def remove_helper(self, helper_id: str) -> None:
        with self._mu:
            t = self._helpers.pop(helper_id, None)
            if t is not None:
                t.close()
            if helper_id in self._order:
                self._order.remove(helper_id)
            self._refresh_admitted()

    def set_routing(self, on: bool) -> None:
        """Autotuner actuator: keep the tier configured but stop (or
        resume) routing work helper-ward."""
        with self._mu:
            self.routing = bool(on)

    def _refresh_admitted(self) -> None:
        self.g_admitted.set(len([h for h in self._order
                                 if h not in self._quarantined]))

    def active(self) -> bool:
        with self._mu:
            return (self.enabled and self.routing
                    and any(h not in self._quarantined
                            for h in self._order))

    # ---- leasing -----------------------------------------------------

    def lease(self, kind: int, payload: bytes,
              n_items: int) -> Optional[Tuple[str, bytes]]:
        """(helper_id, response payload) or None -> run locally. The
        response payload is UNVERIFIED — callers must pass it through a
        soundness check before it can touch a verdict."""
        with self._mu:
            if not (self.enabled and self.routing):
                return None
            if self._inflight >= self.max_inflight:
                self.m_local.inc()
                return None
            self._inflight += 1
        try:
            tried: set = set()
            for _attempt in range(2):       # deadline + single retry
                h = self._pick(tried)
                if h is None:
                    break
                tried.add(h.helper_id)
                br = get_breaker(f"helper.{h.helper_id}")
                with self._mu:
                    self._lease_seq += 1
                    lease_id = self._lease_seq
                self.m_issued.inc()
                flight.record(flight.EV_OFF_LEASE, arg=n_items, view=kind)
                t0 = time.perf_counter()
                try:
                    with br.attempt("lease"):
                        body = h.call(lease_id, kind, payload,
                                      self.lease_timeout_s)
                except BreakerOpen:
                    continue
                except _ByzantineResponse as e:
                    self.report_byzantine(h.helper_id, str(e))
                    continue
                except Exception as e:  # noqa: BLE001 — transport
                    # fault / deadline miss: the breaker recorded the
                    # failure (sick path — cooldown + probe)
                    self.m_timeouts.inc()
                    log.warning("lease to helper %s failed (sick): %s",
                                h.helper_id, e)
                    continue
                dt_us = int((time.perf_counter() - t0) * 1e6)
                self._hists()[1].record(dt_us)
                with self._mu:
                    self.lease_us_total += dt_us
                    self.lease_items_total += max(1, n_items)
                return h.helper_id, body
            self.m_local.inc()
            return None
        finally:
            with self._mu:
                self._inflight -= 1

    def _pick(self, tried: set) -> Optional[HelperTransport]:
        """Round-robin over admitted (non-quarantined, breaker-willing)
        helpers, skipping ones this lease already tried."""
        with self._mu:
            n = len(self._order)
            for i in range(n):
                hid = self._order[(self._rr + i) % n]
                if hid in tried or hid in self._quarantined:
                    continue
                if not get_breaker(f"helper.{hid}").allow():
                    continue
                self._rr = (self._rr + i + 1) % n
                return self._helpers[hid]
            return None

    # ---- verdicts on helpers ----------------------------------------

    def lease_verified(self, helper_id: str, soundness_us: int) -> None:
        self.m_verified.inc()
        self._hists()[0].record(soundness_us)
        with self._mu:
            self.soundness_us_total += soundness_us
        flight.record(flight.EV_OFF_VERIFIED, arg=soundness_us)

    def lease_rejected(self, helper_id: str, soundness_us: int) -> None:
        self.m_rejected.inc()
        self._hists()[0].record(soundness_us)
        with self._mu:
            self.soundness_us_total += soundness_us
        with self._mu:
            ordinal = (self._order.index(helper_id)
                       if helper_id in self._order else -1)
        flight.record(flight.EV_OFF_REJECTED, arg=max(0, ordinal))

    def report_byzantine(self, helper_id: str, reason: str) -> None:
        """Quarantine: the helper lied. No cooldown path back — the
        forced breaker trip keeps `status get health` degraded until an
        operator resets it (a lying helper held out of the pool IS a
        degraded fleet, not a healed one)."""
        with self._mu:
            if helper_id in self._quarantined:
                return
            self._quarantined.add(helper_id)
            self._refresh_admitted()
        get_breaker(f"helper.{helper_id}").trip(
            cooldown_s=QUARANTINE_COOLDOWN_S, cause="byzantine")
        self.m_evicted.inc()
        flight.record(flight.EV_OFF_EVICT, arg=1)
        log.error("helper %s evicted as BYZANTINE (%s) — quarantined, "
                  "operator reset required", helper_id, reason)

    def operator_reset(self, helper_id: str) -> None:
        """The ONE way back in for a quarantined helper."""
        with self._mu:
            self._quarantined.discard(helper_id)
            self._refresh_admitted()
        get_breaker(f"helper.{helper_id}").reset()
        log.warning("helper %s re-admitted by operator reset", helper_id)

    @property
    def quarantined(self) -> set:
        with self._mu:
            return set(self._quarantined)

    # ---- observability ----------------------------------------------

    def snapshot(self) -> Dict:
        with self._mu:
            return {
                "enabled": self.enabled,
                "routing": self.routing,
                "helpers": list(self._order),
                "quarantined": sorted(self._quarantined),
                "max_inflight": self.max_inflight,
                "lease_timeout_ms": int(self.lease_timeout_s * 1000),
                "lease_us_total": self.lease_us_total,
                "lease_items_total": self.lease_items_total,
                "soundness_us_total": self.soundness_us_total,
                "counters": {k: c.value
                             for k, c in self.metrics.counters.items()},
            }

    def reset(self) -> None:
        """Test/chaos-campaign isolation: drop helpers, quarantine and
        counters; per-helper breakers reset too (they are registry-
        global and would otherwise leak state across scenarios)."""
        with self._mu:
            for t in self._helpers.values():
                t.close()
            for hid in self._order:
                get_breaker(f"helper.{hid}").reset()
            self._helpers.clear()
            self._order.clear()
            self._quarantined.clear()
            self._inflight = 0
            self.enabled = False
            self.routing = True
            self.lease_timeout_s = 0.2
            self.max_inflight = 4
            self.lease_us_total = 0
            self.lease_items_total = 0
            self.soundness_us_total = 0
            for c in self.metrics.counters.values():
                c.value = 0
            self._refresh_admitted()


# ---------------------------------------------------------------------
# process-wide accessor (ops/dispatch.offload_pool() fronts this)
# ---------------------------------------------------------------------
_POOL: Optional[HelperPool] = None
_POOL_MU = threading.Lock()


def get_offload_pool() -> HelperPool:
    global _POOL
    with _POOL_MU:
        if _POOL is None:
            _POOL = HelperPool()
            flight.register_dump_provider(
                "offload", lambda: _POOL.snapshot()
                if _POOL is not None else {})
        return _POOL


def pool_if_active() -> Optional[HelperPool]:
    """The pool iff it exists AND is currently routing work — the hot
    paths' cheap gate (no pool construction on the offload-off path)."""
    p = _POOL
    return p if (p is not None and p.active()) else None


def reset_offload_pool() -> None:
    p = _POOL
    if p is not None:
        p.reset()


# ---------------------------------------------------------------------
# the verified high-level API — what crypto call sites use
# ---------------------------------------------------------------------

def combine_via_offload(segments: Sequence[Tuple[Sequence[int],
                                                 Sequence[object]]],
                        digests: Sequence[bytes], master_pk,
                        local_fn: Callable[[], List]) -> Optional[List]:
    """Lease the threshold Lagrange/MSM combine. Returns the per-
    segment combined points — VERIFIED helper output, or (after a
    failed check) the local re-run's output — or None when no lease
    happened and the caller should run its own path. Callers get
    byte-identical results to `local_fn()` in every case."""
    from tpubft.crypto import bls12381 as bls
    pool = pool_if_active()
    if pool is None:
        return None
    live = [i for i, (ids, _) in enumerate(segments) if ids]
    if not live:
        return None
    try:
        req = proto.encode_bls_segments(
            [(list(segments[i][0]),
              [bls.g1_compress(p) for p in segments[i][1]])
             for i in live])
    except proto.ProtocolError:
        return None
    leased = pool.lease(proto.KIND_BLS_COMBINE, req,
                        sum(len(segments[i][0]) for i in live))
    if leased is None:
        return None
    hid, resp = leased
    t0 = time.perf_counter()
    raw_pts = proto.decode_points(resp, len(live))
    pts = soundness.decompress_points(raw_pts) if raw_pts else None
    ok = pts is not None and soundness.check_bls_combine(
        master_pk, [digests[i] for i in live], pts)
    dt_us = int((time.perf_counter() - t0) * 1e6)
    if ok:
        pool.lease_verified(hid, dt_us)
        out = [None] * len(segments)
        for i, pt in zip(live, pts):
            out[i] = pt
        return out
    # check failed: ONE local re-run disambiguates bad shares from a
    # lying helper (see soundness.py docstring)
    pool.lease_rejected(hid, dt_us)
    local = local_fn()
    if pts is None or any(
            bls.g1_compress(pts[j]) != bls.g1_compress(local[i])
            for j, i in enumerate(live) if local[i] is not None):
        pool.report_byzantine(hid, "bls-combine soundness check failed")
    # helper honest, shares bad: the local (equally failing) points
    # flow to verify_batch_certs -> bad-share identification exactly
    # as with offload off
    return local


def sum_via_offload(segments: Sequence[Sequence[object]],
                    meta: Sequence[Optional[Tuple[bytes, Tuple[int, ...]]]],
                    verifier, local_fn: Callable[[], List]
                    ) -> Optional[List]:
    """Lease the multisig-BLS unweighted sums. meta[i] = (digest,
    contributor ids) per segment (None segments stay local)."""
    from tpubft.crypto import bls12381 as bls
    pool = pool_if_active()
    if pool is None:
        return None
    live = [i for i, pts in enumerate(segments)
            if pts and meta[i] is not None and meta[i][1]]
    if not live:
        return None
    try:
        # ids are a no-op for the unweighted sum — zeros keep the one
        # segment encoding shared with the combine lease
        req = proto.encode_bls_segments(
            [([0] * len(segments[i]),
              [bls.g1_compress(p) for p in segments[i]])
             for i in live])
    except proto.ProtocolError:
        return None
    leased = pool.lease(proto.KIND_BLS_SUM, req,
                        sum(len(segments[i]) for i in live))
    if leased is None:
        return None
    hid, resp = leased
    t0 = time.perf_counter()
    raw_pts = proto.decode_points(resp, len(live))
    pts = soundness.decompress_points(raw_pts) if raw_pts else None
    ok = False
    if pts is not None:
        try:
            check_meta = [(meta[i][0], verifier.agg_pk(list(meta[i][1])))
                          for i in live]
            ok = soundness.check_bls_sum(check_meta, pts)
        except Exception:  # noqa: BLE001 — out-of-range ids etc.:
            ok = False     # treat as unverifiable, fall to local
    dt_us = int((time.perf_counter() - t0) * 1e6)
    if ok:
        pool.lease_verified(hid, dt_us)
        out = [None] * len(segments)
        for i, pt in zip(live, pts):
            out[i] = pt
        return out
    pool.lease_rejected(hid, dt_us)
    local = local_fn()
    if pts is None or any(
            local[i] is not None
            and bls.g1_compress(pts[j]) != bls.g1_compress(local[i])
            for j, i in enumerate(live)):
        pool.report_byzantine(hid, "bls-sum soundness check failed")
    return local


def ecdsa_via_offload(curve: str,
                      items: Sequence[Tuple[bytes, bytes, bytes]],
                      local_fn: Callable[[], List[bool]]
                      ) -> Optional[List[bool]]:
    """Lease the ECDSA verdict storm: the helper returns per-item bits,
    the replica re-folds the accepted subset in ONE launch with its own
    coefficients and host-checks the plausible rejects. The win is
    skipping the bisection descent under forgery floods; a lying
    helper (either direction) is evicted and the whole batch re-runs
    locally."""
    pool = pool_if_active()
    if pool is None:
        return None
    leased = pool.lease(proto.KIND_ECDSA_RLC,
                        proto.encode_ecdsa_items(curve, items), len(items))
    if leased is None:
        return None
    hid, resp = leased
    from tpubft.ops import ecdsa as ops_ecdsa
    t0 = time.perf_counter()
    bits = proto.decode_verdicts(resp, len(items))
    verdicts = None
    if bits is not None:
        try:
            prep = ops_ecdsa.prepare_rlc_batch(curve, items)
            verdicts = soundness.check_ecdsa_verdicts(curve, items,
                                                      prep, bits)
        except Exception:  # noqa: BLE001 — device loss during the
            # check launch: we cannot verify, so we cannot use the
            # helper's answer; the caller's local path degrades
            # exactly as it would with offload off
            dt_us = int((time.perf_counter() - t0) * 1e6)
            pool.lease_rejected(hid, dt_us)
            return None
    dt_us = int((time.perf_counter() - t0) * 1e6)
    if verdicts is not None:
        pool.lease_verified(hid, dt_us)
        return verdicts
    pool.lease_rejected(hid, dt_us)
    pool.report_byzantine(
        hid, "ecdsa verdict bits failed the re-fold check"
        if bits is not None else "malformed ecdsa verdict payload")
    return local_fn()
