"""Crypto-offload helper: the non-voting sidecar worker.

A helper holds NO key material and NO consensus state — it receives
segments of compressed G1 shares (or ECDSA items), does the arithmetic,
and returns points/verdicts. It is never trusted: the replica re-checks
every answer (tpubft/offload/soundness.py), so a helper binary can be
anything from this process to rented burst capacity on somebody else's
accelerator.

Process model mirrors apps/skvbc_replica.py: `python -m
tpubft.offload.helper --port 7700` runs the TCP daemon (length-prefixed
frames, one handler thread per connection). `HelperServer` is the
in-process equivalent the tests/benchmarks/chaos scenarios drive
directly.

Byzantine test strategies (`--strategy`, same named-factory pattern as
testing/byzantine.py): every lie the fault-matrix tests and the
`offload-byzantine-helper-flood` chaos scenario need — wrong point,
wrong-but-on-curve point, stale lease replay, garbage bytes, slow-loris
and crash-mid-lease.
"""
from __future__ import annotations

import argparse
import hashlib
import logging
import socket
import threading
import time
from typing import Callable, Dict, List, Optional

from tpubft.offload import protocol as proto

log = logging.getLogger("tpubft.offload.helper")


class HelperCrashed(Exception):
    """In-process stand-in for a helper dying mid-lease (connection
    drop): the pool classifies it as a transport fault (sick)."""


# ---------------------------------------------------------------------
# honest compute
# ---------------------------------------------------------------------

def compute(kind: int, payload: bytes) -> bytes:
    from tpubft.crypto import bls12381 as bls
    if kind == proto.KIND_BLS_COMBINE:
        segs = proto.decode_bls_segments(payload)
        out = []
        for ids, shares in segs:
            pts = [bls.g1_decompress(p) for p in shares]
            out.append(bls.g1_compress(bls.combine_shares(ids, pts)))
        return proto.encode_points(out)
    if kind == proto.KIND_BLS_SUM:
        segs = proto.decode_bls_segments(payload)
        out = []
        for _ids, shares in segs:
            acc = None
            for p in shares:
                acc = bls.g1_add(acc, bls.g1_decompress(p))
            out.append(bls.g1_compress(acc))
        return proto.encode_points(out)
    if kind == proto.KIND_ECDSA_RLC:
        from tpubft.crypto import scalar as _scalar
        curve, items = proto.decode_ecdsa_items(payload)
        bits = _scalar.ecdsa_verify_batch(
            [(pk, d, s) for d, s, pk in items], curve)
        return proto.encode_verdicts(bits)
    raise proto.ProtocolError(f"unknown lease kind {kind}")


# ---------------------------------------------------------------------
# Byzantine strategies: (lease_id, kind, payload, honest_response) ->
# (response_lease_id, response_payload) — or side effects (sleep/crash)
# ---------------------------------------------------------------------

def _tag_point(seed: bytes) -> bytes:
    """A valid, in-subgroup, wrong G1 point (the hardest lie: it
    decompresses fine and only the pairing check can expose it)."""
    from tpubft.crypto import bls12381 as bls
    return bls.g1_compress(bls.hash_to_g1(b"byzantine-helper" + seed))


def _strategy_honest(server: "HelperServer"):
    return lambda lease_id, kind, payload, resp: (lease_id, resp)


def _strategy_wrong_point(server: "HelperServer"):
    """Bit-flipped points: undecodable 48-byte blobs (for ECDSA leases:
    flipped verdict bits — the analogous wrong-answer shape)."""
    def mutate(lease_id, kind, payload, resp):
        if kind == proto.KIND_ECDSA_RLC:
            return lease_id, bytes(b ^ 1 for b in resp)
        return lease_id, bytes(b ^ 0xFF for b in resp)
    return mutate


def _strategy_wrong_on_curve(server: "HelperServer"):
    """Replace every returned point with a VALID subgroup point that is
    not the answer; for ECDSA, flip only the first verdict."""
    def mutate(lease_id, kind, payload, resp):
        if kind == proto.KIND_ECDSA_RLC:
            if not resp:
                return lease_id, resp
            return lease_id, bytes([resp[0] ^ 1]) + resp[1:]
        n = len(resp) // proto.G1_LEN
        return lease_id, b"".join(
            _tag_point(payload[:32] + bytes([i & 0xFF]))
            for i in range(n))
    return mutate


def _strategy_stale_replay(server: "HelperServer"):
    """Answer every lease after the first with the FIRST lease's full
    response (old lease id + old payload) — the classic replay."""
    def mutate(lease_id, kind, payload, resp):
        if server._replay_cache is None:
            server._replay_cache = (lease_id, resp)
            return lease_id, resp
        return server._replay_cache
    return mutate


def _strategy_garbage(server: "HelperServer"):
    def mutate(lease_id, kind, payload, resp):
        junk = hashlib.sha256(payload or b"junk").digest()
        return lease_id, (junk * (len(resp) // 32 + 2))[:max(len(resp), 7)]
    return mutate


def _strategy_slow_loris(server: "HelperServer"):
    def mutate(lease_id, kind, payload, resp):
        # sleep past any sane deadline; the pool's lease timeout fires
        # first and classifies the helper as sick
        time.sleep(server.slow_s)
        return lease_id, resp
    return mutate


def _strategy_crash(server: "HelperServer"):
    def mutate(lease_id, kind, payload, resp):
        raise HelperCrashed("helper crashed mid-lease")
    return mutate


STRATEGIES: Dict[str, Callable] = {
    "honest": _strategy_honest,
    "wrong-point": _strategy_wrong_point,
    "wrong-on-curve": _strategy_wrong_on_curve,
    "stale-replay": _strategy_stale_replay,
    "garbage": _strategy_garbage,
    "slow-loris": _strategy_slow_loris,
    "crash": _strategy_crash,
}


class HelperServer:
    """One helper's brain: decode lease, compute, apply strategy. The
    in-process pool transport calls `handle()` directly; the TCP daemon
    wraps it in the frame loop."""

    def __init__(self, helper_id: str = "h0",
                 strategy: str = "honest", slow_s: float = 2.0):
        if strategy not in STRATEGIES:
            raise ValueError(f"unknown helper strategy {strategy!r} "
                             f"(have: {sorted(STRATEGIES)})")
        self.helper_id = helper_id
        self.strategy_name = strategy
        self.slow_s = slow_s
        self.leases_served = 0
        self._replay_cache: Optional[tuple] = None
        self._mutate = STRATEGIES[strategy](self)

    def set_strategy(self, strategy: str) -> None:
        """Swap behavior mid-run (chaos: an honest helper turns liar
        under load — the exact adversary the soundness check exists
        for)."""
        if strategy not in STRATEGIES:
            raise ValueError(f"unknown helper strategy {strategy!r} "
                             f"(have: {sorted(STRATEGIES)})")
        self.strategy_name = strategy
        self._mutate = STRATEGIES[strategy](self)

    def handle(self, request: bytes) -> bytes:
        lease_id, kind, _deadline_ms, payload = proto.decode_request(request)
        self.leases_served += 1
        try:
            resp = compute(kind, payload)
            status = proto.ST_OK
        except HelperCrashed:
            raise
        except Exception as e:  # noqa: BLE001 — an honest helper
            # reports a compute error rather than fabricating bytes
            log.warning("helper %s compute failed: %s", self.helper_id, e)
            resp, status = b"", proto.ST_ERR
        if status == proto.ST_OK:
            lease_id, resp = self._mutate(lease_id, kind, payload, resp)
        return proto.encode_response(lease_id, status, resp)


# ---------------------------------------------------------------------
# TCP daemon (skvbc_replica process model)
# ---------------------------------------------------------------------

class HelperDaemon:
    def __init__(self, port: int, host: str = "127.0.0.1",
                 helper_id: str = "h0", strategy: str = "honest"):
        self.server = HelperServer(helper_id, strategy)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._running = False
        self._accept_thread: Optional[threading.Thread] = None

    def start(self) -> "HelperDaemon":
        self._running = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="offload-helper-accept",
            daemon=True)
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             name="offload-helper-conn",
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                req = proto.recv_frame(conn)
                if req is None:
                    return
                try:
                    resp = self.server.handle(req)
                except HelperCrashed:
                    return          # drop the connection mid-lease
                proto.send_frame(conn, resp)
        except (OSError, proto.ProtocolError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="tpubft crypto-offload helper daemon (non-voting, "
                    "untrusted — every answer is re-verified on-replica)")
    p.add_argument("--port", type=int, default=7700)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--id", default="h0", help="helper id (breaker name)")
    p.add_argument("--strategy", default="honest",
                   choices=sorted(STRATEGIES),
                   help="byzantine test behavior (default: honest)")
    p.add_argument("--log-level", default="INFO")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = make_parser().parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    daemon = HelperDaemon(args.port, args.host, args.id,
                          args.strategy).start()
    log.info("offload helper %s listening on %s:%d (strategy=%s)",
             args.id, args.host, daemon.port, args.strategy)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        daemon.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
