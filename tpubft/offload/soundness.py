"""On-replica soundness checks for leased crypto work (the "2G2T"
constant-size MSM-outsourcing verification, arXiv 2602.23464).

The helper is UNTRUSTED: nothing it returns may influence a verdict
until it survives one of these checks. All three checks share the same
shape — fold the whole lease into ONE aggregate statement with
Fiat-Shamir coefficients drawn AFTER the helper committed to its
answer, then verify the aggregate at constant pairing/launch cost:

  * BLS threshold combine: the returned per-segment points C_s must be
    valid signatures on their slot digests under the MASTER public key
    (BLS uniqueness: for each digest there is exactly one valid
    signature, so check-pass ⟹ C_s is byte-identical to what an honest
    local combine over good shares produces). One 128-bit RLC over the
    segments → two G1 MSMs + ONE 2-pairing check, regardless of how
    many shares the helper combined.

  * multisig-BLS sum: same fold, but each segment verifies against the
    sum of its CONTRIBUTORS' G2 keys, so the H(d)-side cannot collapse
    to a single pairing — it is one Miller batch of 1+nsegs pairings,
    still constant per segment and independent of share count.

  * ECDSA RLC: the helper returns per-item verdict bits; the replica
    re-folds the ACCEPTED subset with its OWN Fiat-Shamir coefficients
    in one `_rlc_launch` (2^-128 soundness), and re-checks the
    rejected-but-plausible items on the batched host engine. A helper
    lying in either direction (accepting a forgery, rejecting a valid
    signature) is caught.

Check-failure is AMBIGUOUS for the BLS shapes — the shares themselves
may be Byzantine (then even an honest helper's combine fails the
pairing). The pool layer disambiguates by re-running locally once and
comparing: equal ⟹ helper honest, the shares are bad (the local result
flows to the normal bad-share identification path, byte-identical to
offload-off); different ⟹ the helper lied.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from tpubft.crypto import bls12381 as bls

DOMAIN_COMBINE = b"offload-2g2t-combine"
DOMAIN_SUM = b"offload-2g2t-sum"


def decompress_points(pts: Sequence[bytes]) -> Optional[List[object]]:
    """Helper-returned compressed points -> affine points; None when
    any point is undecodable or outside the G1 subgroup (a helper that
    returns such bytes is lying, not merely wrong)."""
    out = []
    for p in pts:
        try:
            pt = bls.g1_decompress(p)
        except ValueError:
            return None
        if pt is None:      # infinity is never a valid combined sig
            return None
        out.append(pt)
    return out


def check_bls_combine(master_pk, digests: Sequence[bytes],
                      points: Sequence[object]) -> bool:
    """e(Σ z_s·C_s, −g2) · e(Σ z_s·H(d_s), master_pk) == 1 with the
    coefficients bound to the helper's RETURNED points (it committed
    before the draw — a cancellation between wrong points survives with
    probability ~2^-128)."""
    if not points:
        return True
    if len(points) != len(digests):
        return False
    ctx = (DOMAIN_COMBINE + bls.g2_compress(master_pk)
           + b"".join(d + bls.g1_compress(pt)
                      for d, pt in zip(digests, points)))
    zs = bls._rlc_scalars(len(points), ctx)
    agg_sig = bls.g1_msm(list(points), zs)
    agg_h = bls.g1_msm([bls.hash_to_g1(d) for d in digests], zs)
    return bls.pairing_check([(agg_sig, bls.g2_neg(bls.G2_GEN)),
                              (agg_h, master_pk)])


def check_bls_sum(meta: Sequence[Tuple[bytes, object]],
                  points: Sequence[object]) -> bool:
    """meta = [(digest, agg_pk_g2)] per segment: one Miller batch of
    e(Σ z_s·S_s, −g2) · Π e(z_s·H(d_s), apk_s) == 1."""
    if not points:
        return True
    if len(points) != len(meta):
        return False
    ctx = (DOMAIN_SUM
           + b"".join(d + bls.g2_compress(apk) + bls.g1_compress(pt)
                      for (d, apk), pt in zip(meta, points)))
    zs = bls._rlc_scalars(len(points), ctx)
    agg_sig = bls.g1_msm(list(points), zs)
    pairs = [(agg_sig, bls.g2_neg(bls.G2_GEN))]
    for z, (d, apk) in zip(zs, meta):
        pairs.append((bls.g1_mul(bls.hash_to_g1(d), z), apk))
    return bls.pairing_check(pairs)


def check_ecdsa_verdicts(curve: str, items, prep, bits: Sequence[bool]
                         ) -> Optional[List[bool]]:
    """Verify helper verdict bits against one local RLC fold; returns
    the confirmed verdict list (byte-identical to a full local
    `rlc_verify_batch`) or None when the helper LIED. `prep` is the
    replica's own PreparedRlcBatch over `items` — the helper never
    chooses the fold coefficients."""
    from tpubft.crypto import scalar as _scalar
    from tpubft.ops import ecdsa as ops_ecdsa
    accepted = [i for i, b in enumerate(bits) if b]
    # an honest helper never accepts an item the host prechecks already
    # reject (malformed sig/point): accepting one is a lie, full stop
    if any(not prep.host_valid[i] for i in accepted):
        return None
    if accepted:
        # ONE aggregate launch over the accepted subset with OUR
        # coefficients: passes iff every accepted item verifies
        if not ops_ecdsa._rlc_launch(curve, prep, accepted):
            return None
    rejected = [i for i, b in enumerate(bits)
                if not b and prep.host_valid[i]]
    if rejected:
        # a lying-REJECT starves liveness instead of forging — re-check
        # the plausible rejects on the batched host engine (under
        # honest helpers this subset is exactly the genuinely-bad
        # traffic, which local-only verification would also pay for)
        redo = _scalar.ecdsa_verify_batch(
            [(items[i][2], items[i][0], items[i][1]) for i in rejected],
            curve)
        if any(redo):
            return None
    return [bool(b) and bool(prep.host_valid[i])
            for i, b in enumerate(bits)]
