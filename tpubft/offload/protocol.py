"""Lease wire protocol for the crypto-offload tier.

Length-prefixed frames (the thinreplica transport idiom: 4-byte LE u32
length, oversize frames rejected) carrying one lease request or
response each. The encodings are deliberately dumb — fixed-width
headers + concatenated compressed points — because the helper must be
implementable without any tpubft protocol state: it sees points and
scalars, never consensus messages.

Call-site confinement: everything in this module (and the raw socket
plumbing in pool/helper) is tpubft/offload/-only, enforced by the
tpulint `offload-seam` pass. Crypto call sites reach the tier through
the verified high-level API in `tpubft.offload.pool`.
"""
from __future__ import annotations

import socket
import struct
from typing import List, Optional, Sequence, Tuple

MAX_FRAME = 1 << 22          # same bound as the thinreplica transport

# lease kinds
KIND_BLS_COMBINE = 1         # threshold Lagrange combine, per segment
KIND_BLS_SUM = 2             # multisig unweighted G1 sum, per segment
KIND_ECDSA_RLC = 3           # ECDSA verdict bits, per item

KIND_NAMES = {KIND_BLS_COMBINE: "bls-combine", KIND_BLS_SUM: "bls-sum",
              KIND_ECDSA_RLC: "ecdsa-rlc"}

ST_OK = 0
ST_ERR = 1

G1_LEN = 48                  # compressed G1 point

_CURVE_IDS = {"secp256k1": 0, "secp256r1": 1}
_CURVE_BY_ID = {v: k for k, v in _CURVE_IDS.items()}


class ProtocolError(ValueError):
    """Malformed frame/payload — at the replica side this is evidence
    of a lying helper, not a transport fault."""


# ---------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------

def send_frame(sock: socket.socket, body: bytes) -> None:
    if len(body) > MAX_FRAME:
        raise ProtocolError(f"frame too large ({len(body)} bytes)")
    sock.sendall(struct.pack("<I", len(body)) + body)


def recv_frame(sock: socket.socket) -> Optional[bytes]:
    """One frame, or None on clean EOF. Raises on oversize/truncation
    (socket timeouts propagate as socket.timeout)."""
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = struct.unpack("<I", hdr)
    if n > MAX_FRAME:
        raise ProtocolError(f"oversize frame ({n} bytes)")
    body = _recv_exact(sock, n)
    if body is None:
        raise ProtocolError("truncated frame")
    return body


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


# ---------------------------------------------------------------------
# request / response envelopes
# ---------------------------------------------------------------------

def encode_request(lease_id: int, kind: int, deadline_ms: int,
                   payload: bytes) -> bytes:
    return struct.pack("<QBI", lease_id, kind, deadline_ms) + payload


def decode_request(body: bytes) -> Tuple[int, int, int, bytes]:
    if len(body) < 13:
        raise ProtocolError("short lease request")
    lease_id, kind, deadline_ms = struct.unpack_from("<QBI", body, 0)
    return lease_id, kind, deadline_ms, body[13:]


def encode_response(lease_id: int, status: int, payload: bytes) -> bytes:
    return struct.pack("<QB", lease_id, status) + payload


def decode_response(body: bytes) -> Tuple[int, int, bytes]:
    if len(body) < 9:
        raise ProtocolError("short lease response")
    lease_id, status = struct.unpack_from("<QB", body, 0)
    return lease_id, status, body[9:]


# ---------------------------------------------------------------------
# BLS combine / sum payloads: segments of identified compressed shares
# ---------------------------------------------------------------------

def encode_bls_segments(segments: Sequence[Tuple[Sequence[int],
                                                 Sequence[bytes]]]) -> bytes:
    """[(ids, [48B compressed G1 shares])] — for KIND_BLS_SUM the ids
    still travel (the helper ignores them; keeping one encoding keeps
    the helper dumb)."""
    out = [struct.pack("<I", len(segments))]
    for ids, pts in segments:
        if len(ids) != len(pts):
            raise ProtocolError("ids/points length mismatch")
        out.append(struct.pack("<I", len(ids)))
        out.append(struct.pack(f"<{len(ids)}I", *ids) if ids else b"")
        for p in pts:
            if len(p) != G1_LEN:
                raise ProtocolError("bad G1 share length")
            out.append(p)
    return b"".join(out)


def decode_bls_segments(payload: bytes
                        ) -> List[Tuple[List[int], List[bytes]]]:
    try:
        (nsegs,) = struct.unpack_from("<I", payload, 0)
        off = 4
        segs: List[Tuple[List[int], List[bytes]]] = []
        for _ in range(nsegs):
            (k,) = struct.unpack_from("<I", payload, off)
            off += 4
            ids = list(struct.unpack_from(f"<{k}I", payload, off))
            off += 4 * k
            pts = []
            for _ in range(k):
                pts.append(payload[off:off + G1_LEN])
                off += G1_LEN
                if len(pts[-1]) != G1_LEN:
                    raise ProtocolError("truncated share")
            segs.append((ids, pts))
        if off != len(payload):
            raise ProtocolError("trailing bytes in segments payload")
        return segs
    except struct.error as e:
        raise ProtocolError(str(e)) from e


def encode_points(pts: Sequence[bytes]) -> bytes:
    for p in pts:
        if len(p) != G1_LEN:
            raise ProtocolError("bad G1 point length")
    return b"".join(pts)


def decode_points(payload: bytes, expect: int) -> Optional[List[bytes]]:
    """Fixed-count compressed points; None (not an exception) on a
    shape mismatch — the caller classifies that as a lying helper."""
    if len(payload) != expect * G1_LEN:
        return None
    return [payload[i * G1_LEN:(i + 1) * G1_LEN] for i in range(expect)]


# ---------------------------------------------------------------------
# ECDSA payloads: (digest, sig, pk) items -> verdict bytes
# ---------------------------------------------------------------------

def encode_ecdsa_items(curve: str,
                       items: Sequence[Tuple[bytes, bytes, bytes]]) -> bytes:
    out = [struct.pack("<BI", _CURVE_IDS[curve], len(items))]
    for d, s, pk in items:
        out.append(struct.pack("<III", len(d), len(s), len(pk)))
        out.extend((d, s, pk))
    return b"".join(out)


def decode_ecdsa_items(payload: bytes
                       ) -> Tuple[str, List[Tuple[bytes, bytes, bytes]]]:
    try:
        curve_id, n = struct.unpack_from("<BI", payload, 0)
        curve = _CURVE_BY_ID.get(curve_id)
        if curve is None:
            raise ProtocolError(f"unknown curve id {curve_id}")
        off = 5
        items = []
        for _ in range(n):
            dl, sl, pl = struct.unpack_from("<III", payload, off)
            off += 12
            if off + dl + sl + pl > len(payload):
                raise ProtocolError("truncated ecdsa item")
            d = payload[off:off + dl]; off += dl
            s = payload[off:off + sl]; off += sl
            pk = payload[off:off + pl]; off += pl
            items.append((d, s, pk))
        if off != len(payload):
            raise ProtocolError("trailing bytes in ecdsa payload")
        return curve, items
    except struct.error as e:
        raise ProtocolError(str(e)) from e


def encode_verdicts(bits: Sequence[bool]) -> bytes:
    return bytes(1 if b else 0 for b in bits)


def decode_verdicts(payload: bytes, expect: int) -> Optional[List[bool]]:
    if len(payload) != expect or any(b > 1 for b in payload):
        return None
    return [bool(b) for b in payload]
