// aescbc — AES-256-CBC for secrets-at-rest (the OpenSSL-AES role of the
// reference's secretsmanager, /root/reference/secretsmanager/src/aes.cpp),
// implemented natively so key material never round-trips through slow
// pure-Python byte loops. C ABI, consumed via ctypes.
//
// Standard FIPS-197 AES with a 14-round 256-bit key schedule; CBC mode
// with caller-supplied IV. Padding/integrity live in the Python layer
// (PKCS#7 + HMAC-SHA256 encrypt-then-MAC).

#include <cstdint>
#include <cstring>

namespace {

const uint8_t SBOX[256] = {
    0x63,0x7c,0x77,0x7b,0xf2,0x6b,0x6f,0xc5,0x30,0x01,0x67,0x2b,0xfe,0xd7,0xab,0x76,
    0xca,0x82,0xc9,0x7d,0xfa,0x59,0x47,0xf0,0xad,0xd4,0xa2,0xaf,0x9c,0xa4,0x72,0xc0,
    0xb7,0xfd,0x93,0x26,0x36,0x3f,0xf7,0xcc,0x34,0xa5,0xe5,0xf1,0x71,0xd8,0x31,0x15,
    0x04,0xc7,0x23,0xc3,0x18,0x96,0x05,0x9a,0x07,0x12,0x80,0xe2,0xeb,0x27,0xb2,0x75,
    0x09,0x83,0x2c,0x1a,0x1b,0x6e,0x5a,0xa0,0x52,0x3b,0xd6,0xb3,0x29,0xe3,0x2f,0x84,
    0x53,0xd1,0x00,0xed,0x20,0xfc,0xb1,0x5b,0x6a,0xcb,0xbe,0x39,0x4a,0x4c,0x58,0xcf,
    0xd0,0xef,0xaa,0xfb,0x43,0x4d,0x33,0x85,0x45,0xf9,0x02,0x7f,0x50,0x3c,0x9f,0xa8,
    0x51,0xa3,0x40,0x8f,0x92,0x9d,0x38,0xf5,0xbc,0xb6,0xda,0x21,0x10,0xff,0xf3,0xd2,
    0xcd,0x0c,0x13,0xec,0x5f,0x97,0x44,0x17,0xc4,0xa7,0x7e,0x3d,0x64,0x5d,0x19,0x73,
    0x60,0x81,0x4f,0xdc,0x22,0x2a,0x90,0x88,0x46,0xee,0xb8,0x14,0xde,0x5e,0x0b,0xdb,
    0xe0,0x32,0x3a,0x0a,0x49,0x06,0x24,0x5c,0xc2,0xd3,0xac,0x62,0x91,0x95,0xe4,0x79,
    0xe7,0xc8,0x37,0x6d,0x8d,0xd5,0x4e,0xa9,0x6c,0x56,0xf4,0xea,0x65,0x7a,0xae,0x08,
    0xba,0x78,0x25,0x2e,0x1c,0xa6,0xb4,0xc6,0xe8,0xdd,0x74,0x1f,0x4b,0xbd,0x8b,0x8a,
    0x70,0x3e,0xb5,0x66,0x48,0x03,0xf6,0x0e,0x61,0x35,0x57,0xb9,0x86,0xc1,0x1d,0x9e,
    0xe1,0xf8,0x98,0x11,0x69,0xd9,0x8e,0x94,0x9b,0x1e,0x87,0xe9,0xce,0x55,0x28,0xdf,
    0x8c,0xa1,0x89,0x0d,0xbf,0xe6,0x42,0x68,0x41,0x99,0x2d,0x0f,0xb0,0x54,0xbb,0x16};

uint8_t INV_SBOX[256];
struct InvInit {
  InvInit() { for (int i = 0; i < 256; i++) INV_SBOX[SBOX[i]] = (uint8_t)i; }
} inv_init_;

const uint8_t RCON[15] = {0x01,0x02,0x04,0x08,0x10,0x20,0x40,0x80,
                          0x1b,0x36,0x6c,0xd8,0xab,0x4d,0x9a};

inline uint8_t xtime(uint8_t x) {
  return (uint8_t)((x << 1) ^ ((x >> 7) * 0x1b));
}

inline uint8_t gmul(uint8_t a, uint8_t b) {
  uint8_t p = 0;
  for (int i = 0; i < 8; i++) {
    if (b & 1) p ^= a;
    a = xtime(a);
    b >>= 1;
  }
  return p;
}

struct Aes256 {
  uint8_t rk[15][16];  // round keys

  explicit Aes256(const uint8_t key[32]) {
    uint8_t w[60][4];
    memcpy(w, key, 32);
    for (int i = 8; i < 60; i++) {
      uint8_t t[4] = {w[i-1][0], w[i-1][1], w[i-1][2], w[i-1][3]};
      if (i % 8 == 0) {
        uint8_t tmp = t[0];
        t[0] = (uint8_t)(SBOX[t[1]] ^ RCON[i/8 - 1]);
        t[1] = SBOX[t[2]]; t[2] = SBOX[t[3]]; t[3] = SBOX[tmp];
      } else if (i % 8 == 4) {
        for (int k = 0; k < 4; k++) t[k] = SBOX[t[k]];
      }
      for (int k = 0; k < 4; k++) w[i][k] = (uint8_t)(w[i-8][k] ^ t[k]);
    }
    memcpy(rk, w, 240);
  }

  void encrypt_block(uint8_t s[16]) const {
    add_rk(s, 0);
    for (int r = 1; r < 14; r++) {
      sub_shift(s);
      mix(s);
      add_rk(s, r);
    }
    sub_shift(s);
    add_rk(s, 14);
  }

  void decrypt_block(uint8_t s[16]) const {
    add_rk(s, 14);
    inv_sub_shift(s);
    for (int r = 13; r >= 1; r--) {
      add_rk(s, r);
      inv_mix(s);
      inv_sub_shift(s);
    }
    add_rk(s, 0);
  }

 private:
  void add_rk(uint8_t s[16], int r) const {
    for (int i = 0; i < 16; i++) s[i] ^= rk[r][i];
  }

  static void sub_shift(uint8_t s[16]) {
    uint8_t t[16];
    // SubBytes + ShiftRows fused (column-major state layout)
    static const int M[16] = {0,5,10,15,4,9,14,3,8,13,2,7,12,1,6,11};
    for (int i = 0; i < 16; i++) t[i] = SBOX[s[M[i]]];
    memcpy(s, t, 16);
  }

  static void inv_sub_shift(uint8_t s[16]) {
    uint8_t t[16];
    static const int M[16] = {0,13,10,7,4,1,14,11,8,5,2,15,12,9,6,3};
    for (int i = 0; i < 16; i++) t[i] = INV_SBOX[s[M[i]]];
    memcpy(s, t, 16);
  }

  static void mix(uint8_t s[16]) {
    for (int c = 0; c < 4; c++) {
      uint8_t* p = s + 4 * c;
      uint8_t a0 = p[0], a1 = p[1], a2 = p[2], a3 = p[3];
      p[0] = (uint8_t)(xtime(a0) ^ (xtime(a1) ^ a1) ^ a2 ^ a3);
      p[1] = (uint8_t)(a0 ^ xtime(a1) ^ (xtime(a2) ^ a2) ^ a3);
      p[2] = (uint8_t)(a0 ^ a1 ^ xtime(a2) ^ (xtime(a3) ^ a3));
      p[3] = (uint8_t)((xtime(a0) ^ a0) ^ a1 ^ a2 ^ xtime(a3));
    }
  }

  static void inv_mix(uint8_t s[16]) {
    for (int c = 0; c < 4; c++) {
      uint8_t* p = s + 4 * c;
      uint8_t a0 = p[0], a1 = p[1], a2 = p[2], a3 = p[3];
      p[0] = (uint8_t)(gmul(a0,14) ^ gmul(a1,11) ^ gmul(a2,13) ^ gmul(a3,9));
      p[1] = (uint8_t)(gmul(a0,9) ^ gmul(a1,14) ^ gmul(a2,11) ^ gmul(a3,13));
      p[2] = (uint8_t)(gmul(a0,13) ^ gmul(a1,9) ^ gmul(a2,14) ^ gmul(a3,11));
      p[3] = (uint8_t)(gmul(a0,11) ^ gmul(a1,13) ^ gmul(a2,9) ^ gmul(a3,14));
    }
  }
};

}  // namespace

extern "C" {

// data length must be a multiple of 16 (padding done by the caller).
int aes256_cbc_encrypt(const uint8_t key[32], const uint8_t iv[16],
                       const uint8_t* in, uint8_t* out, uint32_t len) {
  if (len % 16) return -1;
  Aes256 aes(key);
  uint8_t chain[16];
  memcpy(chain, iv, 16);
  for (uint32_t off = 0; off < len; off += 16) {
    uint8_t block[16];
    for (int i = 0; i < 16; i++) block[i] = (uint8_t)(in[off+i] ^ chain[i]);
    aes.encrypt_block(block);
    memcpy(out + off, block, 16);
    memcpy(chain, block, 16);
  }
  return 0;
}

int aes256_cbc_decrypt(const uint8_t key[32], const uint8_t iv[16],
                       const uint8_t* in, uint8_t* out, uint32_t len) {
  if (len % 16) return -1;
  Aes256 aes(key);
  uint8_t chain[16];
  memcpy(chain, iv, 16);
  for (uint32_t off = 0; off < len; off += 16) {
    uint8_t block[16];
    memcpy(block, in + off, 16);
    uint8_t next_chain[16];
    memcpy(next_chain, block, 16);
    aes.decrypt_block(block);
    for (int i = 0; i < 16; i++) out[off+i] = (uint8_t)(block[i] ^ chain[i]);
    memcpy(chain, next_chain, 16);
  }
  return 0;
}

}  // extern "C"
