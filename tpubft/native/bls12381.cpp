// BLS12-381 pairing + group arithmetic — native engine.
//
// Plays the role RELIC plays in the reference (threshsign/src/bls/relic/:
// the pairing and exponentiation core under BlsThresholdVerifier /
// BlsBatchVerifier). This is a from-scratch implementation of the SAME
// algorithms as the project's pure-Python golden model
// (tpubft/crypto/bls12381.py) — tower Fp2/Fp6/Fp12 with xi = u+1, ate
// Miller loop over the D-type twist, signature checks as multi-pairing
// products — with the two standard speedups the Python model omits:
//   * Montgomery-form 6x64-limb Fp arithmetic (CIOS multiply);
//   * fast final exponentiation: easy part (p^6-1)(p^2+1), then the
//     hard part via the numerically VERIFIED identity
//       3*(p^4 - p^2 + 1)/r = (x-1)^2 * (x+p) * (x^2 + p^2 - 1) + 3
//     (cubing the output is sound for equality-with-one checks: the
//     pre-image lies in the order-r subgroup and r is a prime != 3).
//
// The ctypes ABI at the bottom exchanges raw big-endian affine
// coordinates; all validation beyond range checks stays in Python.

#include <cstdint>
#include <cstring>

using u64 = uint64_t;
using u128 = unsigned __int128;

// generated from tpubft/crypto/bls12381.py (python golden model)
static const uint64_t P_LIMBS[6] = {0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL, 0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL};
static const uint64_t N0INV = 0x89f3fffcfffcfffdULL;
static const uint64_t R2C[6] = {0xf4df1f341c341746ULL, 0x0a76e6a609d104f1ULL, 0x8de5476c4c95b6d5ULL, 0x67eb88a9939d83c0ULL, 0x9a793e85b519952dULL, 0x11988fe592cae3aaULL};
static const uint64_t ONE_M[6] = {0x760900000002fffdULL, 0xebf4000bc40c0002ULL, 0x5f48985753c758baULL, 0x77ce585370525745ULL, 0x5c071a97a256ec6dULL, 0x15f65ec3fa80e493ULL};
static const uint64_t G1C1_0[6] = {0x07089552b319d465ULL, 0xc6695f92b50a8313ULL, 0x97e83cccd117228fULL, 0xa35baecab2dc29eeULL, 0x1ce393ea5daace4dULL, 0x08f2220fb0fb66ebULL};
static const uint64_t G1C1_1[6] = {0xb2f66aad4ce5d646ULL, 0x5842a06bfc497cecULL, 0xcf4895d42599d394ULL, 0xc11b9cba40a8e8d0ULL, 0x2e3813cbe5a0de89ULL, 0x110eefda88847fafULL};
static const uint64_t G1C2_0[6] = {0x0000000000000000ULL, 0x0000000000000000ULL, 0x0000000000000000ULL, 0x0000000000000000ULL, 0x0000000000000000ULL, 0x0000000000000000ULL};
static const uint64_t G1C2_1[6] = {0xcd03c9e48671f071ULL, 0x5dab22461fcda5d2ULL, 0x587042afd3851b95ULL, 0x8eb60ebe01bacb9eULL, 0x03f97d6e83d050d2ULL, 0x18f0206554638741ULL};
static const uint64_t G1C3_0[6] = {0x7bcfa7a25aa30fdaULL, 0xdc17dec12a927e7cULL, 0x2f088dd86b4ebef1ULL, 0xd1ca2087da74d4a7ULL, 0x2da2596696cebc1dULL, 0x0e2b7eedbbfd87d2ULL};
static const uint64_t G1C3_1[6] = {0x7bcfa7a25aa30fdaULL, 0xdc17dec12a927e7cULL, 0x2f088dd86b4ebef1ULL, 0xd1ca2087da74d4a7ULL, 0x2da2596696cebc1dULL, 0x0e2b7eedbbfd87d2ULL};
static const uint64_t G1C4_0[6] = {0x890dc9e4867545c3ULL, 0x2af322533285a5d5ULL, 0x50880866309b7e2cULL, 0xa20d1b8c7e881024ULL, 0x14e4f04fe2db9068ULL, 0x14e56d3f1564853aULL};
static const uint64_t G1C4_1[6] = {0x0000000000000000ULL, 0x0000000000000000ULL, 0x0000000000000000ULL, 0x0000000000000000ULL, 0x0000000000000000ULL, 0x0000000000000000ULL};
static const uint64_t G1C5_0[6] = {0x82d83cf50dbce43fULL, 0xa2813e53df9d018fULL, 0xc6f0caa53c65e181ULL, 0x7525cf528d50fe95ULL, 0x4a85ed50f4798a6bULL, 0x171da0fd6cf8eebdULL};
static const uint64_t G1C5_1[6] = {0x3726c30af242c66cULL, 0x7c2ac1aad1b6fe70ULL, 0xa04007fbba4b14a2ULL, 0xef517c3266341429ULL, 0x0095ba654ed2226bULL, 0x02e370eccc86f7ddULL};
static const uint64_t G2C1_0[6] = {0xecfb361b798dba3aULL, 0xc100ddb891865a2cULL, 0x0ec08ff1232bda8eULL, 0xd5c13cc6f1ca4721ULL, 0x47222a47bf7b5c04ULL, 0x0110f184e51c5f59ULL};
static const uint64_t G2C2_0[6] = {0x30f1361b798a64e8ULL, 0xf3b8ddab7ece5a2aULL, 0x16a8ca3ac61577f7ULL, 0xc26a2ff874fd029bULL, 0x3636b76660701c6eULL, 0x051ba4ab241b6160ULL};
static const uint64_t G2C3_0[6] = {0x43f5fffffffcaaaeULL, 0x32b7fff2ed47fffdULL, 0x07e83a49a2e99d69ULL, 0xeca8f3318332bb7aULL, 0xef148d1ea0f4c069ULL, 0x040ab3263eff0206ULL};
static const uint64_t G2C4_0[6] = {0xcd03c9e48671f071ULL, 0x5dab22461fcda5d2ULL, 0x587042afd3851b95ULL, 0x8eb60ebe01bacb9eULL, 0x03f97d6e83d050d2ULL, 0x18f0206554638741ULL};
static const uint64_t G2C5_0[6] = {0x890dc9e4867545c3ULL, 0x2af322533285a5d5ULL, 0x50880866309b7e2cULL, 0xa20d1b8c7e881024ULL, 0x14e4f04fe2db9068ULL, 0x14e56d3f1564853aULL};

static const u64 X_ABS = 0xd201000000010000ULL;  // |x|, x negative
static u64 SQRT_EXP[6];                          // (p+1)/4, set in ensure_init
static uint8_t P_BE[48], P_HALF_BE[48];          // p and (p-1)/2, big-endian

// ---------------- Fp (Montgomery form) ----------------

struct Fp { u64 l[6]; };

static inline bool fp_is_zero(const Fp& a) {
    u64 acc = 0;
    for (int i = 0; i < 6; i++) acc |= a.l[i];
    return acc == 0;
}

static inline bool fp_eq(const Fp& a, const Fp& b) {
    u64 acc = 0;
    for (int i = 0; i < 6; i++) acc |= a.l[i] ^ b.l[i];
    return acc == 0;
}

static inline int fp_cmp_p(const u64* a) {  // a >= P ?
    for (int i = 5; i >= 0; i--) {
        if (a[i] < P_LIMBS[i]) return -1;
        if (a[i] > P_LIMBS[i]) return 1;
    }
    return 0;
}

static inline void fp_sub_p(u64* a) {
    u128 borrow = 0;
    for (int i = 0; i < 6; i++) {
        u128 d = (u128)a[i] - P_LIMBS[i] - borrow;
        a[i] = (u64)d;
        borrow = (d >> 64) & 1;
    }
}

static void fp_add(Fp& r, const Fp& a, const Fp& b) {
    u128 c = 0;
    for (int i = 0; i < 6; i++) {
        c += (u128)a.l[i] + b.l[i];
        r.l[i] = (u64)c;
        c >>= 64;
    }
    if (c || fp_cmp_p(r.l) >= 0) fp_sub_p(r.l);
}

static void fp_sub(Fp& r, const Fp& a, const Fp& b) {
    u128 borrow = 0;
    for (int i = 0; i < 6; i++) {
        u128 d = (u128)a.l[i] - b.l[i] - borrow;
        r.l[i] = (u64)d;
        borrow = (d >> 64) & 1;
    }
    if (borrow) {  // add P back
        u128 c = 0;
        for (int i = 0; i < 6; i++) {
            c += (u128)r.l[i] + P_LIMBS[i];
            r.l[i] = (u64)c;
            c >>= 64;
        }
    }
}

static void fp_neg(Fp& r, const Fp& a) {
    if (fp_is_zero(a)) { r = a; return; }
    u128 borrow = 0;
    for (int i = 0; i < 6; i++) {
        u128 d = (u128)P_LIMBS[i] - a.l[i] - borrow;
        r.l[i] = (u64)d;
        borrow = (d >> 64) & 1;
    }
}

// CIOS Montgomery multiplication: r = a*b*R^-1 mod p
static void fp_mul(Fp& r, const Fp& a, const Fp& b) {
    u64 t[8] = {0, 0, 0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 6; i++) {
        u128 c = 0;
        for (int j = 0; j < 6; j++) {
            c += (u128)t[j] + (u128)a.l[j] * b.l[i];
            t[j] = (u64)c;
            c >>= 64;
        }
        c += t[6];
        t[6] = (u64)c;
        t[7] = (u64)(c >> 64);
        u64 m = t[0] * N0INV;
        c = (u128)t[0] + (u128)m * P_LIMBS[0];
        c >>= 64;
        for (int j = 1; j < 6; j++) {
            c += (u128)t[j] + (u128)m * P_LIMBS[j];
            t[j - 1] = (u64)c;
            c >>= 64;
        }
        c += t[6];
        t[5] = (u64)c;
        t[6] = t[7] + (u64)(c >> 64);
    }
    if (t[6] || fp_cmp_p(t) >= 0) fp_sub_p(t);
    memcpy(r.l, t, 48);
}

static inline void fp_sqr(Fp& r, const Fp& a) { fp_mul(r, a, a); }

static void fp_pow(Fp& r, const Fp& a, const u64* e, int nlimbs) {
    Fp result;
    memcpy(result.l, ONE_M, 48);
    Fp base = a;
    for (int i = 0; i < nlimbs; i++) {
        u64 w = e[i];
        for (int b = 0; b < 64; b++) {
            if (i * 64 + b >= nlimbs * 64) break;
            if (w & 1) fp_mul(result, result, base);
            fp_sqr(base, base);
            w >>= 1;
        }
    }
    r = result;
}

static void fp_inv(Fp& r, const Fp& a) {  // a^(p-2)
    u64 e[6];
    memcpy(e, P_LIMBS, 48);
    // P - 2 (no borrow past limb 0: low limb is ...aaab)
    e[0] -= 2;
    fp_pow(r, a, e, 6);
}

static void fp_from_be(Fp& r, const uint8_t* be48) {
    Fp raw;
    for (int i = 0; i < 6; i++) {
        u64 w = 0;
        for (int j = 0; j < 8; j++) w = (w << 8) | be48[(5 - i) * 8 + j];
        raw.l[i] = w;
    }
    Fp r2;
    memcpy(r2.l, R2C, 48);
    fp_mul(r, raw, r2);               // to Montgomery form
}

static void fp_to_be(uint8_t* be48, const Fp& a) {
    Fp one = {{1, 0, 0, 0, 0, 0}};
    Fp plain;
    fp_mul(plain, a, one);            // from Montgomery form
    for (int i = 0; i < 6; i++) {
        u64 w = plain.l[5 - i];
        for (int j = 0; j < 8; j++) {
            be48[i * 8 + j] = (uint8_t)(w >> (56 - 8 * j));
        }
    }
}

static Fp FP_ZERO_C, FP_ONE_C;

// ---------------- Fp2 = Fp[u]/(u^2+1) ----------------

struct Fp2 { Fp c0, c1; };

static void fp2_add(Fp2& r, const Fp2& a, const Fp2& b) {
    fp_add(r.c0, a.c0, b.c0);
    fp_add(r.c1, a.c1, b.c1);
}

static void fp2_sub(Fp2& r, const Fp2& a, const Fp2& b) {
    fp_sub(r.c0, a.c0, b.c0);
    fp_sub(r.c1, a.c1, b.c1);
}

static void fp2_neg(Fp2& r, const Fp2& a) {
    fp_neg(r.c0, a.c0);
    fp_neg(r.c1, a.c1);
}

static void fp2_mul(Fp2& r, const Fp2& a, const Fp2& b) {
    Fp t0, t1, t2, s0, s1;
    fp_mul(t0, a.c0, b.c0);
    fp_mul(t1, a.c1, b.c1);
    fp_add(s0, a.c0, a.c1);
    fp_add(s1, b.c0, b.c1);
    fp_mul(t2, s0, s1);
    fp_sub(r.c0, t0, t1);
    fp_sub(t2, t2, t0);
    fp_sub(r.c1, t2, t1);
}

static void fp2_sqr(Fp2& r, const Fp2& a) {
    Fp t0, t1, t2;
    fp_add(t0, a.c0, a.c1);
    fp_sub(t1, a.c0, a.c1);
    fp_mul(t2, a.c0, a.c1);
    fp_mul(r.c0, t0, t1);
    fp_add(r.c1, t2, t2);
}

static void fp2_conj(Fp2& r, const Fp2& a) {
    r.c0 = a.c0;
    fp_neg(r.c1, a.c1);
}

static void fp2_inv(Fp2& r, const Fp2& a) {
    Fp n, t0, t1;
    fp_sqr(t0, a.c0);
    fp_sqr(t1, a.c1);
    fp_add(n, t0, t1);
    fp_inv(n, n);
    fp_mul(r.c0, a.c0, n);
    fp_mul(t0, a.c1, n);
    fp_neg(r.c1, t0);
}

static void fp2_mul_fp(Fp2& r, const Fp2& a, const Fp& k) {
    fp_mul(r.c0, a.c0, k);
    fp_mul(r.c1, a.c1, k);
}

static void fp2_mul_xi(Fp2& r, const Fp2& a) {  // * (u+1)
    Fp t0, t1;
    fp_sub(t0, a.c0, a.c1);
    fp_add(t1, a.c0, a.c1);
    r.c0 = t0;
    r.c1 = t1;
}

static bool fp2_is_zero(const Fp2& a) {
    return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}

static bool fp2_eq(const Fp2& a, const Fp2& b) {
    return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1);
}

static Fp2 FP2_ZERO_C, FP2_ONE_C;

// ---------------- Fp6 = Fp2[v]/(v^3 - (u+1)) ----------------

struct Fp6 { Fp2 c0, c1, c2; };

static void fp6_add(Fp6& r, const Fp6& a, const Fp6& b) {
    fp2_add(r.c0, a.c0, b.c0);
    fp2_add(r.c1, a.c1, b.c1);
    fp2_add(r.c2, a.c2, b.c2);
}

static void fp6_sub(Fp6& r, const Fp6& a, const Fp6& b) {
    fp2_sub(r.c0, a.c0, b.c0);
    fp2_sub(r.c1, a.c1, b.c1);
    fp2_sub(r.c2, a.c2, b.c2);
}

static void fp6_neg(Fp6& r, const Fp6& a) {
    fp2_neg(r.c0, a.c0);
    fp2_neg(r.c1, a.c1);
    fp2_neg(r.c2, a.c2);
}

static void fp6_mul(Fp6& r, const Fp6& a, const Fp6& b) {
    Fp2 t0, t1, t2, s0, s1, u0, u1;
    fp2_mul(t0, a.c0, b.c0);
    fp2_mul(t1, a.c1, b.c1);
    fp2_mul(t2, a.c2, b.c2);
    // c0 = t0 + xi*((a1+a2)(b1+b2) - t1 - t2)
    fp2_add(s0, a.c1, a.c2);
    fp2_add(s1, b.c1, b.c2);
    fp2_mul(u0, s0, s1);
    fp2_sub(u0, u0, t1);
    fp2_sub(u0, u0, t2);
    fp2_mul_xi(u0, u0);
    Fp2 c0;
    fp2_add(c0, t0, u0);
    // c1 = (a0+a1)(b0+b1) - t0 - t1 + xi*t2
    fp2_add(s0, a.c0, a.c1);
    fp2_add(s1, b.c0, b.c1);
    fp2_mul(u0, s0, s1);
    fp2_sub(u0, u0, t0);
    fp2_sub(u0, u0, t1);
    fp2_mul_xi(u1, t2);
    Fp2 c1;
    fp2_add(c1, u0, u1);
    // c2 = (a0+a2)(b0+b2) - t0 - t2 + t1
    fp2_add(s0, a.c0, a.c2);
    fp2_add(s1, b.c0, b.c2);
    fp2_mul(u0, s0, s1);
    fp2_sub(u0, u0, t0);
    fp2_sub(u0, u0, t2);
    fp2_add(r.c2, u0, t1);
    r.c0 = c0;
    r.c1 = c1;
}

static void fp6_inv(Fp6& r, const Fp6& a) {
    Fp2 c0, c1, c2, t0, t1;
    fp2_sqr(t0, a.c0);
    fp2_mul(t1, a.c1, a.c2);
    fp2_mul_xi(t1, t1);
    fp2_sub(c0, t0, t1);
    fp2_sqr(t0, a.c2);
    fp2_mul_xi(t0, t0);
    fp2_mul(t1, a.c0, a.c1);
    fp2_sub(c1, t0, t1);
    fp2_sqr(t0, a.c1);
    fp2_mul(t1, a.c0, a.c2);
    fp2_sub(c2, t0, t1);
    Fp2 t;
    fp2_mul(t0, a.c2, c1);
    fp2_mul(t1, a.c1, c2);
    fp2_add(t0, t0, t1);
    fp2_mul_xi(t0, t0);
    fp2_mul(t1, a.c0, c0);
    fp2_add(t, t1, t0);
    fp2_inv(t, t);
    fp2_mul(r.c0, c0, t);
    fp2_mul(r.c1, c1, t);
    fp2_mul(r.c2, c2, t);
}

static Fp6 FP6_ZERO_C, FP6_ONE_C;

// ---------------- Fp12 = Fp6[w]/(w^2 - v) ----------------

struct Fp12 { Fp6 c0, c1; };

static void fp6_mul_v(Fp6& r, const Fp6& a) {  // multiply by v
    Fp2 t;
    fp2_mul_xi(t, a.c2);
    r.c2 = a.c1;
    r.c1 = a.c0;
    r.c0 = t;
}

static void fp12_mul(Fp12& r, const Fp12& a, const Fp12& b) {
    Fp6 t0, t1, s0, s1, vt1;
    fp6_mul(t0, a.c0, b.c0);
    fp6_mul(t1, a.c1, b.c1);
    fp6_mul_v(vt1, t1);
    Fp6 c0;
    fp6_add(c0, t0, vt1);
    fp6_add(s0, a.c0, a.c1);
    fp6_add(s1, b.c0, b.c1);
    fp6_mul(s0, s0, s1);
    fp6_sub(s0, s0, t0);
    fp6_sub(r.c1, s0, t1);
    r.c0 = c0;
}

static void fp12_sqr(Fp12& r, const Fp12& a) {
    // complex squaring: 2 Fp6 muls instead of fp12_mul's 3 —
    // (c0 + c1 w)^2 with w^2 = v:
    //   c0' = c0^2 + v c1^2 = (c0+c1)(c0+v c1) - (1+v) c0 c1
    //   c1' = 2 c0 c1
    Fp6 t0, t1, t2, vt0;
    fp6_mul(t0, a.c0, a.c1);
    fp6_add(t1, a.c0, a.c1);
    fp6_mul_v(t2, a.c1);
    fp6_add(t2, t2, a.c0);
    fp6_mul(t1, t1, t2);
    fp6_sub(t1, t1, t0);
    fp6_mul_v(vt0, t0);
    fp6_sub(r.c0, t1, vt0);
    fp6_add(r.c1, t0, t0);
}

static void fp12_conj(Fp12& r, const Fp12& a) {
    r.c0 = a.c0;
    fp6_neg(r.c1, a.c1);
}

static void fp12_inv(Fp12& r, const Fp12& a) {
    Fp6 t0, t1, vt1;
    fp6_mul(t0, a.c0, a.c0);
    fp6_mul(t1, a.c1, a.c1);
    fp6_mul_v(vt1, t1);
    fp6_sub(t0, t0, vt1);
    fp6_inv(t0, t0);
    fp6_mul(r.c0, a.c0, t0);
    Fp6 t2;
    fp6_mul(t2, a.c1, t0);
    fp6_neg(r.c1, t2);
}

static bool fp12_is_one(const Fp12& a) {
    return fp2_eq(a.c0.c0, FP2_ONE_C) && fp2_is_zero(a.c0.c1)
        && fp2_is_zero(a.c0.c2) && fp2_is_zero(a.c1.c0)
        && fp2_is_zero(a.c1.c1) && fp2_is_zero(a.c1.c2);
}

// Frobenius: conj each Fp2 coefficient, multiply the w^i coefficient by
// gamma1[i] (w-power basis order: c0.c0=w^0, c1.c0=w^1, c0.c1=w^2,
// c1.c1=w^3, c0.c2=w^4, c1.c2=w^5)
static Fp2 G1C[6], G2C[6];

static void fp12_frob1(Fp12& r, const Fp12& a) {
    Fp2 t;
    fp2_conj(r.c0.c0, a.c0.c0);
    fp2_conj(t, a.c1.c0); fp2_mul(r.c1.c0, t, G1C[1]);
    fp2_conj(t, a.c0.c1); fp2_mul(r.c0.c1, t, G1C[2]);
    fp2_conj(t, a.c1.c1); fp2_mul(r.c1.c1, t, G1C[3]);
    fp2_conj(t, a.c0.c2); fp2_mul(r.c0.c2, t, G1C[4]);
    fp2_conj(t, a.c1.c2); fp2_mul(r.c1.c2, t, G1C[5]);
}

static void fp12_frob2(Fp12& r, const Fp12& a) {
    // gamma2 coefficients are real: plain Fp2-by-Fp scalar multiplies
    r.c0.c0 = a.c0.c0;
    fp2_mul_fp(r.c1.c0, a.c1.c0, G2C[1].c0);
    fp2_mul_fp(r.c0.c1, a.c0.c1, G2C[2].c0);
    fp2_mul_fp(r.c1.c1, a.c1.c1, G2C[3].c0);
    fp2_mul_fp(r.c0.c2, a.c0.c2, G2C[4].c0);
    fp2_mul_fp(r.c1.c2, a.c1.c2, G2C[5].c0);
}

// Granger-Scott squaring for elements of the cyclotomic subgroup
// G_{Phi6(p^2)} (everything after the easy part of the final
// exponentiation lives there): 9 Fp2 squarings instead of full
// fp12_sqr's 12 Fp2 multiplications — the dominant cost of pow_x.
static void fp12_cyc_sqr(Fp12& z, const Fp12& x) {
    Fp2 t0, t1, t2, t3, t4, t5, t6, t7, t8, u;
    fp2_sqr(t0, x.c1.c1);
    fp2_sqr(t1, x.c0.c0);
    fp2_add(t6, x.c1.c1, x.c0.c0);
    fp2_sqr(t6, t6);
    fp2_sub(t6, t6, t0);
    fp2_sub(t6, t6, t1);                  // 2 x00 x11
    fp2_sqr(t2, x.c0.c2);
    fp2_sqr(t3, x.c1.c0);
    fp2_add(t7, x.c0.c2, x.c1.c0);
    fp2_sqr(t7, t7);
    fp2_sub(t7, t7, t2);
    fp2_sub(t7, t7, t3);                  // 2 x02 x10
    fp2_sqr(t4, x.c1.c2);
    fp2_sqr(t5, x.c0.c1);
    fp2_add(t8, x.c1.c2, x.c0.c1);
    fp2_sqr(t8, t8);
    fp2_sub(t8, t8, t4);
    fp2_sub(t8, t8, t5);
    fp2_mul_xi(t8, t8);                   // 2 x01 x12 xi
    fp2_mul_xi(u, t0);
    fp2_add(t0, u, t1);                   // xi x11^2 + x00^2
    fp2_mul_xi(u, t2);
    fp2_add(t2, u, t3);                   // xi x02^2 + x10^2
    fp2_mul_xi(u, t4);
    fp2_add(t4, u, t5);                   // xi x12^2 + x01^2
    fp2_sub(u, t0, x.c0.c0);
    fp2_add(u, u, u);
    fp2_add(z.c0.c0, u, t0);
    fp2_sub(u, t2, x.c0.c1);
    fp2_add(u, u, u);
    fp2_add(z.c0.c1, u, t2);
    fp2_sub(u, t4, x.c0.c2);
    fp2_add(u, u, u);
    fp2_add(z.c0.c2, u, t4);
    fp2_add(u, t8, x.c1.c0);
    fp2_add(u, u, u);
    fp2_add(z.c1.c0, u, t8);
    fp2_add(u, t6, x.c1.c1);
    fp2_add(u, u, u);
    fp2_add(z.c1.c1, u, t6);
    fp2_add(u, t7, x.c1.c2);
    fp2_add(u, u, u);
    fp2_add(z.c1.c2, u, t7);
}

// m^x for the curve parameter x (negative): conj(m^|x|); cyclotomic
// subgroup makes conj the inverse and enables Granger-Scott squaring
// (pow_x is only ever applied after the easy part)
static void fp12_pow_x(Fp12& r, const Fp12& m) {
    Fp12 result = m;                      // consume the msb implicitly
    for (int i = 62; i >= 0; i--) {
        fp12_cyc_sqr(result, result);
        if ((X_ABS >> i) & 1) fp12_mul(result, result, m);
    }
    fp12_conj(r, result);
}

// ---------------- Miller loop (affine, twist coordinates) ----------------
// Lines are scaled by powers of w (killed by the final exponentiation):
//   regular: (lam*x1 - y1) + (-lam*xP)*w^2 + yP*w^3
//   vertical: (-x1) + xP*w^2
// w-basis placement: w^0 -> c0.c0, w^2 -> c0.c1, w^3 -> c1.c1.

struct G1A { Fp x, y; bool inf; };
struct G2A { Fp2 x, y; bool inf; };

// A line is sparse in the w-power basis: only w^0 (c0.c0 = A),
// w^2 (c0.c1 = B) and w^3 (c1.c1 = C) are nonzero.
struct Line { Fp2 A, B, C; };

static void line_eval(Line& l, const Fp2& lam, const Fp2& x1, const Fp2& y1,
                      const Fp& xp, const Fp& yp) {
    Fp2 t;
    fp2_mul(t, lam, x1);
    fp2_sub(l.A, t, y1);
    fp2_mul_fp(t, lam, xp);
    fp2_neg(l.B, t);
    l.C.c0 = yp;
    l.C.c1 = FP_ZERO_C;
}


// a * (b0 + b1 v) over Fp6 — the sparse2 shape both line products need
static void fp6_mul_sparse2(Fp6& r, const Fp6& a, const Fp2& b0,
                            const Fp2& b1) {
    Fp2 t, u, c0, c1, c2;
    fp2_mul(t, a.c2, b1);
    fp2_mul_xi(t, t);
    fp2_mul(u, a.c0, b0);
    fp2_add(c0, u, t);
    fp2_mul(t, a.c0, b1);
    fp2_mul(u, a.c1, b0);
    fp2_add(c1, t, u);
    fp2_mul(t, a.c1, b1);
    fp2_mul(u, a.c2, b0);
    fp2_add(c2, t, u);
    r.c0 = c0; r.c1 = c1; r.c2 = c2;
}

// f *= line: 15 Fp2 muls instead of fp12_mul's 18 (line.c0 = A + B v,
// line.c1 = C v)
static void fp12_mul_line(Fp12& f, const Line& l) {
    Fp6 t0, t1, cross, vt1;
    // t1 = f.c1 * (C v): c0 = xi a2 C, c1 = a0 C, c2 = a1 C
    Fp2 u;
    fp2_mul(u, f.c1.c2, l.C);
    fp2_mul_xi(t1.c0, u);
    fp2_mul(t1.c1, f.c1.c0, l.C);
    fp2_mul(t1.c2, f.c1.c1, l.C);
    fp6_mul_sparse2(t0, f.c0, l.A, l.B);
    Fp6 s;
    fp6_add(s, f.c0, f.c1);
    Fp2 bc;
    fp2_add(bc, l.B, l.C);
    fp6_mul_sparse2(cross, s, l.A, bc);
    fp6_sub(cross, cross, t0);
    fp6_sub(f.c1, cross, t1);
    fp6_mul_v(vt1, t1);
    fp6_add(f.c0, t0, vt1);
}


// Lockstep multi-Miller: computes f = prod_i f_{|x|,Q_i}(P_i) directly
// (what pairing_check needs), batching each step's denominators into a
// single inversion. At most 16 pairs per call (callers chunk).
// Returns false on degenerate inputs (zero denominator / T==Q collision
// reachable only with non-subgroup points) — callers must REJECT: a
// malformed point must never produce an arbitrary verdict.
static const int MAX_PAIRS = 16;

// Homogeneous projective Miller loop: the affine version paid one Fp2
// (=Fp) inversion PER ITERATION (~570 muls each, ~63 of them — the
// dominant cost of a pairing); projective T and polynomial line
// coefficients eliminate every inversion. Lines are scaled freely by
// Fp2 factors — the easy part of the final exponentiation kills any
// Fp2 scalar (c^(p^6-1) = 1 for c in Fp2), so verdicts are unchanged.
static bool multi_miller(Fp12& f, const G2A* qs, const G1A* ps, int n) {
    Fp2 TX[MAX_PAIRS], TY[MAX_PAIRS], TZ[MAX_PAIRS];
    bool live[MAX_PAIRS];
    for (int k = 0; k < n; k++) {
        live[k] = !(qs[k].inf || ps[k].inf);
        if (live[k]) {
            TX[k] = qs[k].x;
            TY[k] = qs[k].y;
            TZ[k] = FP2_ONE_C;
        }
    }
    memset(&f, 0, sizeof(f));
    f.c0.c0 = FP2_ONE_C;
    Line l;
    Fp2 t0, t1, W, S, Bv, H, X2, Y2, S2;
    for (int i = 62; i >= 0; i--) {       // |x| has 64 bits; start msb-1
        fp12_sqr(f, f);
        for (int k = 0; k < n; k++) {
            if (!live[k]) continue;
            // tangent line at T=(X,Y,Z), scaled by 2YZ^2:
            //   A = 3X^3 - 2Y^2 Z, B = -3X^2 Z * xP, C = 2YZ^2 * yP
            fp2_sqr(X2, TX[k]);                   // X^2
            fp2_add(W, X2, X2);
            fp2_add(W, W, X2);                    // W = 3X^2
            fp2_mul(S, TY[k], TZ[k]);             // S = YZ
            if (fp2_is_zero(S)) return false;     // order-2 / degenerate
            fp2_sqr(Y2, TY[k]);                   // Y^2
            fp2_mul(t0, X2, TX[k]);               // X^3
            fp2_add(l.A, t0, t0);
            fp2_add(l.A, l.A, t0);                // 3X^3
            fp2_mul(t1, Y2, TZ[k]);               // Y^2 Z
            fp2_add(t0, t1, t1);                  // 2Y^2 Z
            fp2_sub(l.A, l.A, t0);
            fp2_mul(t0, W, TZ[k]);                // 3X^2 Z
            fp2_neg(t0, t0);
            fp2_mul_fp(l.B, t0, ps[k].x);
            fp2_mul(t0, S, TZ[k]);                // YZ^2
            fp2_add(t0, t0, t0);                  // 2YZ^2
            fp2_mul_fp(l.C, t0, ps[k].y);
            fp12_mul_line(f, l);
            // projective doubling (a=0): W=3X^2, S=YZ, Bv=XY*S,
            // H=W^2-8Bv; X'=2HS, Y'=W(4Bv-H)-8(YS)^2, Z'=8S^3
            fp2_mul(t0, TX[k], TY[k]);
            fp2_mul(Bv, t0, S);                   // XY*S
            fp2_sqr(H, W);
            fp2_add(t0, Bv, Bv);
            fp2_add(t0, t0, t0);
            fp2_add(t1, t0, t0);                  // 8Bv
            fp2_sub(H, H, t1);                    // H = W^2 - 8Bv
            fp2_mul(t1, H, S);
            fp2_add(TX[k], t1, t1);               // X' = 2HS
            fp2_mul(S2, TY[k], S);                // YS
            fp2_sqr(S2, S2);                      // (YS)^2
            fp2_sub(t0, t0, H);                   // 4Bv - H
            fp2_mul(t0, W, t0);
            fp2_add(t1, S2, S2);
            fp2_add(t1, t1, t1);
            fp2_add(t1, t1, t1);                  // 8(YS)^2
            fp2_sub(TY[k], t0, t1);               // Y'
            fp2_sqr(t0, S);
            fp2_mul(t0, t0, S);                   // S^3
            fp2_add(t0, t0, t0);
            fp2_add(t0, t0, t0);
            fp2_add(TZ[k], t0, t0);               // Z' = 8S^3
        }
        if (!((X_ABS >> i) & 1)) continue;
        for (int k = 0; k < n; k++) {
            if (!live[k]) continue;
            // mixed addition T + Q, Q=(x2,y2) affine:
            //   u = y2 Z - Y, v = x2 Z - X
            Fp2 u, v, v2, v3, A2;
            fp2_mul(t0, qs[k].y, TZ[k]);
            fp2_sub(u, t0, TY[k]);
            fp2_mul(t0, qs[k].x, TZ[k]);
            fp2_sub(v, t0, TX[k]);
            if (fp2_is_zero(v)) {
                // x_T == x_Q projectively: T == Q (inside the ate loop
                // only reachable with non-subgroup inputs) or T == -Q;
                // both REJECT — decompression enforces the subgroup, so
                // honest inputs never land here
                return false;
            }
            // line through Q and T evaluated at P, scaled by v:
            //   A = u*x2 - v*y2, B = -u*xP, C = v*yP
            fp2_mul(t0, u, qs[k].x);
            fp2_mul(t1, v, qs[k].y);
            fp2_sub(l.A, t0, t1);
            fp2_neg(t0, u);
            fp2_mul_fp(l.B, t0, ps[k].x);
            fp2_mul_fp(l.C, v, ps[k].y);
            fp12_mul_line(f, l);
            // add-1998-cmo-2 mixed addition:
            //   A2 = u^2 Z - v^3 - 2v^2 X
            //   X' = v*A2; Y' = u*(v^2 X - A2) - v^3 Y; Z' = v^3 Z
            fp2_sqr(v2, v);
            fp2_mul(v3, v2, v);
            fp2_sqr(t0, u);
            fp2_mul(t0, t0, TZ[k]);               // u^2 Z
            fp2_mul(t1, v2, TX[k]);               // v^2 X
            fp2_sub(A2, t0, v3);
            fp2_sub(A2, A2, t1);
            fp2_sub(A2, A2, t1);                  // - 2 v^2 X
            fp2_mul(TX[k], v, A2);
            fp2_sub(t1, t1, A2);                  // v^2 X - A2
            fp2_mul(t0, u, t1);
            fp2_mul(t1, v3, TY[k]);
            fp2_sub(TY[k], t0, t1);
            fp2_mul(TZ[k], v3, TZ[k]);
        }
    }
    Fp12 fc;
    fp12_conj(fc, f);                     // x < 0
    f = fc;
    return true;
}

// ---------------- final exponentiation ----------------

static void final_exp(Fp12& r, const Fp12& f) {
    // easy part: f^((p^6-1)(p^2+1))
    Fp12 t0, t1, m;
    fp12_conj(t0, f);
    fp12_inv(t1, f);
    fp12_mul(m, t0, t1);                  // f^(p^6-1)
    fp12_frob2(t0, m);
    fp12_mul(m, t0, m);                   // ^(p^2+1); now cyclotomic
    // hard part (exponent 3*(p^4-p^2+1)/r, verified identity):
    //   m^((x-1)^2 * (x+p) * (x^2+p^2-1)) * m^3
    Fp12 a, b;
    fp12_pow_x(t0, m);
    fp12_conj(t1, m);
    fp12_mul(a, t0, t1);                  // m^(x-1)
    fp12_pow_x(t0, a);
    fp12_conj(t1, a);
    fp12_mul(a, t0, t1);                  // m^((x-1)^2)
    fp12_pow_x(t0, a);
    fp12_frob1(t1, a);
    fp12_mul(b, t0, t1);                  // a^(x+p)
    fp12_pow_x(t0, b);
    fp12_pow_x(t0, t0);                   // b^(x^2)
    fp12_frob2(t1, b);
    fp12_mul(t0, t0, t1);                 // * b^(p^2)
    fp12_conj(t1, b);
    fp12_mul(b, t0, t1);                  // b^(x^2+p^2-1)
    Fp12 m3;
    fp12_sqr(m3, m);
    fp12_mul(m3, m3, m);
    fp12_mul(r, b, m3);
}

// ---------------- jacobian group ops (for mul / msm) ----------------
// Generic over the coordinate field via macros would be noise; G1 and G2
// versions are written out (same dbl-1998-cmo / add-2007-bl shapes).

struct G1J { Fp x, y, z; bool inf; };
struct G2J { Fp2 x, y, z; bool inf; };

static void g1j_dbl(G1J& r, const G1J& in) {
    const G1J a = in;                  // r may alias in
    if (a.inf || fp_is_zero(a.y)) { r.inf = true; return; }
    Fp xx, yy, yyyy, zz, s, mm, t;
    fp_sqr(xx, a.x);
    fp_sqr(yy, a.y);
    fp_sqr(yyyy, yy);
    fp_sqr(zz, a.z);
    fp_add(s, a.x, yy);
    fp_sqr(s, s);
    fp_sub(s, s, xx);
    fp_sub(s, s, yyyy);
    fp_add(s, s, s);
    fp_add(mm, xx, xx);
    fp_add(mm, mm, xx);
    fp_sqr(t, mm);
    fp_sub(t, t, s);
    fp_sub(r.x, t, s);
    fp_sub(t, s, r.x);
    fp_mul(t, mm, t);
    Fp y8;
    fp_add(y8, yyyy, yyyy);
    fp_add(y8, y8, y8);
    fp_add(y8, y8, y8);
    fp_sub(r.y, t, y8);
    fp_mul(t, a.y, a.z);
    fp_add(r.z, t, t);
    r.inf = false;
}

static void g1j_add_affine(G1J& r, const G1J& in, const G1A& b) {
    const G1J a = in;                  // r may alias in
    if (b.inf) { r = a; return; }
    if (a.inf) {
        r.x = b.x; r.y = b.y;
        memcpy(r.z.l, ONE_M, 48);
        r.inf = false;
        return;
    }
    Fp z2, u2, s2, h, hh, i, j, rr, v, t;
    fp_sqr(z2, a.z);
    fp_mul(u2, b.x, z2);
    fp_mul(s2, b.y, z2);
    fp_mul(s2, s2, a.z);
    fp_sub(h, u2, a.x);
    fp_sub(rr, s2, a.y);
    if (fp_is_zero(h)) {
        if (fp_is_zero(rr)) { g1j_dbl(r, a); return; }
        r.inf = true;
        return;
    }
    fp_sqr(hh, h);
    fp_add(i, hh, hh);
    fp_add(i, i, i);
    fp_mul(j, h, i);
    fp_add(rr, rr, rr);
    fp_mul(v, a.x, i);
    fp_sqr(t, rr);
    fp_sub(t, t, j);
    fp_sub(t, t, v);
    fp_sub(r.x, t, v);
    fp_sub(t, v, r.x);
    fp_mul(t, rr, t);
    Fp t2;
    fp_mul(t2, a.y, j);
    fp_add(t2, t2, t2);
    fp_sub(r.y, t, t2);
    fp_mul(r.z, a.z, h);
    fp_add(r.z, r.z, r.z);
    r.inf = false;
}

static void g1j_to_affine(G1A& r, const G1J& a) {
    if (a.inf) { r.inf = true; return; }
    Fp zi, zi2, zi3;
    fp_inv(zi, a.z);
    fp_sqr(zi2, zi);
    fp_mul(zi3, zi2, zi);
    fp_mul(r.x, a.x, zi2);
    fp_mul(r.y, a.y, zi3);
    r.inf = false;
}

static void g2j_dbl(G2J& r, const G2J& in) {
    const G2J a = in;                  // r may alias in
    if (a.inf || fp2_is_zero(a.y)) { r.inf = true; return; }
    Fp2 xx, yy, yyyy, s, mm, t;
    fp2_sqr(xx, a.x);
    fp2_sqr(yy, a.y);
    fp2_sqr(yyyy, yy);
    fp2_add(s, a.x, yy);
    fp2_sqr(s, s);
    fp2_sub(s, s, xx);
    fp2_sub(s, s, yyyy);
    fp2_add(s, s, s);
    fp2_add(mm, xx, xx);
    fp2_add(mm, mm, xx);
    fp2_sqr(t, mm);
    fp2_sub(t, t, s);
    fp2_sub(r.x, t, s);
    fp2_sub(t, s, r.x);
    fp2_mul(t, mm, t);
    Fp2 y8;
    fp2_add(y8, yyyy, yyyy);
    fp2_add(y8, y8, y8);
    fp2_add(y8, y8, y8);
    fp2_sub(r.y, t, y8);
    fp2_mul(t, a.y, a.z);
    fp2_add(r.z, t, t);
    r.inf = false;
}

static void g2j_add_affine(G2J& r, const G2J& in, const G2A& b) {
    const G2J a = in;                  // r may alias in
    if (b.inf) { r = a; return; }
    if (a.inf) {
        r.x = b.x; r.y = b.y;
        memcpy(r.z.c0.l, ONE_M, 48);
        r.z.c1 = FP_ZERO_C;
        r.inf = false;
        return;
    }
    Fp2 z2, u2, s2, h, hh, i, j, rr, v, t;
    fp2_sqr(z2, a.z);
    fp2_mul(u2, b.x, z2);
    fp2_mul(s2, b.y, z2);
    fp2_mul(s2, s2, a.z);
    fp2_sub(h, u2, a.x);
    fp2_sub(rr, s2, a.y);
    if (fp2_is_zero(h)) {
        if (fp2_is_zero(rr)) { g2j_dbl(r, a); return; }
        r.inf = true;
        return;
    }
    fp2_sqr(hh, h);
    fp2_add(i, hh, hh);
    fp2_add(i, i, i);
    fp2_mul(j, h, i);
    fp2_add(rr, rr, rr);
    fp2_mul(v, a.x, i);
    fp2_sqr(t, rr);
    fp2_sub(t, t, j);
    fp2_sub(t, t, v);
    fp2_sub(r.x, t, v);
    fp2_sub(t, v, r.x);
    fp2_mul(t, rr, t);
    Fp2 t2;
    fp2_mul(t2, a.y, j);
    fp2_add(t2, t2, t2);
    fp2_sub(r.y, t, t2);
    fp2_mul(r.z, a.z, h);
    fp2_add(r.z, r.z, r.z);
    r.inf = false;
}

// Jacobian + Jacobian additions (add-2007-bl) — needed by the Pippenger
// bucket sweep, where both operands are accumulated sums.
static void g1j_add(G1J& r, const G1J& ain, const G1J& bin) {
    const G1J a = ain, b = bin;           // r may alias either
    if (a.inf) { r = b; return; }
    if (b.inf) { r = a; return; }
    Fp z1z1, z2z2, u1, u2, s1, s2, h, i, j, rr, v, t;
    fp_sqr(z1z1, a.z);
    fp_sqr(z2z2, b.z);
    fp_mul(u1, a.x, z2z2);
    fp_mul(u2, b.x, z1z1);
    fp_mul(s1, a.y, b.z);
    fp_mul(s1, s1, z2z2);
    fp_mul(s2, b.y, a.z);
    fp_mul(s2, s2, z1z1);
    fp_sub(h, u2, u1);
    fp_sub(rr, s2, s1);
    if (fp_is_zero(h)) {
        if (fp_is_zero(rr)) { g1j_dbl(r, a); return; }
        r.inf = true;
        return;
    }
    fp_add(i, h, h);
    fp_sqr(i, i);
    fp_mul(j, h, i);
    fp_add(rr, rr, rr);
    fp_mul(v, u1, i);
    fp_sqr(t, rr);
    fp_sub(t, t, j);
    fp_sub(t, t, v);
    fp_sub(r.x, t, v);
    fp_sub(t, v, r.x);
    fp_mul(t, rr, t);
    Fp t2;
    fp_mul(t2, s1, j);
    fp_add(t2, t2, t2);
    fp_sub(r.y, t, t2);
    fp_add(t, a.z, b.z);
    fp_sqr(t, t);
    fp_sub(t, t, z1z1);
    fp_sub(t, t, z2z2);
    fp_mul(r.z, t, h);
    r.inf = false;
}

static void g2j_add(G2J& r, const G2J& ain, const G2J& bin) {
    const G2J a = ain, b = bin;
    if (a.inf) { r = b; return; }
    if (b.inf) { r = a; return; }
    Fp2 z1z1, z2z2, u1, u2, s1, s2, h, i, j, rr, v, t;
    fp2_sqr(z1z1, a.z);
    fp2_sqr(z2z2, b.z);
    fp2_mul(u1, a.x, z2z2);
    fp2_mul(u2, b.x, z1z1);
    fp2_mul(s1, a.y, b.z);
    fp2_mul(s1, s1, z2z2);
    fp2_mul(s2, b.y, a.z);
    fp2_mul(s2, s2, z1z1);
    fp2_sub(h, u2, u1);
    fp2_sub(rr, s2, s1);
    if (fp2_is_zero(h)) {
        if (fp2_is_zero(rr)) { g2j_dbl(r, a); return; }
        r.inf = true;
        return;
    }
    fp2_add(i, h, h);
    fp2_sqr(i, i);
    fp2_mul(j, h, i);
    fp2_add(rr, rr, rr);
    fp2_mul(v, u1, i);
    fp2_sqr(t, rr);
    fp2_sub(t, t, j);
    fp2_sub(t, t, v);
    fp2_sub(r.x, t, v);
    fp2_sub(t, v, r.x);
    fp2_mul(t, rr, t);
    Fp2 t2;
    fp2_mul(t2, s1, j);
    fp2_add(t2, t2, t2);
    fp2_sub(r.y, t, t2);
    fp2_add(t, a.z, b.z);
    fp2_sqr(t, t);
    fp2_sub(t, t, z1z1);
    fp2_sub(t, t, z2z2);
    fp2_mul(r.z, t, h);
    r.inf = false;
}

static void g2j_to_affine(G2A& r, const G2J& a) {
    if (a.inf) { r.inf = true; return; }
    Fp2 zi, zi2, zi3;
    fp2_inv(zi, a.z);
    fp2_sqr(zi2, zi);
    fp2_mul(zi3, zi2, zi);
    fp2_mul(r.x, a.x, zi2);
    fp2_mul(r.y, a.y, zi3);
    r.inf = false;
}

// ---------------- init ----------------

static bool g_ready = false;

static void ensure_init() {
    if (g_ready) return;
    {   // (p+1)/4 for the decompress sqrt (p ≡ 3 mod 4)
        u64 tmp[6];
        u128 c = (u128)P_LIMBS[0] + 1;
        for (int i = 0; i < 6; i++) {
            if (i) c = (u128)P_LIMBS[i] + (c >> 64);
            tmp[i] = (u64)c;
        }
        for (int i = 0; i < 6; i++) {
            u64 lo = tmp[i] >> 2;
            u64 hi = (i < 5) ? (tmp[i + 1] << 62) : 0;
            SQRT_EXP[i] = lo | hi;
        }
        for (int i = 0; i < 6; i++) {
            u64 w = P_LIMBS[5 - i];
            for (int j = 0; j < 8; j++)
                P_BE[i * 8 + j] = (uint8_t)(w >> (56 - 8 * j));
        }
        for (int i = 0; i < 6; i++) {
            u64 lo = P_LIMBS[i] >> 1;       // (p-1)/2 = p >> 1 (p odd)
            u64 hi = (i < 5) ? (P_LIMBS[i + 1] << 63) : 0;
            tmp[i] = lo | hi;
        }
        for (int i = 0; i < 6; i++) {
            u64 w = tmp[5 - i];
            for (int j = 0; j < 8; j++)
                P_HALF_BE[i * 8 + j] = (uint8_t)(w >> (56 - 8 * j));
        }
    }
    memset(&FP_ZERO_C, 0, sizeof(FP_ZERO_C));
    memcpy(FP_ONE_C.l, ONE_M, 48);
    FP2_ZERO_C.c0 = FP_ZERO_C; FP2_ZERO_C.c1 = FP_ZERO_C;
    FP2_ONE_C.c0 = FP_ONE_C; FP2_ONE_C.c1 = FP_ZERO_C;
    memset(&FP6_ZERO_C, 0, sizeof(FP6_ZERO_C));
    FP6_ONE_C = FP6_ZERO_C;
    FP6_ONE_C.c0 = FP2_ONE_C;
    const u64* g1p[6][2] = {{nullptr, nullptr},
                            {G1C1_0, G1C1_1}, {G1C2_0, G1C2_1},
                            {G1C3_0, G1C3_1}, {G1C4_0, G1C4_1},
                            {G1C5_0, G1C5_1}};
    const u64* g2p[6] = {nullptr, G2C1_0, G2C2_0, G2C3_0, G2C4_0, G2C5_0};
    for (int i = 1; i < 6; i++) {
        memcpy(G1C[i].c0.l, g1p[i][0], 48);
        memcpy(G1C[i].c1.l, g1p[i][1], 48);
        memcpy(G2C[i].c0.l, g2p[i], 48);
        G2C[i].c1 = FP_ZERO_C;
    }
    g_ready = true;
}

// ---------------- byte-boundary helpers ----------------

static bool load_g1(G1A& p, const uint8_t* xy96, int inf) {
    p.inf = inf != 0;
    if (p.inf) return true;
    fp_from_be(p.x, xy96);
    fp_from_be(p.y, xy96 + 48);
    return true;
}

static bool load_g2(G2A& q, const uint8_t* c192, int inf) {
    q.inf = inf != 0;
    if (q.inf) return true;
    fp_from_be(q.x.c0, c192);
    fp_from_be(q.x.c1, c192 + 48);
    fp_from_be(q.y.c0, c192 + 96);
    fp_from_be(q.y.c1, c192 + 144);
    return true;
}

// Pippenger bucket MSM (the role of fastMultExp, FastMultExp.cpp:27-59,
// at bucket-method complexity): windows of c bits; per window each point
// lands in its digit's bucket (one mixed add), then one running-sum
// sweep over 2^c-1 buckets yields sum_b b*bucket[b]. Window size chosen
// from n; ~2.5-3x over the shared-doubling square-and-add at n>=500.
static inline int msm_window_bits(int n) {
    if (n < 8) return 3;
    if (n < 64) return 5;
    if (n < 256) return 7;
    return 8;
}

static inline int msm_digit(const uint8_t* k32, int w, int c) {
    // bits [w*c, w*c+c) of a 32-byte big-endian scalar, LSB bit order
    int d = 0;
    for (int b = 0; b < c; b++) {
        int bit = w * c + b;
        if (bit > 255) break;
        d |= ((k32[31 - bit / 8] >> (bit % 8)) & 1) << b;
    }
    return d;
}

// Dedicated single-scalar windowed mul (4-bit fixed window): the
// Pippenger machinery pays a full bucket sweep per window, which is
// pure overhead at n=1 — and n=1 is the subgroup-check / cofactor-clear
// hot case.
template <typename Jac, typename Aff>
static void mul_single(Jac& acc, const Aff& p, const uint8_t* k32,
                       void (*dbl)(Jac&, const Jac&),
                       void (*add_aff)(Jac&, const Jac&, const Aff&),
                       void (*add_jj)(Jac&, const Jac&, const Jac&)) {
    Jac tbl[15];
    tbl[0].inf = true;
    add_aff(tbl[0], tbl[0], p);                 // [1]P
    for (int i = 1; i < 15; i++)
        add_aff(tbl[i], tbl[i - 1], p);         // [i+1]P
    acc.inf = true;
    // big-endian scalar: nibble position d (0 = least significant) lives
    // in byte 31 - d/2; odd d is that byte's HIGH nibble
    auto nibble = [&](int d) -> int {
        int b = k32[31 - d / 2];
        return (d & 1) ? (b >> 4) : (b & 0x0F);
    };
    int start = 63;
    while (start >= 0 && nibble(start) == 0) start--;
    for (int d = start; d >= 0; d--) {
        if (!acc.inf) {
            dbl(acc, acc); dbl(acc, acc); dbl(acc, acc); dbl(acc, acc);
        }
        int nib = nibble(d);
        if (nib) add_jj(acc, acc, tbl[nib - 1]);
    }
}

template <typename Jac, typename Aff>
static void msm_pippenger(Jac& acc, const Aff* aff, const uint8_t* ks,
                          int n,
                          void (*dbl)(Jac&, const Jac&),
                          void (*add_aff)(Jac&, const Jac&, const Aff&),
                          void (*add_jj)(Jac&, const Jac&, const Jac&)) {
    if (n == 1 && !aff[0].inf) {
        mul_single<Jac, Aff>(acc, aff[0], ks, dbl, add_aff, add_jj);
        return;
    }
    const int c = msm_window_bits(n);
    const int nbuckets = (1 << c) - 1;
    const int windows = (255 / c) + 1;
    Jac* buckets = new Jac[nbuckets];
    acc.inf = true;
    for (int w = windows - 1; w >= 0; w--) {
        if (!acc.inf) {
            for (int b = 0; b < c; b++) dbl(acc, acc);
        }
        for (int b = 0; b < nbuckets; b++) buckets[b].inf = true;
        for (int i = 0; i < n; i++) {
            if (aff[i].inf) continue;
            int d = msm_digit(ks + (size_t)i * 32, w, c);
            if (d) add_aff(buckets[d - 1], buckets[d - 1], aff[i]);
        }
        Jac running, sum;
        running.inf = true;
        sum.inf = true;
        for (int b = nbuckets - 1; b >= 0; b--) {
            add_jj(running, running, buckets[b]);
            add_jj(sum, sum, running);
        }
        add_jj(acc, acc, sum);
    }
    delete[] buckets;
}


extern "C" {

// prod_i e(P_i, Q_i) == 1 ?  (multi-pairing: miller loops multiplied,
// ONE final exponentiation — the multi-pair structure VERDICT asks for)
int bls381_pairing_check(const uint8_t* g1s, const uint8_t* g2s,
                         const uint8_t* infs, int n) {
    ensure_init();
    Fp12 f, chunk_f;
    memset(&f, 0, sizeof(f));
    f.c0.c0 = FP2_ONE_C;
    G1A ps[MAX_PAIRS];
    G2A qs[MAX_PAIRS];
    for (int base = 0; base < n; base += MAX_PAIRS) {
        int m = n - base < MAX_PAIRS ? n - base : MAX_PAIRS;
        for (int i = 0; i < m; i++) {
            load_g1(ps[i], g1s + (size_t)(base + i) * 96,
                    infs[base + i] & 1);
            load_g2(qs[i], g2s + (size_t)(base + i) * 192,
                    infs[base + i] & 2);
        }
        if (!multi_miller(chunk_f, qs, ps, m)) return 0;  // reject
        fp12_mul(f, f, chunk_f);
    }
    if (n == 0) { return 1; }
    final_exp(f, f);
    return fp12_is_one(f) ? 1 : 0;
}

// out = sum_i [k_i] P_i over G1 (affine in/out, 96B points, 32B BE
// scalars); returns 1, out_inf set if the sum is infinity.
// Interleaved (Straus) chain: ONE shared 256-doubling run, a mixed add
// per set bit, and a single Jacobian->affine inversion at the end —
// the fastMultExp role (reference FastMultExp.cpp:27).
// Decompress a 48-byte ZCash-style compressed G1 point: canonical-
// encoding + on-curve checks here, sqrt via one fp_pow (the Python-side
// modexp at ~0.3 ms each was the per-share decompress bottleneck).
// Returns 1 ok (affine out), 2 infinity, 0 invalid. No subgroup check —
// the Python layer runs the GLV endomorphism membership test on top
// (a probabilistic batch check would be unsound: the cofactor has small
// prime factors).
int bls381_g1_decompress(uint8_t* out96, const uint8_t* in48) {
    ensure_init();
    uint8_t flags = in48[0];
    if (!(flags & 0x80)) return 0;
    if (flags & 0x40) {                 // infinity: canonical form only
        if (flags != 0xC0) return 0;
        for (int i = 1; i < 48; i++) {
            if (in48[i]) return 0;
        }
        return 2;
    }
    uint8_t xbe[48];
    memcpy(xbe, in48, 48);
    xbe[0] &= 0x1F;
    // canonical: x < p (big-endian compare; P_BE set in ensure_init)
    int cmp = memcmp(xbe, P_BE, 48);
    if (cmp >= 0) return 0;
    Fp x, x3, y2, y;
    fp_from_be(x, xbe);
    fp_sqr(x3, x);
    fp_mul(x3, x3, x);
    Fp b4;
    {   // b = 4 in Montgomery form: 4 * ONE_M
        Fp one;
        memcpy(one.l, ONE_M, 48);
        fp_add(b4, one, one);
        fp_add(b4, b4, b4);
    }
    fp_add(y2, x3, b4);
    // sqrt: y = y2^((p+1)/4)  (p ≡ 3 mod 4); SQRT_EXP set in ensure_init
    fp_pow(y, y2, SQRT_EXP, 6);
    Fp chk;
    fp_sqr(chk, y);
    if (!fp_eq(chk, y2)) return 0;      // not a QR: off curve
    // sign selection: flag 0x20 = y lexicographically greater than p/2
    uint8_t ybe[48];
    fp_to_be(ybe, y);
    // greater iff 2y > p  <=>  y > (p-1)/2: compare 2*y vs p in plain ints
    bool greater = memcmp(ybe, P_HALF_BE, 48) > 0;
    if (greater != !!(flags & 0x20)) {
        fp_neg(y, y);
        fp_to_be(ybe, y);
    }
    memcpy(out96, xbe, 48);
    memcpy(out96 + 48, ybe, 48);
    return 1;
}

// Square root in Fp via one fp_pow (p ≡ 3 mod 4): the Python-side modexp
// at ~0.3 ms dominated hash-to-curve; returns 0 when not a QR.
int bls381_fp_sqrt(uint8_t* out48, const uint8_t* in48) {
    ensure_init();
    Fp a, y, chk;
    fp_from_be(a, in48);
    fp_pow(y, a, SQRT_EXP, 6);
    fp_sqr(chk, y);
    if (!fp_eq(chk, a)) return 0;
    fp_to_be(out48, y);
    return 1;
}

int bls381_g1_msm(uint8_t* out96, uint8_t* out_inf, const uint8_t* pts,
                  const uint8_t* infs, const uint8_t* ks, int n) {
    ensure_init();
    G1A* aff = new G1A[n > 0 ? n : 1];
    for (int i = 0; i < n; i++) {
        load_g1(aff[i], pts + (size_t)i * 96, infs[i]);
    }
    G1J acc;
    msm_pippenger<G1J, G1A>(acc, aff, ks, n, g1j_dbl, g1j_add_affine,
                            g1j_add);
    delete[] aff;
    G1A r;
    g1j_to_affine(r, acc);
    *out_inf = r.inf ? 1 : 0;
    if (!r.inf) {
        fp_to_be(out96, r.x);
        fp_to_be(out96 + 48, r.y);
    }
    return 1;
}

int bls381_g2_msm(uint8_t* out192, uint8_t* out_inf, const uint8_t* pts,
                  const uint8_t* infs, const uint8_t* ks, int n) {
    ensure_init();
    G2A* aff = new G2A[n > 0 ? n : 1];
    for (int i = 0; i < n; i++) {
        load_g2(aff[i], pts + (size_t)i * 192, infs[i]);
    }
    G2J acc;
    msm_pippenger<G2J, G2A>(acc, aff, ks, n, g2j_dbl, g2j_add_affine,
                            g2j_add);
    delete[] aff;
    G2A r;
    g2j_to_affine(r, acc);
    *out_inf = r.inf ? 1 : 0;
    if (!r.inf) {
        fp_to_be(out192, r.x.c0);
        fp_to_be(out192 + 48, r.x.c1);
        fp_to_be(out192 + 96, r.y.c0);
        fp_to_be(out192 + 144, r.y.c1);
    }
    return 1;
}

}  // extern "C"
