"""Build-and-cache for the native C++ components.

Compiles <name>.cpp in this directory into _<name>.so next to it on first
use; recompiles when the source is newer than the cached object. No
network, no external build system — just g++ (baked into the image).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()
_cache: dict = {}


class NativeBuildError(RuntimeError):
    pass


_FLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC", "-fno-plt"]


def load(name: str) -> ctypes.CDLL:
    with _lock:
        if name in _cache:
            return _cache[name]
        src = os.path.join(_HERE, f"{name}.cpp")
        so = os.path.join(_HERE, f"_{name}.so")
        stamp = so + ".flags"
        # staleness = newer source OR different compile flags (a flags
        # bump must invalidate cached objects, including prebuilts)
        want = " ".join(_FLAGS)
        have = ""
        if os.path.exists(stamp):
            with open(stamp) as f:
                have = f.read().strip()
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)
                or have != want):
            tmp = so + ".build"
            cmd = ["g++", *_FLAGS, "-o", tmp, src]
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise NativeBuildError(
                    f"g++ failed for {name}:\n{proc.stderr[-4000:]}")
            os.replace(tmp, so)
            with open(stamp, "w") as f:
                f.write(want)
        lib = ctypes.CDLL(so)
        _cache[name] = lib
        return lib
