"""Native (C++) runtime components, built on demand with g++ and loaded
via ctypes. See build.py for the compile-and-cache logic."""
