// kvlog — native log-structured KV engine (the RocksDB role of the
// reference's storage layer, /root/reference/storage/src/rocksdb_client.cpp,
// rebuilt as a small crash-consistent C++ engine for this framework).
//
// Design: append-only WAL of checksummed batch records + full in-memory
// ordered index (std::map). Recovery replays complete records and stops at
// the first torn/corrupt tail. Compaction rewrites the live set as a single
// batch record into a temp file and atomically renames it over the log.
//
// Record framing:  [u32 magic 0x4b564c47][u32 crc32(payload)][u32 len][payload]
// Batch payload:   repeat{ u8 op(1=put,2=del) | u32 klen | key | [u32 vlen | val] }
// (shared with Python WriteBatch.encode, tpubft/storage/interfaces.py)
//
// C ABI only — consumed via ctypes from tpubft/storage/native.py.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x4b564c47;  // "GLVK" little-endian

uint32_t crc_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc_table[i] = c;
    }
  }
} crc_init_;

uint32_t crc32(const uint8_t* p, size_t n) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = crc_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

uint32_t rd_u32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

void wr_u32(uint8_t* p, uint32_t v) {
  p[0] = v & 0xFF; p[1] = (v >> 8) & 0xFF;
  p[2] = (v >> 16) & 0xFF; p[3] = (v >> 24) & 0xFF;
}

struct KvLog {
  std::map<std::string, std::string> index;
  std::string path;
  int fd = -1;
  uint64_t wal_bytes = 0;
  bool sync_writes = true;
  std::mutex mu;
};

// Apply a validated batch payload to the index. Returns false on malformed.
bool apply_payload(KvLog* db, const uint8_t* p, size_t n) {
  size_t off = 0;
  while (off < n) {
    if (off + 5 > n) return false;
    uint8_t op = p[off];
    uint32_t klen = rd_u32(p + off + 1);
    off += 5;
    if (off + klen > n) return false;
    std::string key((const char*)p + off, klen);
    off += klen;
    if (op == 1) {
      if (off + 4 > n) return false;
      uint32_t vlen = rd_u32(p + off);
      off += 4;
      if (off + vlen > n) return false;
      db->index[std::move(key)] = std::string((const char*)p + off, vlen);
      off += vlen;
    } else if (op == 2) {
      db->index.erase(key);
    } else {
      return false;
    }
  }
  return true;
}

bool validate_payload(const uint8_t* p, size_t n) {
  size_t off = 0;
  while (off < n) {
    if (off + 5 > n) return false;
    uint8_t op = p[off];
    uint32_t klen = rd_u32(p + off + 1);
    off += 5 + klen;
    if (off > n) return false;
    if (op == 1) {
      if (off + 4 > n) return false;
      uint32_t vlen = rd_u32(p + off);
      off += 4 + vlen;
      if (off > n) return false;
    } else if (op != 2) {
      return false;
    }
  }
  return off == n;
}

void append_put(std::vector<uint8_t>& out, const std::string& k,
                const std::string& v) {
  size_t base = out.size();
  out.resize(base + 9 + k.size() + v.size());
  out[base] = 1;
  wr_u32(out.data() + base + 1, (uint32_t)k.size());
  memcpy(out.data() + base + 5, k.data(), k.size());
  wr_u32(out.data() + base + 5 + k.size(), (uint32_t)v.size());
  memcpy(out.data() + base + 9 + k.size(), v.data(), v.size());
}

// Serialize the whole index as one batch payload (for compaction).
std::vector<uint8_t> snapshot_payload(KvLog* db) {
  std::vector<uint8_t> out;
  for (const auto& [k, v] : db->index) append_put(out, k, v);
  return out;
}

bool write_record(int fd, const uint8_t* payload, uint32_t len, bool sync) {
  uint8_t hdr[12];
  wr_u32(hdr, kMagic);
  wr_u32(hdr + 4, crc32(payload, len));
  wr_u32(hdr + 8, len);
  struct iovec {
    const uint8_t* p; size_t n;
  } parts[2] = {{hdr, 12}, {payload, len}};
  for (auto& part : parts) {
    size_t done = 0;
    while (done < part.n) {
      ssize_t w = ::write(fd, part.p + done, part.n - done);
      if (w < 0) return false;
      done += (size_t)w;
    }
  }
  if (sync && fsync(fd) != 0) return false;
  return true;
}

}  // namespace

extern "C" {

KvLog* kvlog_open(const char* path, int sync_writes) {
  KvLog* db = new KvLog;
  db->path = path;
  db->sync_writes = sync_writes != 0;
  int fd = ::open(path, O_RDWR | O_CREAT, 0644);
  if (fd < 0) { delete db; return nullptr; }
  // Recover: scan records until torn/corrupt tail, then truncate there so
  // future appends start from a clean boundary.
  off_t valid_end = 0;
  std::vector<uint8_t> buf;
  for (;;) {
    uint8_t hdr[12];
    ssize_t r = ::pread(fd, hdr, 12, valid_end);
    if (r != 12) break;
    if (rd_u32(hdr) != kMagic) break;
    uint32_t crc = rd_u32(hdr + 4), len = rd_u32(hdr + 8);
    if (len > (1u << 30)) break;
    buf.resize(len);
    r = ::pread(fd, buf.data(), len, valid_end + 12);
    if (r != (ssize_t)len) break;
    if (crc32(buf.data(), len) != crc) break;
    if (!apply_payload(db, buf.data(), len)) break;
    valid_end += 12 + len;
  }
  if (ftruncate(fd, valid_end) != 0) { ::close(fd); delete db; return nullptr; }
  if (lseek(fd, valid_end, SEEK_SET) < 0) { ::close(fd); delete db; return nullptr; }
  db->fd = fd;
  db->wal_bytes = (uint64_t)valid_end;
  return db;
}

void kvlog_close(KvLog* db) {
  if (!db) return;
  if (db->fd >= 0) ::close(db->fd);
  delete db;
}

// Atomically apply + log one batch (payload = WriteBatch encoding).
int kvlog_apply(KvLog* db, const uint8_t* payload, uint32_t len) {
  std::lock_guard<std::mutex> g(db->mu);
  // Recovery rejects len > 1GiB as corruption — refuse to write what we
  // could never replay.
  if (len > (1u << 30)) return -3;
  if (!validate_payload(payload, len)) return -2;
  if (!write_record(db->fd, payload, len, db->sync_writes)) {
    // Roll back a partial append so the torn bytes can't shadow later
    // successfully-committed records at recovery time.
    if (ftruncate(db->fd, (off_t)db->wal_bytes) == 0)
      lseek(db->fd, (off_t)db->wal_bytes, SEEK_SET);
    return -1;
  }
  apply_payload(db, payload, len);
  db->wal_bytes += 12 + len;
  return 0;
}

int kvlog_get(KvLog* db, const uint8_t* key, uint32_t klen, uint8_t** val,
              uint32_t* vlen) {
  std::lock_guard<std::mutex> g(db->mu);
  auto it = db->index.find(std::string((const char*)key, klen));
  if (it == db->index.end()) return 1;
  *vlen = (uint32_t)it->second.size();
  *val = (uint8_t*)malloc(it->second.size() ? it->second.size() : 1);
  memcpy(*val, it->second.data(), it->second.size());
  return 0;
}

void kvlog_free(uint8_t* p) { free(p); }

uint64_t kvlog_count(KvLog* db) {
  std::lock_guard<std::mutex> g(db->mu);
  return db->index.size();
}

uint64_t kvlog_wal_bytes(KvLog* db) {
  std::lock_guard<std::mutex> g(db->mu);
  return db->wal_bytes;
}

// Range scan [start, end) materialized as one buffer in batch-payload
// format (all ops = put). elen==0xFFFFFFFF means unbounded end.
int kvlog_scan(KvLog* db, const uint8_t* start, uint32_t slen,
               const uint8_t* end, uint32_t elen, uint8_t** out,
               uint32_t* outlen) {
  std::lock_guard<std::mutex> g(db->mu);
  std::string lo((const char*)start, slen);
  auto it = db->index.lower_bound(lo);
  auto stop = (elen == 0xFFFFFFFFu)
                  ? db->index.end()
                  : db->index.lower_bound(std::string((const char*)end, elen));
  std::vector<uint8_t> buf;
  for (; it != stop; ++it) append_put(buf, it->first, it->second);
  *outlen = (uint32_t)buf.size();
  *out = (uint8_t*)malloc(buf.size() ? buf.size() : 1);
  memcpy(*out, buf.data(), buf.size());
  return 0;
}

// Rewrite live set into <path>.tmp, fsync, rename over the log.
int kvlog_compact(KvLog* db) {
  std::lock_guard<std::mutex> g(db->mu);
  auto payload = snapshot_payload(db);
  std::string tmp = db->path + ".tmp";
  int fd = ::open(tmp.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  if (!write_record(fd, payload.data(), (uint32_t)payload.size(), true)) {
    ::close(fd);
    return -1;
  }
  if (rename(tmp.c_str(), db->path.c_str()) != 0) { ::close(fd); return -1; }
  ::close(db->fd);
  db->fd = fd;
  db->wal_bytes = 12 + payload.size();
  return 0;
}

int kvlog_sync(KvLog* db) {
  std::lock_guard<std::mutex> g(db->mu);
  return fsync(db->fd) == 0 ? 0 : -1;
}

// Write a consistent snapshot of the live set to `path` (operator DB
// checkpoints — the RocksDB-checkpoint role of DbCheckpointManager).
int kvlog_checkpoint(KvLog* db, const char* path) {
  std::lock_guard<std::mutex> g(db->mu);
  auto payload = snapshot_payload(db);
  std::string tmp = std::string(path) + ".tmp";
  int fd = ::open(tmp.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  bool ok = write_record(fd, payload.data(), (uint32_t)payload.size(), true);
  ::close(fd);
  if (!ok || rename(tmp.c_str(), path) != 0) {
    unlink(tmp.c_str());
    return -1;
  }
  return 0;
}

}  // extern "C"
