// Batched UDP transmit — one sendmmsg(2) syscall for a whole dispatcher
// iteration's outbound datagrams.
//
// Role in the rebuild: the reference's PlainUDPCommunication
// (/root/reference/communication/src/PlainUDPCommunication.cpp:340) pays
// one sendto per message from its send thread; profiling the Python
// rebuild showed per-sendto syscall overhead dominating the consensus
// dispatcher (~10 datagrams per ordered op). Collapsing an iteration's
// sends into one kernel entry removes that per-message cost without
// changing wire behavior.
//
// Input: n records packed back-to-back, each
//   | u32 ipv4 (network byte order) | u16 port (LITTLE-endian) |
//   | u32 payload length (LITTLE-endian) | payload bytes       |
// The wire record byte order is DEFINED (little-endian for the scalar
// fields, assembled byte-by-byte below) rather than inherited from the
// host: the Python side packs with to_bytes(..., "little"), and a
// host-order memcpy here would silently byte-swap port/length on a
// big-endian host.
// Returns datagrams handed to the kernel (best-effort, like UDP), or -1
// on a malformed buffer.
#include <arpa/inet.h>
#include <cstdint>
#include <cstring>
#include <netinet/in.h>
#include <sys/socket.h>

extern "C" {

int net_sendmmsg(int fd, const uint8_t* buf, uint32_t buflen, int n) {
  if (n <= 0) return 0;
  constexpr int kMaxBatch = 64;
  mmsghdr hdrs[kMaxBatch];
  iovec iovs[kMaxBatch];
  sockaddr_in addrs[kMaxBatch];
  int sent_total = 0;
  const uint8_t* p = buf;
  const uint8_t* end = buf + buflen;
  while (n > 0) {
    const int batch = n > kMaxBatch ? kMaxBatch : n;
    for (int i = 0; i < batch; i++) {
      if (p + 10 > end) return -1;
      uint32_t ip;
      memcpy(&ip, p, 4);  // already network order: passed through as-is
      const uint16_t port = static_cast<uint16_t>(p[4] | (p[5] << 8));
      const uint32_t len = static_cast<uint32_t>(p[6]) |
                           (static_cast<uint32_t>(p[7]) << 8) |
                           (static_cast<uint32_t>(p[8]) << 16) |
                           (static_cast<uint32_t>(p[9]) << 24);
      p += 10;
      if (p + len > end) return -1;
      memset(&addrs[i], 0, sizeof(sockaddr_in));
      addrs[i].sin_family = AF_INET;
      addrs[i].sin_addr.s_addr = ip;
      addrs[i].sin_port = htons(port);
      iovs[i].iov_base = const_cast<uint8_t*>(p);
      iovs[i].iov_len = len;
      memset(&hdrs[i], 0, sizeof(mmsghdr));
      hdrs[i].msg_hdr.msg_name = &addrs[i];
      hdrs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
      hdrs[i].msg_hdr.msg_iov = &iovs[i];
      hdrs[i].msg_hdr.msg_iovlen = 1;
      p += len;
    }
    const int r = sendmmsg(fd, hdrs, batch, 0);
    if (r > 0) sent_total += r;  // partial/failed batch: UDP best-effort
    n -= batch;
  }
  return sent_total;
}

}  // extern "C"
