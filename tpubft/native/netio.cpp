// Batched UDP transmit — one sendmmsg(2) syscall for a whole dispatcher
// iteration's outbound datagrams.
//
// Role in the rebuild: the reference's PlainUDPCommunication
// (/root/reference/communication/src/PlainUDPCommunication.cpp:340) pays
// one sendto per message from its send thread; profiling the Python
// rebuild showed per-sendto syscall overhead dominating the consensus
// dispatcher (~10 datagrams per ordered op). Collapsing an iteration's
// sends into one kernel entry removes that per-message cost without
// changing wire behavior.
//
// Input: n records packed back-to-back, each
//   | u32 ipv4 (network byte order) | u16 port (host order) |
//   | u32 payload length            | payload bytes          |
// Returns datagrams handed to the kernel (best-effort, like UDP), or -1
// on a malformed buffer.
#include <arpa/inet.h>
#include <cstdint>
#include <cstring>
#include <netinet/in.h>
#include <sys/socket.h>

extern "C" {

int net_sendmmsg(int fd, const uint8_t* buf, uint32_t buflen, int n) {
  if (n <= 0) return 0;
  constexpr int kMaxBatch = 64;
  mmsghdr hdrs[kMaxBatch];
  iovec iovs[kMaxBatch];
  sockaddr_in addrs[kMaxBatch];
  int sent_total = 0;
  const uint8_t* p = buf;
  const uint8_t* end = buf + buflen;
  while (n > 0) {
    const int batch = n > kMaxBatch ? kMaxBatch : n;
    for (int i = 0; i < batch; i++) {
      if (p + 10 > end) return -1;
      uint32_t ip, len;
      uint16_t port;
      memcpy(&ip, p, 4);
      memcpy(&port, p + 4, 2);
      memcpy(&len, p + 6, 4);
      p += 10;
      if (p + len > end) return -1;
      memset(&addrs[i], 0, sizeof(sockaddr_in));
      addrs[i].sin_family = AF_INET;
      addrs[i].sin_addr.s_addr = ip;
      addrs[i].sin_port = htons(port);
      iovs[i].iov_base = const_cast<uint8_t*>(p);
      iovs[i].iov_len = len;
      memset(&hdrs[i], 0, sizeof(mmsghdr));
      hdrs[i].msg_hdr.msg_name = &addrs[i];
      hdrs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
      hdrs[i].msg_hdr.msg_iov = &iovs[i];
      hdrs[i].msg_hdr.msg_iovlen = 1;
      p += len;
    }
    const int r = sendmmsg(fd, hdrs, batch, 0);
    if (r > 0) sent_total += r;  // partial/failed batch: UDP best-effort
    n -= batch;
  }
  return sent_total;
}

}  // extern "C"
