// Batched UDP transmit/receive — one sendmmsg(2)/recvmmsg(2) syscall
// for a whole dispatcher iteration's outbound datagrams or a whole
// inbound burst.
//
// Role in the rebuild: the reference's PlainUDPCommunication
// (/root/reference/communication/src/PlainUDPCommunication.cpp:340) pays
// one sendto per message from its send thread; profiling the Python
// rebuild showed per-sendto syscall overhead dominating the consensus
// dispatcher (~10 datagrams per ordered op). Collapsing an iteration's
// sends into one kernel entry removes that per-message cost without
// changing wire behavior.
//
// Input: n records packed back-to-back, each
//   | u32 ipv4 (network byte order) | u16 port (LITTLE-endian) |
//   | u32 payload length (LITTLE-endian) | payload bytes       |
// The wire record byte order is DEFINED (little-endian for the scalar
// fields, assembled byte-by-byte below) rather than inherited from the
// host: the Python side packs with to_bytes(..., "little"), and a
// host-order memcpy here would silently byte-swap port/length on a
// big-endian host.
// Returns datagrams handed to the kernel (best-effort, like UDP), or -1
// on a malformed buffer.
#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <netinet/in.h>
#include <sys/socket.h>

extern "C" {

int net_sendmmsg(int fd, const uint8_t* buf, uint32_t buflen, int n) {
  if (n <= 0) return 0;
  constexpr int kMaxBatch = 64;
  mmsghdr hdrs[kMaxBatch];
  iovec iovs[kMaxBatch];
  sockaddr_in addrs[kMaxBatch];
  int sent_total = 0;
  const uint8_t* p = buf;
  const uint8_t* end = buf + buflen;
  while (n > 0) {
    const int batch = n > kMaxBatch ? kMaxBatch : n;
    for (int i = 0; i < batch; i++) {
      if (p + 10 > end) return -1;
      uint32_t ip;
      memcpy(&ip, p, 4);  // already network order: passed through as-is
      const uint16_t port = static_cast<uint16_t>(p[4] | (p[5] << 8));
      const uint32_t len = static_cast<uint32_t>(p[6]) |
                           (static_cast<uint32_t>(p[7]) << 8) |
                           (static_cast<uint32_t>(p[8]) << 16) |
                           (static_cast<uint32_t>(p[9]) << 24);
      p += 10;
      if (p + len > end) return -1;
      memset(&addrs[i], 0, sizeof(sockaddr_in));
      addrs[i].sin_family = AF_INET;
      addrs[i].sin_addr.s_addr = ip;
      addrs[i].sin_port = htons(port);
      iovs[i].iov_base = const_cast<uint8_t*>(p);
      iovs[i].iov_len = len;
      memset(&hdrs[i], 0, sizeof(mmsghdr));
      hdrs[i].msg_hdr.msg_name = &addrs[i];
      hdrs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
      hdrs[i].msg_hdr.msg_iov = &iovs[i];
      hdrs[i].msg_hdr.msg_iovlen = 1;
      p += len;
    }
    const int r = sendmmsg(fd, hdrs, batch, 0);
    if (r > 0) sent_total += r;  // partial/failed batch: UDP best-effort
    n -= batch;
  }
  return sent_total;
}

// Batched receive: drain every immediately-available datagram in ONE
// kernel entry (the admission plane's ingest side, mirroring the
// sendmmsg plane above; reference role: PlainUDPCommunication's
// per-recvfrom receive thread, one syscall per datagram).
//
// The caller selects()/polls for readability first, then calls this
// with MSG_DONTWAIT semantics: datagram i lands at buf + i*slot_len,
// its length in lens[i]. A datagram longer than slot_len is truncated
// by the kernel (callers size slots at max_message_size + header, so
// an over-long datagram is invalid traffic anyway; MSG_TRUNC in
// msg_flags is reflected as len = slot_len and dropped in Python by
// the sender-prefix/shape checks). Returns datagrams received, 0 when
// nothing was pending (EAGAIN), -1 on a real socket error.
int net_recvmmsg(int fd, uint8_t* buf, uint32_t slot_len, int max_n,
                 uint32_t* lens) {
  if (max_n <= 0 || slot_len == 0) return 0;
  constexpr int kMaxBatch = 64;
  if (max_n > kMaxBatch) max_n = kMaxBatch;
  mmsghdr hdrs[kMaxBatch];
  iovec iovs[kMaxBatch];
  for (int i = 0; i < max_n; i++) {
    iovs[i].iov_base = buf + static_cast<size_t>(i) * slot_len;
    iovs[i].iov_len = slot_len;
    memset(&hdrs[i], 0, sizeof(mmsghdr));
    hdrs[i].msg_hdr.msg_iov = &iovs[i];
    hdrs[i].msg_hdr.msg_iovlen = 1;
  }
  const int r = recvmmsg(fd, hdrs, max_n, MSG_DONTWAIT, nullptr);
  if (r < 0) {
    if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) return 0;
    return -1;
  }
  for (int i = 0; i < r; i++) lens[i] = hdrs[i].msg_len;
  return r;
}

}  // extern "C"
