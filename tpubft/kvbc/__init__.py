"""KVBC — the ledger layer: categorized key-value blockchain over the
storage layer, with a sparse Merkle tree for state proofs.

Rebuild of /root/reference/kvbc/ (categorized KeyValueBlockchain,
kvbc/include/categorization/kv_blockchain.h:40; sparse_merkle/tree.cpp),
TPU-first: bulk digests (Merkle levels, block hashing) go through the
batched SHA-256 kernel (tpubft/ops/sha256.py) instead of per-node CPU
hashing.
"""
from tpubft.kvbc.blockchain import KeyValueBlockchain
from tpubft.kvbc.categories import (BLOCK_MERKLE, IMMUTABLE, VERSIONED_KV,
                                    BlockUpdates, CategoryUpdates)
from tpubft.kvbc.sparse_merkle import SparseMerkleTree

__all__ = ["KeyValueBlockchain", "SparseMerkleTree", "BlockUpdates",
           "CategoryUpdates", "BLOCK_MERKLE", "VERSIONED_KV", "IMMUTABLE"]
