"""KVBC — the ledger layer: categorized key-value blockchain over the
storage layer, with a sparse Merkle tree for state proofs.

Rebuild of /root/reference/kvbc/ (categorized KeyValueBlockchain,
kvbc/include/categorization/kv_blockchain.h:40; sparse_merkle/tree.cpp),
TPU-first: bulk digests (Merkle levels, block hashing) go through the
batched SHA-256 kernel (tpubft/ops/sha256.py) instead of per-node CPU
hashing.
"""
from tpubft.kvbc.blockchain import KeyValueBlockchain
from tpubft.kvbc.categories import (BLOCK_MERKLE, IMMUTABLE, VERSIONED_KV,
                                    BlockUpdates, CategoryUpdates)
from tpubft.kvbc.sparse_merkle import SparseMerkleTree
from tpubft.kvbc.v4 import V4KeyValueBlockchain


def create_blockchain(db, version: str = "categorized",
                      use_device_hashing: bool = True):
    """Engine-selecting facade (reference kvbc_adapter,
    /root/reference/kvbc/src/kvbc_adapter/): one call site picks the
    categorized engine (multi-version reads + sparse-Merkle proofs) or
    the v4 engine (latest-keys-native, write-optimized) behind the same
    interface."""
    if version in ("categorized", "v2"):
        return KeyValueBlockchain(db, use_device_hashing=use_device_hashing)
    if version == "v4":
        return V4KeyValueBlockchain(db)
    if version in ("v1", "direct"):
        from tpubft.kvbc.v1 import DirectKVBlockchain
        return DirectKVBlockchain(db)
    raise ValueError(f"unknown kvbc version {version!r}")


__all__ = ["KeyValueBlockchain", "V4KeyValueBlockchain", "create_blockchain",
           "SparseMerkleTree", "BlockUpdates",
           "CategoryUpdates", "BLOCK_MERKLE", "VERSIONED_KV", "IMMUTABLE"]
