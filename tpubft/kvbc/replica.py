"""KvbcReplica — the process object wiring consensus + ledger + storage.

Rebuild of `concord::kvbc::Replica` (/root/reference/kvbc/include/Replica.h:42,
src/Replica.cpp): owns the DB backend, the categorized blockchain, the
consensus engine (whose persistent metadata lands in the same DB via
DBPersistentStorage), and the command handler that executes ordered
requests against the blockchain. The same inversion as the reference:
this object sits *above* the consensus engine it creates while also
implementing its execution upcall.
"""
from __future__ import annotations

import os
from typing import Optional

from tpubft.comm.interfaces import ICommunication
from tpubft.consensus.keys import ClusterKeys
from tpubft.consensus.replica import IRequestsHandler, Replica
from tpubft.kvbc.blockchain import KeyValueBlockchain
from tpubft.storage.interfaces import IDBClient
from tpubft.storage.memorydb import MemoryDB
from tpubft.storage.metadata import DBPersistentStorage
from tpubft.utils.config import ReplicaConfig
from tpubft.utils.metrics import Aggregator


def open_db(db_path: Optional[str],
            sync_writes: bool = False,
            sync_families=()) -> IDBClient:
    """Storage factory (reference: kvbc storage factories — RocksDB for
    production, memorydb for tests). `sync_writes` mirrors RocksDB
    WriteOptions.sync (reference leaves it false); `sync_families` keeps
    the named families fsync-durable regardless (the consensus-metadata
    carve-out)."""
    if db_path is None:
        return MemoryDB()
    from tpubft.storage.native import NativeDB
    os.makedirs(os.path.dirname(db_path) or ".", exist_ok=True)
    return NativeDB(db_path, sync_writes=sync_writes,
                    sync_families=sync_families)


class KvbcReplica:
    def __init__(self, cfg: ReplicaConfig, keys: ClusterKeys,
                 comm: ICommunication,
                 db_path: Optional[str] = None,
                 handler_factory=None,
                 aggregator: Optional[Aggregator] = None,
                 use_device_hashing: Optional[bool] = None,
                 thin_replica_port: Optional[int] = None) -> None:
        from tpubft.storage.metadata import CONSENSUS_META_FAMILIES
        self.db = open_db(
            db_path,
            sync_writes=getattr(cfg, "db_sync_writes", False),
            sync_families=(CONSENSUS_META_FAMILIES
                           if getattr(cfg, "db_sync_metadata", True)
                           else ()))
        from tpubft.kvbc import create_blockchain
        # resolve "auto" BEFORE the hashing decision below reads it (the
        # consensus Replica performs the same write-back; both orderings
        # must agree)
        from tpubft.crypto.backend import resolve_backend
        cfg.crypto_backend = resolve_backend(cfg.crypto_backend)
        if use_device_hashing is None:
            # device-backed crypto implies device-backed bulk hashing —
            # Merkle levels and block digests ride the batched SHA-256
            # kernel alongside the signature kernels
            use_device_hashing = cfg.crypto_backend == "tpu"
        self.blockchain = create_blockchain(
            self.db, version=getattr(cfg, "kvbc_version", "categorized"),
            use_device_hashing=use_device_hashing)
        if handler_factory is None:
            from tpubft.apps.skvbc import SkvbcHandler
            handler_factory = SkvbcHandler
        self.handler: IRequestsHandler = handler_factory(self.blockchain)
        from tpubft.consensus.reserved_pages import ReservedPages
        # pages share the LEDGER's DB on purpose: the execution lane
        # folds each run's reply-ring/marker pages into the ledger's
        # accumulated WriteBatch (ReservedPages.shares_db), so a run's
        # durable apply is atomic across blocks and at-most-once state —
        # a crash can never see blocks without their reply markers or
        # vice versa. Splitting pages into their own DB silently
        # downgrades that to two ordered batches.
        pages = ReservedPages(self.db)
        if thin_replica_port is not None:
            # the CLI port must win over cfg.thin_replica_port even
            # when thin_replica_enabled makes the Replica constructor
            # attach the server itself
            cfg.thin_replica_port = thin_replica_port
        self.replica = Replica(cfg, keys, comm, self.handler,
                               storage=DBPersistentStorage(self.db),
                               aggregator=aggregator,
                               reserved_pages=pages)
        from tpubft.statetransfer import StateTransferManager
        from tpubft.statetransfer.manager import StConfig
        self.state_transfer = StateTransferManager(
            cfg.replica_id, self.blockchain,
            StConfig(fetch_batch_blocks=cfg.state_transfer_batch_blocks,
                     max_chunk_bytes=cfg.max_block_chunk_bytes,
                     window_ranges=cfg.st_window_ranges,
                     device_digest_threshold=cfg.st_device_digest_threshold,
                     use_device_digests=use_device_hashing),
            reserved_pages=pages, aggregator=aggregator)
        self.replica.set_state_transfer(self.state_transfer)
        from tpubft.reconfiguration.dispatcher import standard_dispatcher
        ckpt_dir = (os.path.join(os.path.dirname(db_path), "db_checkpoints")
                    if db_path else "db_checkpoints")
        self.replica.set_reconfiguration(standard_dispatcher(
            blockchain=self.blockchain, db=self.db,
            db_checkpoint_dir=ckpt_dir))

        # thin-replica read tier: the consensus Replica owns the server
        # (commit-stream feed + signed checkpoint anchor + metrics live
        # there). The explicit port arg (process CLI --trs-port) wins:
        # it is written into cfg BEFORE the Replica constructor runs
        # (see above), so a thin_replica_enabled config attaches at the
        # CLI port; without the knob, attach explicitly here.
        if thin_replica_port is not None \
                and self.replica.thin_replica is None:
            self.replica.attach_thin_replica(port=thin_replica_port)
        self.thin_replica_server = self.replica.thin_replica

    def start(self) -> None:
        self.replica.start()

    def stop(self) -> None:
        self.replica.stop()
        self.db.close()
