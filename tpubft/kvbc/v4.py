"""v4 blockchain engine — latest-keys-native, write-optimized.

Rebuild of the reference's `concord::kvbc::v4blockchain::KeyValueBlockchain`
(/root/reference/kvbc/src/v4blockchain/v4_blockchain.cpp:847 +
detail/{blockchain,latest_keys,st_chain}.cpp): three keyspaces —

  * ``blockchain``  — block id → full serialized block (the only history);
  * ``latest_keys`` — (category, key) → latest value stamped with its
                      version, giving O(1) latest reads and cheap writes
                      (no per-version history rows, no Merkle tree
                      maintenance — the categorized engine's costs);
  * ``st_chain``    — out-of-order state-transfer staging.

Historical reads walk the block store backward (the reference reads
through the ``blockchain`` column family the same way); there are no
Merkle proofs in v4 — ``prove``/``merkle_root`` raise, and callers that
need proofs configure the categorized engine (kvbc_adapter role,
reference src/kvbc_adapter/).

The public surface mirrors KeyValueBlockchain so the two engines are
interchangeable behind ``tpubft.kvbc.create_blockchain``.
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

from tpubft.kvbc import categories as cat
from tpubft.kvbc.blockchain import (Block, BlockchainError, BlockStoreMixin,
                                    _bid)
from tpubft.storage.interfaces import IDBClient, WriteBatch

_BLOCKS = b"v4.blocks"
_LATEST = b"v4.latest"
_TAGS = b"v4.tags"
_MISC = b"v4.misc"
_ST = b"v4.st"


def _lk(category: str, key: bytes) -> bytes:
    """latest_keys row key: category-scoped (reference latest_keys.cpp
    prefixes keys with the category id)."""
    c = category.encode()
    return len(c).to_bytes(2, "big") + c + key


def _tag_row(category: str, tag: str, key: bytes) -> bytes:
    """Tag index row: category-scoped like the categorized engine's
    _fam(category, 'tag') family — tags never leak across categories."""
    c, t = category.encode(), tag.encode()
    return (len(c).to_bytes(2, "big") + c
            + len(t).to_bytes(4, "big") + t + key)


class V4KeyValueBlockchain(BlockStoreMixin):
    """Write-optimized engine: one latest-keys write per key per block."""

    VERSION = "v4"
    _F_BLOCKS = _BLOCKS
    _F_MISC = _MISC
    _F_ST = _ST

    def __init__(self, db: IDBClient,
                 use_device_hashing: bool = False) -> None:
        del use_device_hashing          # no Merkle trees to accelerate
        self._db = db
        self._load_head()

    def _stage_block(self, wb: WriteBatch, block_id: int,
                     updates: cat.BlockUpdates) -> Block:
        digests: Dict[str, bytes] = {}
        ver = block_id.to_bytes(8, "big")
        for name in sorted(updates.categories):
            cat_type, cu = updates.categories[name]
            h = hashlib.sha256()
            for k in sorted(cu.kv):
                v = cu.kv[k]
                row = _lk(name, k)
                if cat_type == cat.IMMUTABLE:
                    if v is None:
                        raise cat.CategoryError(
                            "immutable category cannot delete")
                    if self._db.get(row, _LATEST) is not None:
                        raise cat.CategoryError(
                            f"immutable key rewrite: {k!r}")
                    for tag in cu.tags.get(k, []):
                        wb.put(_tag_row(name, tag, k), v, _TAGS)
                if v is None:
                    wb.delete(row, _LATEST)
                    h.update(b"\x00" + len(k).to_bytes(4, "big") + k)
                else:
                    wb.put(row, ver + v, _LATEST)
                    h.update(b"\x01" + len(k).to_bytes(4, "big") + k
                             + hashlib.sha256(v).digest())
            digests[name] = h.digest()
        parent = self.block_digest(block_id - 1) if block_id > 1 else b""
        block = Block(block_id=block_id, parent_digest=parent,
                      category_digests=digests,
                      updates_blob=cat.encode_block_updates(updates))
        self._put_block_row(wb, block_id, block)
        return block

    # ---- v4 reads ----
    def get_latest(self, category: str, key: bytes,
                   cat_type: str = cat.VERSIONED_KV
                   ) -> Optional[Tuple[int, bytes]]:
        raw = self._db.get(_lk(category, key), _LATEST)
        if raw is None:
            return None
        return int.from_bytes(raw[:8], "big"), raw[8:]

    def get_versioned(self, category: str, key: bytes,
                      block_id: int) -> Optional[bytes]:
        """Newest write with version <= block_id — walks the block store
        backward (v4 keeps no per-version rows; the reference reads
        history through the blockchain CF the same way)."""
        latest = self.get_latest(category, key)
        if latest is not None and latest[0] <= block_id:
            return latest[1]
        lo = self._genesis if self._genesis else 1
        for bid in range(min(block_id, self._last), lo - 1, -1):
            blk = self.get_block(bid)
            if blk is None:
                return None                 # pruned past this point
            entry = cat.decode_block_updates(blk.updates_blob)
            got = entry.categories.get(category)
            if got is not None and key in got[1].kv:
                return got[1].kv[key]
        return None

    def get_tagged(self, category: str, tag: str
                   ) -> List[Tuple[bytes, bytes]]:
        prefix = _tag_row(category, tag, b"")
        out = []
        for k, v in self._db.range_iter(_TAGS, start=prefix):
            if not k.startswith(prefix):
                break
            out.append((k[len(prefix):], v))
        return out

    def prove(self, category: str, key: bytes):
        raise BlockchainError(
            "v4 engine keeps no Merkle trees; configure the categorized "
            "engine for proofs (kvbc_adapter role)")

    def merkle_root(self, category: str) -> bytes:
        raise BlockchainError(
            "v4 engine keeps no Merkle trees; configure the categorized "
            "engine for proofs (kvbc_adapter role)")
