"""v4 blockchain engine — latest-keys-native, write-optimized.

Rebuild of the reference's `concord::kvbc::v4blockchain::KeyValueBlockchain`
(/root/reference/kvbc/src/v4blockchain/v4_blockchain.cpp:847 +
detail/{blockchain,latest_keys,st_chain}.cpp): three keyspaces —

  * ``blockchain``  — block id → full serialized block (the only history);
  * ``latest_keys`` — (category, key) → latest value stamped with its
                      version, giving O(1) latest reads and cheap writes
                      (no per-version history rows, no Merkle tree
                      maintenance — the categorized engine's costs);
  * ``st_chain``    — out-of-order state-transfer staging.

Historical reads walk the block store backward (the reference reads
through the ``blockchain`` column family the same way); there are no
Merkle proofs in v4 — ``prove``/``merkle_root`` raise, and callers that
need proofs configure the categorized engine (kvbc_adapter role,
reference src/kvbc_adapter/).

The public surface mirrors KeyValueBlockchain so the two engines are
interchangeable behind ``tpubft.kvbc.create_blockchain``.
"""
from __future__ import annotations

import hashlib
from typing import Callable, Dict, List, Optional, Tuple

from tpubft.kvbc import categories as cat
from tpubft.kvbc.blockchain import Block, BlockchainError, _bid
from tpubft.storage.interfaces import IDBClient, WriteBatch
from tpubft.utils import serialize as ser

_BLOCKS = b"v4.blocks"
_LATEST = b"v4.latest"
_TAGS = b"v4.tags"
_MISC = b"v4.misc"
_ST = b"v4.st"

_K_LAST = b"last"
_K_GENESIS = b"genesis"


def _lk(category: str, key: bytes) -> bytes:
    """latest_keys row key: category-scoped (reference latest_keys.cpp
    prefixes keys with the category id)."""
    c = category.encode()
    return len(c).to_bytes(2, "big") + c + key


class V4KeyValueBlockchain:
    """Write-optimized engine: one latest-keys write per key per block."""

    VERSION = "v4"

    def __init__(self, db: IDBClient,
                 use_device_hashing: bool = False) -> None:
        del use_device_hashing          # no Merkle trees to accelerate
        self._db = db
        self._listeners: List[Callable[[int, cat.BlockUpdates], None]] = []
        last = db.get(_K_LAST, _MISC)
        self._last = int.from_bytes(last, "big") if last else 0
        gen = db.get(_K_GENESIS, _MISC)
        self._genesis = int.from_bytes(gen, "big") if gen else 0

    # ---- properties ----
    @property
    def last_block_id(self) -> int:
        return self._last

    @property
    def genesis_block_id(self) -> int:
        return self._genesis

    # ---- write path ----
    def add_listener(self,
                     fn: Callable[[int, cat.BlockUpdates], None]) -> None:
        self._listeners.append(fn)

    def _notify(self, block_id: int, updates: cat.BlockUpdates) -> None:
        for fn in self._listeners:
            try:
                fn(block_id, updates)
            except Exception:  # noqa: BLE001 — listeners must not break commit
                pass

    def add_block(self, updates: cat.BlockUpdates) -> int:
        block_id = self._last + 1
        wb = WriteBatch()
        self._stage_block(wb, block_id, updates)
        self._db.write(wb)
        self._last = block_id
        if self._genesis == 0:
            self._genesis = 1
        self._notify(block_id, updates)
        return block_id

    def _stage_block(self, wb: WriteBatch, block_id: int,
                     updates: cat.BlockUpdates) -> Block:
        digests: Dict[str, bytes] = {}
        ver = block_id.to_bytes(8, "big")
        for name in sorted(updates.categories):
            cat_type, cu = updates.categories[name]
            h = hashlib.sha256()
            for k in sorted(cu.kv):
                v = cu.kv[k]
                row = _lk(name, k)
                if cat_type == cat.IMMUTABLE:
                    if v is None:
                        raise cat.CategoryError(
                            "immutable category cannot delete")
                    if self._db.get(row, _LATEST) is not None:
                        raise cat.CategoryError(
                            f"immutable key rewrite: {k!r}")
                    for tag in cu.tags.get(k, []):
                        tb = tag.encode()
                        wb.put(len(tb).to_bytes(4, "big") + tb + k, v,
                               _TAGS)
                if v is None:
                    wb.delete(row, _LATEST)
                    h.update(b"\x00" + len(k).to_bytes(4, "big") + k)
                else:
                    wb.put(row, ver + v, _LATEST)
                    h.update(b"\x01" + len(k).to_bytes(4, "big") + k
                             + hashlib.sha256(v).digest())
            digests[name] = h.digest()
        parent = self.block_digest(block_id - 1) if block_id > 1 else b""
        block = Block(block_id=block_id, parent_digest=parent,
                      category_digests=digests,
                      updates_blob=cat.encode_block_updates(updates))
        wb.put(_bid(block_id), ser.encode_msg(block), _BLOCKS)
        wb.put(_K_LAST, _bid(block_id), _MISC)
        if block_id == 1:
            wb.put(_K_GENESIS, _bid(1), _MISC)
        return block

    # ---- read path ----
    def get_block(self, block_id: int) -> Optional[Block]:
        raw = self._db.get(_bid(block_id), _BLOCKS)
        return ser.decode_msg(raw, Block) if raw is not None else None

    def get_raw_block(self, block_id: int) -> Optional[bytes]:
        return self._db.get(_bid(block_id), _BLOCKS)

    def block_digest(self, block_id: int) -> bytes:
        if block_id == 0:
            return b""
        blk = self.get_block(block_id)
        if blk is None:
            raise BlockchainError(f"missing block {block_id}")
        return blk.digest()

    def state_digest(self) -> bytes:
        return self.block_digest(self._last) if self._last else b"\x00" * 32

    def get_latest(self, category: str, key: bytes,
                   cat_type: str = cat.VERSIONED_KV
                   ) -> Optional[Tuple[int, bytes]]:
        raw = self._db.get(_lk(category, key), _LATEST)
        if raw is None:
            return None
        return int.from_bytes(raw[:8], "big"), raw[8:]

    def get_versioned(self, category: str, key: bytes,
                      block_id: int) -> Optional[bytes]:
        """Newest write with version <= block_id — walks the block store
        backward (v4 keeps no per-version rows; the reference reads
        history through the blockchain CF the same way)."""
        latest = self.get_latest(category, key)
        if latest is not None and latest[0] <= block_id:
            return latest[1]
        lo = self._genesis if self._genesis else 1
        for bid in range(min(block_id, self._last), lo - 1, -1):
            blk = self.get_block(bid)
            if blk is None:
                return None                 # pruned past this point
            entry = cat.decode_block_updates(blk.updates_blob)
            got = entry.categories.get(category)
            if got is not None and key in got[1].kv:
                return got[1].kv[key]
        return None

    def get_tagged(self, category: str, tag: str
                   ) -> List[Tuple[bytes, bytes]]:
        tb = tag.encode()
        prefix = len(tb).to_bytes(4, "big") + tb
        out = []
        for k, v in self._db.range_iter(_TAGS, start=prefix):
            if not k.startswith(prefix):
                break
            out.append((k[len(prefix):], v))
        return out

    def prove(self, category: str, key: bytes):
        raise BlockchainError(
            "v4 engine keeps no Merkle trees; configure the categorized "
            "engine for proofs (kvbc_adapter role)")

    def merkle_root(self, category: str) -> bytes:
        raise BlockchainError(
            "v4 engine keeps no Merkle trees; configure the categorized "
            "engine for proofs (kvbc_adapter role)")

    # ---- pruning ----
    def delete_blocks_until(self, until_block_id: int) -> int:
        if until_block_id > self._last:
            raise BlockchainError("cannot prune the chain head")
        start = self._genesis if self._genesis else 1
        if until_block_id <= start:
            return self._genesis
        wb = WriteBatch()
        for bid in range(start, until_block_id):
            wb.delete(_bid(bid), _BLOCKS)
        wb.put(_K_GENESIS, _bid(until_block_id), _MISC)
        self._db.write(wb)
        self._genesis = until_block_id
        return self._genesis

    # ---- state-transfer staging (st_chain.cpp) ----
    def add_raw_st_block(self, block_id: int, raw: bytes) -> None:
        if block_id <= self._last:
            return
        self._db.put(_bid(block_id), raw, _ST)

    def has_st_block(self, block_id: int) -> bool:
        return self._db.has(_bid(block_id), _ST)

    def link_st_chain(self) -> int:
        while True:
            nxt = self._last + 1
            raw = self._db.get(_bid(nxt), _ST)
            if raw is None:
                return self._last
            try:
                blk = ser.decode_msg(raw, Block)
                if blk.block_id != nxt:
                    raise BlockchainError(
                        f"staged block id mismatch: {blk.block_id} != {nxt}")
                expect_parent = (self.block_digest(self._last)
                                 if self._last else b"")
                if blk.parent_digest != expect_parent:
                    raise BlockchainError(f"parent digest mismatch at {nxt}")
                updates = cat.decode_block_updates(blk.updates_blob)
                wb = WriteBatch()
                rebuilt = self._stage_block(wb, nxt, updates)
                if rebuilt.category_digests != blk.category_digests:
                    raise BlockchainError(
                        f"category digest mismatch at {nxt}")
            except Exception:
                self._db.delete(_bid(nxt), _ST)
                raise
            wb.delete(_bid(nxt), _ST)
            self._db.write(wb)
            self._last = nxt
            if self._genesis == 0:
                self._genesis = 1
            self._notify(nxt, updates)
