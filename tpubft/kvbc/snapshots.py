"""State snapshots — whole-state export/import for replica provisioning.

Rebuild of the reference's state-snapshot surface
(/root/reference/kvbc/include/kvbc_app_filter/... state_snapshot_interface.hpp,
the RocksDB-checkpoint-based DbCheckpointManager stream, and the
clientservice state-snapshot gRPC service): a snapshot captures the FULL
storage state (every family — ledger, latest indexes, Merkle nodes,
reserved pages, consensus metadata excluded by filter) into one
self-verifying file a new replica can be provisioned from without
replaying history.

File layout: header JSON line (version, head block, state digest, entry
count) then length-prefixed (family, key, value) records, then a trailing
sha256 over everything before it.
"""
from __future__ import annotations

import hashlib
import json
import os
import struct
import tempfile
from typing import Callable, Optional

from tpubft.storage.interfaces import IDBClient, WriteBatch

MAGIC = b"TPUBFT-SNAPSHOT-1\n"

# families holding per-process consensus metadata a NEW replica must not
# inherit (it would impersonate the source's protocol position)
_DEFAULT_EXCLUDE = (b"metadata",)


class SnapshotError(Exception):
    pass


def _rec(fam: bytes, key: bytes, val: bytes) -> bytes:
    return struct.pack("<HII", len(fam), len(key), len(val)) + fam + key + val


def create_snapshot(db: IDBClient, path: str,
                    head_block: int = 0, state_digest: bytes = b"",
                    exclude: tuple = _DEFAULT_EXCLUDE,
                    filter_fn: Optional[Callable[[bytes], bool]] = None
                    ) -> dict:
    """Stream the store into `path` (atomic: tmp + rename). Returns the
    manifest."""
    # streamed, O(1) memory: records spill to a spool file first (the
    # entry count must precede them in the final layout), then the final
    # file is assembled chunk-wise with an incremental digest — a multi-GB
    # ledger never materializes in RAM
    count = 0
    dirname = os.path.dirname(path) or "."
    sfd, spool = tempfile.mkstemp(dir=dirname)
    tmp = None
    try:
        with os.fdopen(sfd, "wb") as sp:
            for fam, key, val in db.scan_all():
                if fam in exclude:       # exact family match
                    continue
                if filter_fn is not None and not filter_fn(fam):
                    continue
                sp.write(_rec(fam, key, val))
                count += 1
        manifest = {"version": 1, "head_block": head_block,
                    "state_digest": state_digest.hex(),
                    "entries": count}
        h = hashlib.sha256()
        fd, tmp = tempfile.mkstemp(dir=dirname)
        with os.fdopen(fd, "wb") as out, open(spool, "rb") as sp:
            header = MAGIC + json.dumps(manifest).encode() + b"\n"
            out.write(header)
            h.update(header)
            while True:
                chunk = sp.read(1 << 20)
                if not chunk:
                    break
                out.write(chunk)
                h.update(chunk)
            out.write(h.digest())
            out.flush()
            os.fsync(out.fileno())
        os.replace(tmp, path)
    except BaseException:
        if tmp is not None and os.path.exists(tmp):
            os.unlink(tmp)
        raise
    finally:
        if os.path.exists(spool):
            os.unlink(spool)
    return manifest


def read_manifest(path: str) -> dict:
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise SnapshotError("not a tpubft snapshot")
        return json.loads(f.readline().decode())


def restore_snapshot(path: str, db: IDBClient,
                     batch_entries: int = 1024) -> dict:
    """Stream-verify integrity while populating `db` (must be empty of
    the snapshot's families) — two sequential passes over the file, O(1)
    memory. Returns the manifest.

    The digest, record framing, AND manifest entry count are all checked
    in a FIRST full pass before any write reaches the DB, so a corrupt
    or self-inconsistent snapshot never leaves a half-restored store."""
    size = os.path.getsize(path)
    if size < len(MAGIC) + 32:
        raise SnapshotError("truncated snapshot")
    body_len = size - 32
    # pass 1: integrity + framing + count — no DB writes yet
    h = hashlib.sha256()
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise SnapshotError("not a tpubft snapshot")
        h.update(magic)
        header = f.readline()
        h.update(header)
        try:
            manifest = json.loads(header.decode())
            expected_entries = int(manifest["entries"])
        except (UnicodeDecodeError, ValueError, KeyError, TypeError) as e:
            raise SnapshotError(f"corrupt snapshot header: {e}") from e
        counted = 0
        while f.tell() < body_len:
            hdr = f.read(10)
            if len(hdr) != 10 or f.tell() > body_len:
                raise SnapshotError("corrupt record")
            fl, kl, vl = struct.unpack("<HII", hdr)
            if f.tell() + fl + kl + vl > body_len:
                raise SnapshotError("corrupt record")
            body = f.read(fl + kl + vl)
            h.update(hdr)
            h.update(body)
            counted += 1
        if f.read(32) != h.digest():
            raise SnapshotError("snapshot integrity check failed")
        if counted != expected_entries:
            raise SnapshotError(
                f"entry count mismatch: {counted} != {expected_entries}")
    # pass 2: restore (file already fully validated)
    with open(path, "rb") as f:
        f.read(len(MAGIC))
        f.readline()
        wb = WriteBatch()
        while f.tell() < body_len:
            fl, kl, vl = struct.unpack("<HII", f.read(10))
            fam = f.read(fl)
            key = f.read(kl)
            val = f.read(vl)
            wb.put(key, val, fam)
            if len(wb) >= batch_entries:
                db.write(wb)
                wb = WriteBatch()
    if len(wb):
        db.write(wb)
    return manifest
