"""State snapshots — whole-state export/import for replica provisioning.

Rebuild of the reference's state-snapshot surface
(/root/reference/kvbc/include/kvbc_app_filter/... state_snapshot_interface.hpp,
the RocksDB-checkpoint-based DbCheckpointManager stream, and the
clientservice state-snapshot gRPC service): a snapshot captures the FULL
storage state (every family — ledger, latest indexes, Merkle nodes,
reserved pages, consensus metadata excluded by filter) into one
self-verifying file a new replica can be provisioned from without
replaying history.

File layout: header JSON line (version, head block, state digest, entry
count) then length-prefixed (family, key, value) records, then a trailing
sha256 over everything before it.
"""
from __future__ import annotations

import hashlib
import json
import os
import struct
import tempfile
from typing import Callable, Optional

from tpubft.storage.interfaces import IDBClient, WriteBatch

MAGIC = b"TPUBFT-SNAPSHOT-1\n"

# families holding per-process consensus metadata a NEW replica must not
# inherit (it would impersonate the source's protocol position)
_DEFAULT_EXCLUDE = (b"metadata",)


class SnapshotError(Exception):
    pass


def _rec(fam: bytes, key: bytes, val: bytes) -> bytes:
    return struct.pack("<HII", len(fam), len(key), len(val)) + fam + key + val


def create_snapshot(db: IDBClient, path: str,
                    head_block: int = 0, state_digest: bytes = b"",
                    exclude: tuple = _DEFAULT_EXCLUDE,
                    filter_fn: Optional[Callable[[bytes], bool]] = None
                    ) -> dict:
    """Stream the store into `path` (atomic: tmp + rename). Returns the
    manifest."""
    h = hashlib.sha256()
    count = 0
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "wb") as out:
            body = []
            for fam, key, val in db.scan_all():
                if any(fam.startswith(e) for e in exclude):
                    continue
                if filter_fn is not None and not filter_fn(fam):
                    continue
                body.append(_rec(fam, key, val))
                count += 1
            manifest = {"version": 1, "head_block": head_block,
                        "state_digest": state_digest.hex(),
                        "entries": count}
            header = MAGIC + json.dumps(manifest).encode() + b"\n"
            out.write(header)
            h.update(header)
            for rec in body:
                out.write(rec)
                h.update(rec)
            out.write(h.digest())
            out.flush()
            os.fsync(out.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return manifest


def read_manifest(path: str) -> dict:
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise SnapshotError("not a tpubft snapshot")
        return json.loads(f.readline().decode())


def restore_snapshot(path: str, db: IDBClient,
                     batch_entries: int = 1024) -> dict:
    """Verify integrity, then populate `db` (must be empty of the
    snapshot's families). Returns the manifest."""
    with open(path, "rb") as f:
        data = f.read()
    if not data.startswith(MAGIC):
        raise SnapshotError("not a tpubft snapshot")
    if len(data) < 32:
        raise SnapshotError("truncated snapshot")
    body, tail = data[:-32], data[-32:]
    if hashlib.sha256(body).digest() != tail:
        raise SnapshotError("snapshot integrity check failed")
    nl = body.index(b"\n", len(MAGIC))
    manifest = json.loads(body[len(MAGIC):nl].decode())
    off = nl + 1
    wb = WriteBatch()
    seen = 0
    while off < len(body):
        if off + 10 > len(body):
            raise SnapshotError("corrupt record header")
        fl, kl, vl = struct.unpack_from("<HII", body, off)
        off += 10
        if off + fl + kl + vl > len(body):
            raise SnapshotError("corrupt record body")
        fam = body[off:off + fl]
        off += fl
        key = body[off:off + kl]
        off += kl
        val = body[off:off + vl]
        off += vl
        wb.put(key, val, fam)
        seen += 1
        if len(wb) >= batch_entries:
            db.write(wb)
            wb = WriteBatch()
    if len(wb):
        db.write(wb)
    if seen != manifest["entries"]:
        raise SnapshotError(
            f"entry count mismatch: {seen} != {manifest['entries']}")
    return manifest
