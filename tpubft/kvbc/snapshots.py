"""State snapshots — whole-state export/import for replica provisioning.

Rebuild of the reference's state-snapshot surface
(/root/reference/kvbc/include/kvbc_app_filter/... state_snapshot_interface.hpp,
the RocksDB-checkpoint-based DbCheckpointManager stream, and the
clientservice state-snapshot gRPC service): a snapshot captures the FULL
storage state (every family — ledger, latest indexes, Merkle nodes,
reserved pages, consensus metadata excluded by filter) into one
self-verifying file a new replica can be provisioned from without
replaying history.

File layout: header JSON line (version, head block, state digest, entry
count) then length-prefixed (family, key, value) records, then a trailing
sha256 over everything before it.
"""
from __future__ import annotations

import hashlib
import json
import os
import struct
import tempfile
from typing import Callable, Optional

from tpubft.storage.interfaces import IDBClient, WriteBatch

MAGIC = b"TPUBFT-SNAPSHOT-1\n"

# families holding per-process consensus metadata a NEW replica must not
# inherit (it would impersonate the source's protocol position)
_DEFAULT_EXCLUDE = (b"metadata",)


class SnapshotError(Exception):
    pass


def _rec(fam: bytes, key: bytes, val: bytes) -> bytes:
    return struct.pack("<HII", len(fam), len(key), len(val)) + fam + key + val


def create_snapshot(db: IDBClient, path: str,
                    head_block: int = 0, state_digest: bytes = b"",
                    exclude: tuple = _DEFAULT_EXCLUDE,
                    filter_fn: Optional[Callable[[bytes], bool]] = None
                    ) -> dict:
    """Stream the store into `path` (atomic: tmp + rename). Returns the
    manifest."""
    # streamed, O(1) memory: records spill to a spool file first (the
    # entry count must precede them in the final layout), then the final
    # file is assembled chunk-wise with an incremental digest — a multi-GB
    # ledger never materializes in RAM
    count = 0
    dirname = os.path.dirname(path) or "."
    sfd, spool = tempfile.mkstemp(dir=dirname)
    tmp = None
    try:
        with os.fdopen(sfd, "wb") as sp:
            for fam, key, val in db.scan_all():
                if any(fam.startswith(e) for e in exclude):
                    continue
                if filter_fn is not None and not filter_fn(fam):
                    continue
                sp.write(_rec(fam, key, val))
                count += 1
        manifest = {"version": 1, "head_block": head_block,
                    "state_digest": state_digest.hex(),
                    "entries": count}
        h = hashlib.sha256()
        fd, tmp = tempfile.mkstemp(dir=dirname)
        with os.fdopen(fd, "wb") as out, open(spool, "rb") as sp:
            header = MAGIC + json.dumps(manifest).encode() + b"\n"
            out.write(header)
            h.update(header)
            while True:
                chunk = sp.read(1 << 20)
                if not chunk:
                    break
                out.write(chunk)
                h.update(chunk)
            out.write(h.digest())
            out.flush()
            os.fsync(out.fileno())
        os.replace(tmp, path)
    except BaseException:
        if tmp is not None and os.path.exists(tmp):
            os.unlink(tmp)
        raise
    finally:
        if os.path.exists(spool):
            os.unlink(spool)
    return manifest


def read_manifest(path: str) -> dict:
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise SnapshotError("not a tpubft snapshot")
        return json.loads(f.readline().decode())


def restore_snapshot(path: str, db: IDBClient,
                     batch_entries: int = 1024) -> dict:
    """Stream-verify integrity while populating `db` (must be empty of
    the snapshot's families) — two sequential passes over the file, O(1)
    memory. Returns the manifest.

    The digest is checked in a FIRST full pass before any write reaches
    the DB, so a corrupt snapshot never leaves a half-restored store."""
    size = os.path.getsize(path)
    if size < len(MAGIC) + 32:
        raise SnapshotError("truncated snapshot")
    body_len = size - 32
    # pass 1: integrity
    h = hashlib.sha256()
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise SnapshotError("not a tpubft snapshot")
        h.update(magic)
        remaining = body_len - len(MAGIC)
        while remaining:
            chunk = f.read(min(1 << 20, remaining))
            if not chunk:
                raise SnapshotError("truncated snapshot")
            h.update(chunk)
            remaining -= len(chunk)
        if f.read(32) != h.digest():
            raise SnapshotError("snapshot integrity check failed")
    # pass 2: restore
    with open(path, "rb") as f:
        f.read(len(MAGIC))
        manifest = json.loads(f.readline().decode())
        wb = WriteBatch()
        seen = 0

        def need(n: int) -> bytes:
            if f.tell() + n > body_len:
                raise SnapshotError("corrupt record")
            return f.read(n)

        while f.tell() < body_len:
            fl, kl, vl = struct.unpack("<HII", need(10))
            fam = need(fl)
            key = need(kl)
            val = need(vl)
            wb.put(key, val, fam)
            seen += 1
            if len(wb) >= batch_entries:
                db.write(wb)
                wb = WriteBatch()
    if len(wb):
        db.write(wb)
    if seen != manifest["entries"]:
        raise SnapshotError(
            f"entry count mismatch: {seen} != {manifest['entries']}")
    return manifest
