"""Read-only replica: state-transfer-only node with ledger archival.

Rebuild of the reference's ReadOnlyReplica
(/root/reference/bftengine/src/bftengine/ReadOnlyReplica.cpp on top of
ReplicaForStateTransfer.cpp) plus its object-store archival duty
(storage/src/s3/, tested by bftengine/tests/s3): a node with id in
[n, n+num_ro) that

  * holds NO voting keys and signs nothing — it cannot affect safety;
  * listens to the cluster's signed CheckpointMsgs; f+1 matching
    (seq, state digest) pairs form a TRUST ANCHOR (at least one honest
    signer vouches), which triggers/targets state transfer;
  * fetches blocks + reserved pages through the same BCStateTran-role
    StateTransferManager the live replicas use (destination side only);
  * archives every fetched block to an object store with per-object
    integrity digests (ledger backup/DR: the reference's RO replica
    writes the chain to S3);
  * serves READ_ONLY client requests from its local state — a cheap
    read offload that never touches consensus.

The message surface is deliberately tiny: CheckpointMsg,
StateTransferMsg, read-only ClientRequestMsg. Everything else is
dropped (a byzantine peer cannot make an RO replica do anything but
bounded verification work).
"""
from __future__ import annotations

import time
from typing import Dict, Optional, Set, Tuple

from tpubft.comm.interfaces import ICommunication, IReceiver
from tpubft.consensus import messages as m
from tpubft.consensus.incoming import Dispatcher, IncomingMsgsStorage
from tpubft.consensus.keys import ClusterKeys
from tpubft.consensus.replicas_info import ReplicasInfo
from tpubft.consensus.reserved_pages import ReservedPages
from tpubft.consensus.sig_manager import SigManager
from tpubft.kvbc.blockchain import KeyValueBlockchain
from tpubft.statetransfer import StateTransferManager
from tpubft.statetransfer.manager import StConfig
from tpubft.storage.interfaces import IDBClient
from tpubft.storage.memorydb import MemoryDB
from tpubft.storage.objectstore import IObjectStore
from tpubft.utils.config import ReplicaConfig
from tpubft.utils.logging import get_logger, mdc_scope
from tpubft.utils.metrics import Aggregator, Component

log = get_logger("ro_replica")

_K_ARCHIVED = b"ro.archived_to"


def archive_key(block_id: int) -> str:
    """Object-store key for an archived raw block (zero-padded so
    lexicographic list order == block order)."""
    return f"blocks/{block_id:020d}"


def digest_key(block_id: int) -> str:
    return f"digests/{block_id:020d}"


class ReadOnlyReplica(IReceiver):
    def __init__(self, cfg: ReplicaConfig, keys: ClusterKeys,
                 comm: ICommunication,
                 db: Optional[IDBClient] = None,
                 object_store: Optional[IObjectStore] = None,
                 handler_factory=None,
                 aggregator: Optional[Aggregator] = None,
                 st_cfg: Optional[StConfig] = None) -> None:
        self.cfg = cfg
        self.id = cfg.replica_id
        self.info = ReplicasInfo.from_config(cfg)
        assert self.info.n <= self.id < self.info.first_client_id, \
            "read-only replica ids live in [n, n + num_ro_replicas)"
        self.comm = comm
        self.db = db or MemoryDB()
        self.store = object_store
        self.aggregator = aggregator or Aggregator()
        self.blockchain = KeyValueBlockchain(self.db,
                                             use_device_hashing=False)
        if handler_factory is None:
            from tpubft.apps.skvbc import SkvbcHandler
            handler_factory = SkvbcHandler
        self.handler = handler_factory(self.blockchain)
        # verification only — an RO replica never signs anything
        self.sig = SigManager(keys, self.aggregator,
                              grace_seq_window=cfg.work_window_size)

        self.pages = ReservedPages(self.db)
        self.state_transfer = StateTransferManager(
            self.id, self.blockchain, st_cfg or StConfig(),
            reserved_pages=self.pages, aggregator=self.aggregator)
        self.state_transfer.bind(
            send_fn=lambda dest, payload: self.comm.send(
                dest, m.StateTransferMsg(sender_id=self.id,
                                         payload=payload).pack()),
            complete_fn=self._on_transfer_complete,
            replica_ids=list(self.info.replica_ids), f_val=cfg.f_val)

        # checkpoint trust anchors: seq -> (state, pages digest) -> voters.
        # Bounded like the live replica's checkpoint store: one MONOTONE
        # slot per sender (a key can only vote forward) and a cap on
        # distinct candidate seqs / certified anchors — a single byzantine
        # key can never grow memory without bound
        self._ck_votes: Dict[int, Dict[Tuple[bytes, bytes], Set[int]]] = {}
        self._ck_sender_latest: Dict[int, int] = {}
        self._certified: Dict[int, Tuple[bytes, bytes]] = {}
        self.last_anchor = 0
        self._last_anchor_time = 0.0     # monotonic time of last anchor
        self._last_ask = 0.0

        self.incoming = IncomingMsgsStorage()
        self.dispatcher = Dispatcher(self.incoming,
                                     name=f"ro-replica-{self.id}",
                                     thread_mdc={"r": self.id})
        self.dispatcher.set_external_handler(self._on_external)
        self.dispatcher.add_timer(
            (st_cfg.retry_timeout_s if st_cfg else 1.0) / 2,
            self._tick)

        self.metrics = Component("ro_replica", self.aggregator)
        self.m_anchor = self.metrics.register_gauge("last_anchor_seq")
        self.m_blocks = self.metrics.register_gauge("last_block")
        self.m_archived = self.metrics.register_gauge("archived_to")
        self.m_reads = self.metrics.register_counter("served_reads")
        self._running = False

    # ---- lifecycle ----
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self.comm.start(self)
        self.dispatcher.start()
        with mdc_scope(r=self.id):
            log.info("read-only replica up (n=%d, archived_to=%d)",
                     self.info.n, self.archived_to)

    def stop(self) -> None:
        self._running = False
        self.dispatcher.stop()
        self.comm.stop()

    # ---- transport upcall ----
    def on_new_message(self, sender: int, data: bytes) -> None:
        self.incoming.push_external(sender, data)

    # ---- dispatch (RO surface: checkpoints, ST, read-only requests) ----
    def _on_external(self, sender: int, raw: bytes) -> None:
        try:
            msg = m.unpack(raw)
        except m.MsgError:
            return
        if isinstance(msg, m.CheckpointMsg):
            if self.info.is_replica(msg.sender_id):
                self._on_checkpoint(msg)
        elif isinstance(msg, m.StateTransferMsg):
            if self.info.is_replica(sender):
                self.state_transfer.handle_message(sender, msg.payload)
        elif isinstance(msg, m.ClientRequestMsg):
            self._on_client_request(sender, msg)

    def _on_checkpoint(self, ck: m.CheckpointMsg) -> None:
        """f+1 matching signed checkpoint digests = a trust anchor the
        fetch can be validated against (the RO replica trusts no single
        peer; reference RO replica waits for a checkpoint certificate)."""
        if ck.seq_num <= self.last_anchor:
            return
        if ck.seq_num % self.cfg.checkpoint_window_size != 0:
            return
        # monotone per sender BEFORE the signature check: bounds both
        # memory and verification work under replayed/duplicate spam
        if ck.seq_num <= self._ck_sender_latest.get(ck.sender_id, 0):
            return
        if not self.sig.verify(ck.sender_id, ck.signed_payload(),
                               ck.signature, seq=ck.seq_num):
            return
        self._ck_sender_latest[ck.sender_id] = ck.seq_num
        if ck.seq_num not in self._ck_votes and len(self._ck_votes) >= 8:
            del self._ck_votes[min(self._ck_votes)]
        digests = self._ck_votes.setdefault(ck.seq_num, {})
        # the anchor binds BOTH digests the summaries will be checked
        # against (state + reserved pages), like the live replicas'
        # certified_checkpoints map
        pair = (ck.state_digest, ck.res_pages_digest)
        voters = digests.setdefault(pair, set())
        voters.add(ck.sender_id)
        if len(voters) < self.info.st_anchor_quorum:
            return
        self.last_anchor = ck.seq_num
        self._last_anchor_time = time.monotonic()
        self.m_anchor.set(ck.seq_num)
        self._certified[ck.seq_num] = pair
        if len(self._certified) > 32:
            del self._certified[min(self._certified)]
        for s in [s for s in self._ck_votes if s <= ck.seq_num]:
            del self._ck_votes[s]
        log.info("checkpoint anchor at seq %d: fetching", ck.seq_num)
        self.state_transfer.start_collecting(ck.seq_num,
                                             dict(self._certified))

    def _on_client_request(self, sender: int, req: m.ClientRequestMsg) -> None:
        """READ ONLY serving — the whole point of the replica variant:
        reads scale out without touching the voting set."""
        if not req.flags & m.RequestFlag.READ_ONLY:
            return
        if req.flags & (m.RequestFlag.RECONFIG | m.RequestFlag.INTERNAL):
            return
        if not self.info.is_client(req.sender_id) \
                or req.sender_id != sender:
            return
        if not self.sig.verify(req.sender_id, req.signed_payload(),
                               req.signature):
            return
        payload = self.handler.read(req.sender_id, req.request)
        self.m_reads.inc()
        self.comm.send(sender, m.ClientReplyMsg(
            sender_id=self.id, req_seq_num=req.req_seq_num,
            # "unknown": an RO replica tracks no view. Out-of-range on
            # purpose — clients must never take this as a primary hint
            # (their 0 <= x < n filter rejects it)
            current_primary=0xFFFFFFFF, reply=payload,
            replica_specific_info=b"ro").pack())

    # ---- state transfer completion -> archival ----
    @property
    def archived_to(self) -> int:
        raw = self.db.get(_K_ARCHIVED)
        return int.from_bytes(raw, "big") if raw else 0

    def _on_transfer_complete(self, seq: int, state_digest: bytes) -> None:
        log.info("state transfer complete at checkpoint %d (blocks=%d)",
                 seq, self.blockchain.last_block_id)
        self.m_blocks.set(self.blockchain.last_block_id)
        # the cluster may have rotated signing keys since we anchored:
        # adopt them from the fetched reserved pages, or every future
        # CheckpointMsg from a rotated replica would fail verification
        # (the live replica's post-ST key_exchange.load_from_pages())
        from tpubft.consensus.internal import KeyExchangeManager
        from tpubft.consensus.reserved_pages import ReservedPagesClient
        keyex = ReservedPagesClient(self.pages, KeyExchangeManager.CATEGORY)
        for r in self.info.replica_ids:
            pk = keyex.load(index=r)
            if pk:
                self.sig.set_replica_key(r, pk, rotation_seq=seq)
        self._archive_new_blocks()
        # an anchor that formed while this fetch was in flight would
        # otherwise strand us one checkpoint behind until new traffic
        if self.last_anchor > seq:
            self.state_transfer.start_collecting(self.last_anchor,
                                                 dict(self._certified))

    def _archive_new_blocks(self) -> None:
        """Append newly fetched blocks to the object store. Every object
        carries its own integrity digest; the ledger digest chain is
        additionally stored so an auditor can verify linkage offline."""
        if self.store is None:
            return
        start = self.archived_to + 1
        last = self.blockchain.last_block_id
        for bid in range(start, last + 1):
            raw = self.blockchain.get_raw_block(bid)
            if raw is None:
                break
            self.store.put(archive_key(bid), raw)
            self.store.put(digest_key(bid),
                           self.blockchain.block_digest(bid))
            self.db.put(_K_ARCHIVED, bid.to_bytes(8, "big"))
        self.m_archived.set(self.archived_to)

    # ---- periodic ----
    ASK_CHECKPOINT_PERIOD_S = 10.0

    def _tick(self) -> None:
        if not self._running:
            return
        self.state_transfer.tick()
        # poll for checkpoints when anchors aren't arriving on their own
        # (reference ReadOnlyReplica sends AskForCheckpointMsg on a
        # timer): a late joiner must not wait a whole checkpoint window
        # for the cluster's next broadcast
        now = time.monotonic()
        if now - self._last_anchor_time > self.ASK_CHECKPOINT_PERIOD_S \
                and now - self._last_ask > self.ASK_CHECKPOINT_PERIOD_S:
            self._last_ask = now
            ask = m.AskForCheckpointMsg(sender_id=self.id).pack()
            for r in range(self.info.n):
                self.comm.send(r, ask)

    # ---- audit helper (reference object_store integrity check tool) ----
    def verify_archive(self) -> Tuple[int, int]:
        """(verified_blocks, failures): re-read every archived object and
        check integrity + digest linkage against the stored digests."""
        if self.store is None:
            return (0, 0)
        import hashlib
        ok = bad = 0
        for key in self.store.list("blocks/"):
            bid = int(key.split("/")[1])
            raw = self.store.get(key)
            dig = self.store.get(digest_key(bid))
            if raw is None or dig is None:
                bad += 1
            elif hashlib.sha256(raw).digest() != dig:
                # Block.digest() is sha256 over the serialized block
                bad += 1
            else:
                ok += 1
        return ok, bad
