"""Sparse Merkle tree over a 256-bit key space.

Rebuild of the reference's sparse_merkle::Tree
(/root/reference/kvbc/src/sparse_merkle/tree.cpp, internal_node.cpp) with a
TPU-first update path: instead of nibble-batched internal nodes walked one
at a time, updates are applied as a *batch per level* — all changed nodes
of a level are rehashed in one call, which routes through the batched
SHA-256 kernel (tpubft/ops/sha256.py) once the level is wide enough to
amortize device dispatch.

Layout: key -> path = SHA-256(key), 256 levels. Only non-default nodes are
persisted (family `smt`); empty subtrees hash to precomputed defaults.
Leaf hash = H(0x00 || path || value_hash); inner = H(0x01 || l || r).

Versioning (reference tree.cpp is versioned; internal_node.cpp tracks
stale nodes): the LATEST state mutates in place — the hot path reads and
writes exactly one row per node, no version walk. Every node change is
additionally appended to an archive family keyed `node_key || version`
(version = block id), so `prove_at(key, version)` can rebuild the audit
path of any retained block by taking, per node, the newest archive row
at or below that version (absence = default subtree — any older change
would have been archived). `prune_versions(before)` is the stale-node
GC: it drops archive rows superseded before the retention point, exactly
the role of the reference's stale-node index.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from tpubft.storage.interfaces import IDBClient, WriteBatch

DEPTH = 256
_EMPTY = b"\x00" * 32

# default (empty-subtree) hash per depth: _DEFAULTS[256] = empty leaf,
# _DEFAULTS[d] = H(0x01 || _DEFAULTS[d+1] || _DEFAULTS[d+1])
_DEFAULTS: List[bytes] = [b""] * (DEPTH + 1)
_DEFAULTS[DEPTH] = _EMPTY
for _d in range(DEPTH - 1, -1, -1):
    _DEFAULTS[_d] = hashlib.sha256(
        b"\x01" + _DEFAULTS[_d + 1] + _DEFAULTS[_d + 1]).digest()

# below this many nodes in a level, hashlib beats device dispatch
_DEVICE_THRESHOLD = 192


def _hash_level(messages: Sequence[bytes], use_device: bool) -> List[bytes]:
    if use_device and len(messages) >= _DEVICE_THRESHOLD:
        try:
            from tpubft.ops.sha256 import sha256_batch
            return sha256_batch(messages)
        except Exception:  # noqa: BLE001 — device loss (or an OPEN
            # circuit breaker fast-fail) degrades to hashlib: digests
            # are byte-identical, a Merkle update must never die with
            # the accelerator
            pass
    return [hashlib.sha256(m).digest() for m in messages]


def _leaf_hash(path: bytes, value_hash: bytes) -> bytes:
    return hashlib.sha256(b"\x00" + path + value_hash).digest()


def _node_key(depth: int, path_bits: int) -> bytes:
    """Physical key: depth (2B big-endian) + the leading `depth` bits."""
    nbytes = (depth + 7) // 8
    return depth.to_bytes(2, "big") + (
        (path_bits << (nbytes * 8 - depth)).to_bytes(nbytes, "big")
        if depth else b"")


@dataclass
class Proof:
    """Audit path, compressed: bitmap marks levels whose sibling is
    non-default; `siblings` lists only those, bottom (depth 256) first."""
    bitmap: bytes                    # 32 bytes, bit i = level DEPTH - i
    siblings: List[bytes]


class SparseMerkleTree:
    def __init__(self, db: IDBClient, family: bytes = b"smt",
                 use_device: bool = True) -> None:
        self._db = db
        self._family = family
        self._leaf_family = family + b".leaf"
        self._arch_family = family + b".arch"        # node_key+ver8 -> hash
        self._leaf_arch_family = family + b".leafarch"  # path+ver8 -> vh
        self._use_device = use_device

    # ---- reads ----
    # Reads go straight to the DB (no node cache): staged-but-uncommitted
    # updates must never be observable, and an aborted block must leave no
    # residue — the DB's batch atomicity is the single source of truth.
    def _node(self, depth: int, path_bits: int) -> bytes:
        v = self._db.get(_node_key(depth, path_bits), self._family)
        return v if v is not None else _DEFAULTS[depth]

    def root(self) -> bytes:
        return self._node(0, 0)

    def get_value_hash(self, key: bytes) -> Optional[bytes]:
        path = hashlib.sha256(key).digest()
        return self._db.get(path, self._leaf_family)

    # ---- batch update ----
    def update_batch(self, updates: Dict[bytes, Optional[bytes]],
                     batch: Optional[WriteBatch] = None,
                     version: int = 0) -> bytes:
        """Apply {key: value_hash or None(delete)}; returns the new root.
        If `batch` is given, node writes are staged into it (caller
        commits atomically with the block); otherwise committed here.
        `version` (the block id) > 0 additionally archives every changed
        node so `prove_at` can serve this version later."""
        if not updates:
            return self.root()
        own_batch = batch is None
        wb = WriteBatch() if own_batch else batch
        ver = version.to_bytes(8, "big") if version > 0 else None

        # leaf level
        changed: Dict[int, bytes] = {}
        for key, vh in updates.items():
            path = hashlib.sha256(key).digest()
            bits = int.from_bytes(path, "big")
            if vh is None:
                changed[bits] = _EMPTY
                wb.delete(path, self._leaf_family)
            else:
                changed[bits] = _leaf_hash(path, vh)
                wb.put(path, vh, self._leaf_family)
            if ver is not None:
                wb.put(path + ver, vh if vh is not None else b"",
                       self._leaf_arch_family)
        self._stage_level(wb, DEPTH, changed, ver)

        # ascend, rehashing all changed nodes of each level in one batch
        for depth in range(DEPTH, 0, -1):
            parents = sorted({bits >> 1 for bits in changed})
            msgs = []
            for pb in parents:
                left = changed.get(pb << 1)
                if left is None:
                    left = self._node(depth, pb << 1)
                right = changed.get((pb << 1) | 1)
                if right is None:
                    right = self._node(depth, (pb << 1) | 1)
                msgs.append(b"\x01" + left + right)
            hashes = _hash_level(msgs, self._use_device)
            changed = dict(zip(parents, hashes))
            self._stage_level(wb, depth - 1, changed, ver)

        if own_batch:
            self._db.write(wb)
        return changed[0]

    # ---- multi-block batch update ----
    def update_batches(self, updates_list: Sequence[Dict[bytes,
                                                         Optional[bytes]]],
                       batch: Optional[WriteBatch] = None,
                       first_version: int = 0) -> List[bytes]:
        """Apply N consecutive blocks' updates in one level-synchronous
        walk: block i gets version `first_version + i` (0 = unversioned,
        like update_batch). Returns the root AFTER each block, exactly as
        N sequential update_batch calls would, and stages byte-identical
        rows (final node/leaf values + one archive row per changed node
        per version).

        The win over per-block calls is hash batching: at every level,
        the changed nodes of ALL blocks hash in ONE _hash_level call (one
        ops/sha256 device dispatch per level once wide enough) instead of
        one host loop per block per level. Cross-block dependencies are
        handled by tracking, per node, the ordered list of
        (block index, hash) versions: block i's parent hash reads the
        newest child value at or below i, falling back to the DB for
        nodes untouched by the whole batch."""
        if not updates_list:
            return []
        nblocks = len(updates_list)
        if not any(updates_list):
            return [self.root()] * nblocks
        if nblocks == 1:
            # degenerate: the sequential path is the batched path
            return [self.update_batch(dict(updates_list[0]), batch=batch,
                                      version=first_version)]
        own_batch = batch is None
        wb = WriteBatch() if own_batch else batch
        vers = [(first_version + i).to_bytes(8, "big")
                if first_version > 0 else None for i in range(nblocks)]

        # leaf level: per path, ordered (block, hash) versions
        changed: Dict[int, List[Tuple[int, bytes]]] = {}
        final_leaf: Dict[bytes, Optional[bytes]] = {}
        for i, updates in enumerate(updates_list):
            for key, vh in updates.items():
                path = hashlib.sha256(key).digest()
                bits = int.from_bytes(path, "big")
                h = _EMPTY if vh is None else _leaf_hash(path, vh)
                changed.setdefault(bits, []).append((i, h))
                final_leaf[path] = vh
                if vers[i] is not None:
                    wb.put(path + vers[i],
                           vh if vh is not None else b"",
                           self._leaf_arch_family)
        for path, vh in final_leaf.items():
            if vh is None:
                wb.delete(path, self._leaf_family)
            else:
                wb.put(path, vh, self._leaf_family)
        # pre-batch values of this level's changed nodes, captured BEFORE
        # staging them: `wb` may be a read-your-writes mirrored batch (the
        # bulk add_blocks path), where a post-staging read of a node whose
        # first change is at a LATER block would see that final value
        # instead of the pre-batch one — corrupting earlier blocks' roots
        pre: Dict[int, bytes] = {b: self._node(DEPTH, b) for b in changed}
        self._stage_level_multi(wb, DEPTH, changed, vers)

        for depth in range(DEPTH, 0, -1):
            def value_at(bits: int, i: int) -> bytes:
                """Newest value of (depth, bits) at or below block i:
                the node's newest in-batch version ≤ i, its pre-batch
                value if its first change is later, or the DB (which the
                batch never touched for this node)."""
                versions = changed.get(bits)
                if versions is None:
                    return self._node(depth, bits)
                best = None
                for j, h in versions:          # ascending block order
                    if j > i:
                        break
                    best = h
                return best if best is not None else pre[bits]

            # (parent_bits, block) pairs needing a hash, in stable order
            pairs: List[Tuple[int, int]] = []
            seen = set()
            for bits, versions in changed.items():
                pb = bits >> 1
                for i, _ in versions:
                    if (pb, i) not in seen:
                        seen.add((pb, i))
                        pairs.append((pb, i))
            pairs.sort()
            msgs = [b"\x01" + value_at(pb << 1, i)
                    + value_at((pb << 1) | 1, i)
                    for pb, i in pairs]
            hashes = _hash_level(msgs, self._use_device)
            parents: Dict[int, List[Tuple[int, bytes]]] = {}
            for (pb, i), h in zip(pairs, hashes):
                parents.setdefault(pb, []).append((i, h))
            changed = parents                  # pairs sorted → ascending i
            pre = {b: self._node(depth - 1, b) for b in changed}
            self._stage_level_multi(wb, depth - 1, changed, vers)

        if own_batch:
            self._db.write(wb)
        root_versions = changed[0]
        roots, cur = [], pre[0]               # pre-batch root
        it = iter(root_versions)
        nxt = next(it, None)
        for i in range(nblocks):
            while nxt is not None and nxt[0] <= i:
                cur = nxt[1]
                nxt = next(it, None)
            roots.append(cur)
        return roots

    def _stage_level_multi(self, wb: WriteBatch, depth: int,
                           nodes: Dict[int, List[Tuple[int, bytes]]],
                           vers: List[Optional[bytes]]) -> None:
        """Stage a level's multi-version nodes: final value to the live
        family, one archive row per (node, block) change."""
        default = _DEFAULTS[depth]
        for bits, versions in nodes.items():
            k = _node_key(depth, bits)
            final = versions[-1][1]
            if final == default:
                wb.delete(k, self._family)
            else:
                wb.put(k, final, self._family)
            for i, h in versions:
                if vers[i] is not None:
                    wb.put(k + vers[i], b"" if h == default else h,
                           self._arch_family)

    def _stage_level(self, wb: WriteBatch, depth: int,
                     nodes: Dict[int, bytes],
                     ver: Optional[bytes] = None) -> None:
        default = _DEFAULTS[depth]
        for bits, h in nodes.items():
            k = _node_key(depth, bits)
            if h == default:
                wb.delete(k, self._family)
            else:
                wb.put(k, h, self._family)
            if ver is not None:
                # archive row; default is stored as empty so a historical
                # walk can tell "reverted to default at ver" from "never
                # touched" (the latter = default since genesis)
                wb.put(k + ver, b"" if h == default else h,
                       self._arch_family)

    # ---- versioned reads ----
    def _newest_row_at(self, family: bytes, prefix: bytes,
                       version: int) -> Optional[bytes]:
        """Newest archive row for `prefix` at or below `version`, or None
        if the node was never written by then. Rows of one node share a
        fixed-length prefix, so the range scan is exact."""
        row = self._db.last_in_range(
            family, start=prefix,
            end=prefix + (version + 1).to_bytes(8, "big"))
        return row[1] if row else None

    def _node_at(self, depth: int, path_bits: int, version: int) -> bytes:
        row = self._newest_row_at(self._arch_family,
                                  _node_key(depth, path_bits), version)
        if row is None or row == b"":
            return _DEFAULTS[depth]
        return row

    def root_at(self, version: int) -> bytes:
        return self._node_at(0, 0, version)

    def get_value_hash_at(self, key: bytes,
                          version: int) -> Optional[bytes]:
        path = hashlib.sha256(key).digest()
        row = self._newest_row_at(self._leaf_arch_family, path, version)
        return row if row else None        # b"" = deleted at that version

    def prove_at(self, key: bytes, version: int) -> Proof:
        """Audit path as of `version` (a retained block id). Costs one
        archive range-scan per level — proof serving, not the hot path."""
        return self._prove_with(
            key, lambda depth, bits: self._node_at(depth, bits, version))

    def prune_versions(self, before_version: int) -> int:
        """Stale-node GC (reference stale-node index role): drop archive
        rows SUPERSEDED at or below `before_version` — for each node,
        every row older than its newest row ≤ before stays unreachable
        from any retained root ≥ before. Returns rows deleted.

        Cost: one pass over the archive family (O(retained history), a
        maintenance operation like the reference's stale-node sweep, not
        the ordering hot path). A per-write stale index would make this
        O(deleted) at the price of one extra read per node on every
        block commit — wrong trade while prune frequency << block rate."""
        wb = WriteBatch()
        deleted = 0
        for fam in (self._arch_family, self._leaf_arch_family):
            prev_key: Optional[bytes] = None   # candidate superseded row
            for k, _v in self._db.range_iter(fam):
                prefix, ver = k[:-8], int.from_bytes(k[-8:], "big")
                if (prev_key is not None and prev_key[:-8] == prefix
                        and ver <= before_version):
                    wb.delete(prev_key, fam)   # newer row ≤ before exists
                    deleted += 1
                prev_key = k if ver <= before_version else None
        if deleted:
            self._db.write(wb)
        return deleted

    # ---- proofs ----
    def prove(self, key: bytes) -> Proof:
        return self._prove_with(key, self._node)

    def _prove_with(self, key: bytes, node) -> Proof:
        """One audit-path walk for both latest and versioned proofs —
        the bitmap compression must never diverge between the two."""
        path = hashlib.sha256(key).digest()
        bits = int.from_bytes(path, "big")
        bitmap = bytearray(32)
        siblings: List[bytes] = []
        node_bits = bits
        for depth in range(DEPTH, 0, -1):
            sib = node(depth, node_bits ^ 1)
            if sib != _DEFAULTS[depth]:
                i = DEPTH - depth
                bitmap[i // 8] |= 1 << (i % 8)
                siblings.append(sib)
            node_bits >>= 1
        return Proof(bytes(bitmap), siblings)

    @staticmethod
    def verify(root: bytes, key: bytes, value_hash: Optional[bytes],
               proof: Proof) -> bool:
        """Checks membership (value_hash given) or non-membership (None)."""
        if len(proof.bitmap) != 32:
            return False
        path = hashlib.sha256(key).digest()
        bits = int.from_bytes(path, "big")
        acc = _EMPTY if value_hash is None else _leaf_hash(path, value_hash)
        sib_iter = iter(proof.siblings)
        node_bits = bits
        try:
            for depth in range(DEPTH, 0, -1):
                i = DEPTH - depth
                if proof.bitmap[i // 8] >> (i % 8) & 1:
                    sib = next(sib_iter)
                else:
                    sib = _DEFAULTS[depth]
                if node_bits & 1:
                    acc = hashlib.sha256(b"\x01" + sib + acc).digest()
                else:
                    acc = hashlib.sha256(b"\x01" + acc + sib).digest()
                node_bits >>= 1
        except StopIteration:
            return False
        return acc == root
