"""v1 "direct-KV" engine — the legacy block format.

Rebuild of the reference's v1 adapters
(/root/reference/kvbc/src/direct_kv_db_adapter.cpp,
merkle_tree_db_adapter.cpp's direct-KV mode): keys are written DIRECTLY
— one latest-value row per key, no per-version history, no tag indexes,
no Merkle maintenance — with the block row carrying the raw updates for
replay. It exists so deployments on the oldest format can still be
served and, more importantly, MIGRATED: the engine plugs into the same
`create_blockchain` facade and block-row format as the categorized/v4
engines, so `tools/migrate_v4.py --from v1 --to v4` replays a legacy
chain without special cases.

This is a MIGRATION/TOOLING engine, not a consensus engine: the replica
binaries do not offer it (its raising history/proof reads would turn one
versioned client read into a deterministic execution halt on every
correct replica). Serve legacy data by migrating it.

Semantics (deliberately legacy-faithful):
- `get_latest` only; `get_versioned`/`get_tagged`/`prove` raise — the
  format stores no history and no proofs.
- Immutable categories degrade to plain writes (v1 predates category
  types); the updates blob still records the declared category types so
  a migration to a newer engine restores full semantics.
"""
from __future__ import annotations

import hashlib
from typing import Dict, Optional

from tpubft.kvbc import categories as cat
from tpubft.kvbc.blockchain import Block, BlockchainError, BlockStoreMixin
from tpubft.storage.interfaces import IDBClient, WriteBatch

_BLOCKS = b"v1.blocks"
_DATA = b"v1.data"
_MISC = b"v1.misc"
_ST = b"v1.st"


def _dk(category: str, key: bytes) -> bytes:
    c = category.encode()
    return len(c).to_bytes(2, "big") + c + key


class DirectKVBlockchain(BlockStoreMixin):
    """Latest-only direct writes; block rows exist purely for replay,
    state transfer, and digest chaining."""

    VERSION = "v1"
    _F_BLOCKS = _BLOCKS
    _F_MISC = _MISC
    _F_ST = _ST

    def __init__(self, db: IDBClient,
                 use_device_hashing: bool = False) -> None:
        del use_device_hashing          # nothing batched to accelerate
        self._db = db
        self._load_head()

    def _stage_block(self, wb: WriteBatch, block_id: int,
                     updates: cat.BlockUpdates) -> Block:
        digests: Dict[str, bytes] = {}
        for name in sorted(updates.categories):
            _, cu = updates.categories[name]
            h = hashlib.sha256()
            for k in sorted(cu.kv):
                v = cu.kv[k]
                row = _dk(name, k)
                if v is None:
                    wb.delete(row, _DATA)
                    h.update(b"\x00" + len(k).to_bytes(4, "big") + k)
                else:
                    wb.put(row, v, _DATA)   # DIRECT: the raw value
                    h.update(b"\x01" + len(k).to_bytes(4, "big") + k
                             + hashlib.sha256(v).digest())
            digests[name] = h.digest()
        parent = self.block_digest(block_id - 1) if block_id > 1 else b""
        block = Block(block_id=block_id, parent_digest=parent,
                      category_digests=digests,
                      updates_blob=cat.encode_block_updates(updates))
        self._put_block_row(wb, block_id, block)
        return block

    # ---- reads (latest only — the format's defining limitation) ----
    def get_latest(self, category: str, key: bytes,
                   cat_type: str = cat.VERSIONED_KV):
        """(version, value) like the modern engines — but v1 stores no
        version column, so the version is always 0 ("unknown")."""
        del cat_type                    # v1 has no category semantics
        raw = self._db.get(_dk(category, key), _DATA)
        return None if raw is None else (0, raw)

    def get_versioned(self, category: str, key: bytes, block_id: int):
        raise BlockchainError("v1 direct-KV stores no version history; "
                              "migrate to categorized/v4 for versioned "
                              "reads (tools/migrate_v4.py)")

    def get_tagged(self, category: str, tag: str):
        raise BlockchainError("v1 direct-KV has no tag indexes")

    def prove(self, category: str, key: bytes):
        raise BlockchainError("v1 direct-KV has no Merkle proofs")

    def merkle_root(self, category: str) -> bytes:
        raise BlockchainError("v1 direct-KV has no Merkle trees")
