"""Block categories: the three update/storage disciplines of the
categorized blockchain (reference kvbc/src/categorization/
{block_merkle,versioned_kv,immutable_kv}_category.cpp).

- BLOCK_MERKLE:  proven state — keys live in the sparse Merkle tree;
                 per-block root goes into the block's category digest.
- VERSIONED_KV:  multi-version reads — every (key, block) version kept,
                 plus a latest-version index.
- IMMUTABLE:     write-once keys with tags (event-group style); rewrite
                 is rejected.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tpubft.storage.interfaces import IDBClient, WriteBatch
from tpubft.utils import serialize as ser

BLOCK_MERKLE = "block_merkle"
VERSIONED_KV = "versioned_kv"
IMMUTABLE = "immutable"

# names of every merkle category ever written (key = category, value
# empty) — survives restarts so pruning can GC all tree archives.
# Deliberately OUTSIDE the "smt.<category>" namespace: a merkle category
# literally named "registry" must not collide with this family.
SMT_REGISTRY_FAMILY = b"kvbc.smtcats"

CATEGORY_TYPES = (BLOCK_MERKLE, VERSIONED_KV, IMMUTABLE)


@dataclass
class CategoryUpdates:
    """One category's writes in one block. value None = delete (not
    allowed for IMMUTABLE). `tags` only meaningful for IMMUTABLE."""
    kv: Dict[bytes, Optional[bytes]] = field(default_factory=dict)
    tags: Dict[bytes, List[str]] = field(default_factory=dict)

    SPEC = [("kv", ("map", "bytes", ("opt", "bytes"))),
            ("tags", ("map", "bytes", ("list", "str")))]


@dataclass
class BlockUpdates:
    """category id -> (category type, updates)."""
    categories: Dict[str, Tuple[str, CategoryUpdates]] = field(
        default_factory=dict)

    def put(self, category: str, key: bytes, value: bytes,
            cat_type: str = VERSIONED_KV,
            tags: Optional[List[str]] = None) -> "BlockUpdates":
        cu = self._cat(category, cat_type)
        cu.kv[key] = value
        if tags:
            cu.tags[key] = tags
        return self

    def delete(self, category: str, key: bytes,
               cat_type: str = VERSIONED_KV) -> "BlockUpdates":
        self._cat(category, cat_type).kv[key] = None
        return self

    def _cat(self, category: str, cat_type: str) -> CategoryUpdates:
        if cat_type not in CATEGORY_TYPES:
            raise ValueError(f"unknown category type {cat_type}")
        if category in self.categories:
            existing_type, cu = self.categories[category]
            if existing_type != cat_type:
                raise ValueError(
                    f"category {category} is {existing_type}, not {cat_type}")
            return cu
        cu = CategoryUpdates()
        self.categories[category] = (cat_type, cu)
        return cu


# family name helpers (one keyspace per category + discipline)
def _fam(category: str, part: str) -> bytes:
    return f"cat.{category}.{part}".encode()


def _ver_key(key: bytes, block_id: int) -> bytes:
    # descending block order: latest version sorts first in the range
    return bytes([len(key) >> 8, len(key) & 0xFF]) + key + \
        (~block_id & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "big")


class CategoryError(Exception):
    pass


def stage_merkle_data(wb: WriteBatch, category: str,
                      updates: CategoryUpdates, block_id: int) -> None:
    """Stage a block_merkle category's raw data rows (the non-tree half
    of its staging — split out so bulk paths that batch the tree work
    across blocks stage the data rows identically)."""
    for k, v in updates.kv.items():
        if v is None:
            wb.delete(k, _fam(category, "data"))
        else:
            wb.put(k, block_id.to_bytes(8, "big") + v,
                   _fam(category, "data"))


def stage_category(db: IDBClient, wb: WriteBatch, category: str,
                   cat_type: str, updates: CategoryUpdates, block_id: int,
                   merkle_trees) -> bytes:
    """Stage one category's updates for `block_id` into `wb`; returns the
    category's state digest contribution for the block."""
    if cat_type == BLOCK_MERKLE:
        tree = merkle_trees(category)
        # durable registry of merkle categories: archive GC at prune time
        # must find every tree ever written, including ones untouched
        # since the last process restart (the in-memory tree cache alone
        # forgets them)
        wb.put(category.encode(), b"", SMT_REGISTRY_FAMILY)
        leaf = {k: (hashlib.sha256(v).digest() if v is not None else None)
                for k, v in updates.kv.items()}
        root = tree.update_batch(leaf, batch=wb, version=block_id)
        stage_merkle_data(wb, category, updates, block_id)
        return root

    if cat_type == VERSIONED_KV:
        h = hashlib.sha256()
        for k in sorted(updates.kv):
            v = updates.kv[k]
            wb.put(_ver_key(k, block_id),
                   b"\x00" if v is None else b"\x01" + v,
                   _fam(category, "hist"))
            if v is None:
                wb.delete(k, _fam(category, "latest"))
                h.update(b"\x00" + len(k).to_bytes(4, "big") + k)
            else:
                wb.put(k, block_id.to_bytes(8, "big") + v,
                       _fam(category, "latest"))
                h.update(b"\x01" + len(k).to_bytes(4, "big") + k
                         + hashlib.sha256(v).digest())
        return h.digest()

    if cat_type == IMMUTABLE:
        h = hashlib.sha256()
        for k in sorted(updates.kv):
            v = updates.kv[k]
            if v is None:
                raise CategoryError("immutable category cannot delete")
            if db.get(k, _fam(category, "data")) is not None:
                raise CategoryError(f"immutable key rewrite: {k!r}")
            wb.put(k, block_id.to_bytes(8, "big") + v,
                   _fam(category, "data"))
            for tag in updates.tags.get(k, []):
                tb = tag.encode()
                wb.put(len(tb).to_bytes(4, "big") + tb + k, v,
                       _fam(category, "tag"))
            h.update(b"\x01" + len(k).to_bytes(4, "big") + k
                     + hashlib.sha256(v).digest())
        return h.digest()

    raise CategoryError(f"unknown category type {cat_type}")


def get_latest(db: IDBClient, category: str, cat_type: str,
               key: bytes) -> Optional[Tuple[int, bytes]]:
    """-> (block_id, value) of the latest version, or None."""
    if cat_type == VERSIONED_KV:
        raw = db.get(key, _fam(category, "latest"))
    else:
        raw = db.get(key, _fam(category, "data"))
    if raw is None:
        return None
    return int.from_bytes(raw[:8], "big"), raw[8:]


def get_versioned(db: IDBClient, category: str, key: bytes,
                  block_id: int) -> Optional[bytes]:
    """VERSIONED_KV read at a historical version: newest write with
    version <= block_id."""
    fam = _fam(category, "hist")
    start = _ver_key(key, block_id)
    for k, v in db.range_iter(fam, start=start):
        if not k.startswith(start[:2 + len(key)]):
            return None
        return None if v[:1] == b"\x00" else v[1:]
    return None


def get_tagged(db: IDBClient, category: str, tag: str
               ) -> List[Tuple[bytes, bytes]]:
    """IMMUTABLE: all (key, value) written under a tag."""
    tb = tag.encode()
    prefix = len(tb).to_bytes(4, "big") + tb
    out = []
    for k, v in db.range_iter(_fam(category, "tag"), start=prefix):
        if not k.startswith(prefix):
            break
        out.append((k[len(prefix):], v))
    return out


# serialization of a whole block's updates (for the block store + ST)
def encode_block_updates(bu: BlockUpdates) -> bytes:
    buf = bytearray()
    ser.write_uvarint(buf, len(bu.categories))
    for cat in sorted(bu.categories):
        cat_type, cu = bu.categories[cat]
        ser.write_bytes(buf, cat.encode())
        ser.write_bytes(buf, cat_type.encode())
        ser.encode_msg_into(buf, cu)
    return bytes(buf)


def decode_block_updates(data: bytes) -> BlockUpdates:
    mv = memoryview(data)
    n, off = ser.read_uvarint(mv, 0)
    bu = BlockUpdates()
    for _ in range(n):
        cat, off = ser.read_bytes(mv, off)
        cat_type, off = ser.read_bytes(mv, off)
        cu, off = ser.decode_msg_from(mv, off, CategoryUpdates)
        bu.categories[cat.decode()] = (cat_type.decode(), cu)
    if off != len(data):
        raise ser.SerializeError("trailing bytes in block updates")
    return bu
