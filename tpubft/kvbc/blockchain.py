"""Categorized key-value blockchain.

Rebuild of the reference's `concord::kvbc::categorization::KeyValueBlockchain`
(/root/reference/kvbc/include/categorization/kv_blockchain.h:40,
src/categorization/kv_blockchain.cpp): blocks are maps category→updates,
chained by parent digest; per-category state digests (Merkle root for
block_merkle categories) feed the block digest, which is what consensus
checkpoints sign. Also carries the v4-style `st_chain` staging area
(src/v4blockchain/detail/st_chain.cpp) so state transfer can land blocks
out of order and link them with integrity checks.
"""
from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tpubft.kvbc import categories as cat
from tpubft.kvbc.sparse_merkle import SparseMerkleTree
from tpubft.storage.interfaces import IDBClient, WriteBatch, fkey
from tpubft.utils import serialize as ser
from tpubft.utils.racecheck import make_lock

_BLOCKS = b"blk.blocks"
_MISC = b"blk.misc"
_ST = b"blk.st"

_K_LAST = b"last"
_K_GENESIS = b"genesis"


class BlockchainError(Exception):
    pass


@dataclass
class Block:
    block_id: int
    parent_digest: bytes
    category_digests: Dict[str, bytes] = field(default_factory=dict)
    updates_blob: bytes = b""

    SPEC = [("block_id", "u64"), ("parent_digest", "bytes"),
            ("category_digests", ("map", "str", "bytes")),
            ("updates_blob", "bytes")]

    def digest(self) -> bytes:
        return hashlib.sha256(ser.encode_msg(self)).digest()


def _bid(block_id: int) -> bytes:
    return block_id.to_bytes(8, "big")


class _MirroredBatch(WriteBatch):
    """WriteBatch that mirrors every op into an overlay dict (physical
    key -> value-or-None) so staging reads issued later in the SAME batch
    observe earlier staged writes (read-your-writes for batched ST
    linking)."""

    def __init__(self, overlay: Dict[bytes, Optional[bytes]]) -> None:
        super().__init__()
        self._overlay = overlay

    def put(self, key: bytes, value: bytes,
            family: bytes = b"default") -> "WriteBatch":
        self._overlay[fkey(family, key)] = bytes(value)
        return super().put(key, value, family)

    def delete(self, key: bytes,
               family: bytes = b"default") -> "WriteBatch":
        self._overlay[fkey(family, key)] = None
        return super().delete(key, family)


class _StagedReadView(IDBClient):
    """Read view over (overlay, base db) used while linking several
    staged blocks into one WriteBatch: block N+1's staging must see block
    N's pending writes (parent block row, merkle nodes, immutable-rewrite
    checks) before anything hits the real DB. Every staging read in both
    ledger engines is a point `get`; mutations during staging go through
    the shared batch, never this view."""

    def __init__(self, base: IDBClient,
                 overlay: Dict[bytes, Optional[bytes]]) -> None:
        self._base = base
        self._overlay = overlay

    def get(self, key: bytes, family: bytes = b"default"):
        pk = fkey(family, key)
        if pk in self._overlay:
            return self._overlay[pk]
        return self._base.get(key, family)

    def write(self, batch: WriteBatch) -> None:
        raise BlockchainError("staged read view is read-only")

    def range_iter(self, family: bytes = b"default", start=None, end=None):
        # staging never range-scans; reads that do (proof serving) run
        # outside the link path, against the committed base
        return self._base.range_iter(family, start, end)

    def close(self) -> None:  # pragma: no cover - never owned
        pass


class _SpecOverlayView(IDBClient):
    """Thread-routed staged-read view for SPECULATIVE accumulations: the
    executor thread that owns the speculation reads its own staged
    writes through the overlay (read-your-writes), while every OTHER
    thread — read-only queries on the dispatcher, proof serving, status
    handlers — keeps reading the committed base. A speculative run may
    abort; its overlay must never be observable outside the thread that
    can roll it back."""

    def __init__(self, base: IDBClient, view: "_StagedReadView",
                 owner_ident: int) -> None:
        self._base = base
        self._view = view
        self._owner = owner_ident

    def get(self, key: bytes, family: bytes = b"default"):
        if threading.get_ident() == self._owner:
            return self._view.get(key, family)
        return self._base.get(key, family)

    def write(self, batch: WriteBatch) -> None:
        raise BlockchainError("staged read view is read-only")

    def range_iter(self, family: bytes = b"default", start=None, end=None):
        return self._base.range_iter(family, start, end)

    def close(self) -> None:  # pragma: no cover - never owned
        pass


def raw_base(db):
    """Unwrap a durability `_PendingView` to the raw backing store —
    THE one idiom for 'give me the db the io thread writes/fsyncs'
    (the execution lane's sync targets, the test cluster's shared-pages
    wiring, and this module's own seal path all route through here)."""
    return db.base if isinstance(db, _PendingView) else db


class _PendingView(IDBClient):
    """Permanently-installed read view over (durability-pending overlay,
    base db) — the group-commit pipeline's visibility layer. The
    execution lane seals each run's WriteBatch into the
    `durability.PendingStore` instead of writing the base; every reader
    on every thread (execution staging, dispatcher queries, proof
    serving, thin-replica handlers, pages digests) consults the overlay
    first, so the LOGICAL head is what the process observes while the
    io thread lands the bytes behind it. Point gets are lock-free
    overlay lookups; range scans merge the (bounded, seal-queue-sized)
    pending keys into the base iteration so versioned reads and digest
    walks see sealed state too. Writes forward to the base — direct
    writers (ST staging, metadata, link segments) never ride the
    pipeline, and the order-sensitive ones take `_pending_barrier`
    first."""

    def __init__(self, base: IDBClient, store) -> None:
        self._base = base
        self._store = store

    @property
    def base(self) -> IDBClient:
        return self._base

    def get(self, key: bytes, family: bytes = b"default"):
        ent = self._store.lookup(fkey(family, key))
        if ent is not None:
            return ent[1]
        return self._base.get(key, family)

    def write(self, batch: WriteBatch) -> None:
        self._base.write(batch)

    # no sync()/write_group() forwards on purpose: the io thread holds
    # the RAW base (SealedRun.db) — the group boundary never routes
    # through the read view, and the fsync-seam lint keeps it that way

    def range_iter(self, family: bytes = b"default", start=None, end=None):
        from tpubft.storage.interfaces import family_upper_bound
        lo = fkey(family, start if start is not None else b"")
        hi = (fkey(family, end) if end is not None
              else family_upper_bound(family))
        pend = self._store.snapshot_range(lo, hi)
        if not pend:
            yield from self._base.range_iter(family, start, end)
            return
        prefix = 1 + len(family)
        pi = 0
        for k, v in self._base.range_iter(family, start, end):
            while pi < len(pend) and pend[pi][0][prefix:] < k:
                pk, pv = pend[pi]
                pi += 1
                if pv is not None:
                    yield pk[prefix:], pv
            if pi < len(pend) and pend[pi][0][prefix:] == k:
                pk, pv = pend[pi]
                pi += 1
                if pv is not None:      # pending overwrite wins; a
                    yield pk[prefix:], pv   # pending delete hides the row
                continue
            yield k, v
        while pi < len(pend):
            pk, pv = pend[pi]
            pi += 1
            if pv is not None:
                yield pk[prefix:], pv

    def scan_all(self):
        # whole-state walks (snapshot tools, ST streaming) run on
        # drained paths — served from the base
        return self._base.scan_all()

    def close(self) -> None:
        self._base.close()


@dataclass
class _Accumulation:
    """In-flight execution-run accumulation: the shared mirrored batch
    plus what end/abort need to finish or roll back."""
    master: "_MirroredBatch"
    base_last: int
    notifications: List[Tuple[int, "cat.BlockUpdates"]] = field(
        default_factory=list)
    # speculative accumulations stay open across the commit-combine
    # window: their staged reads are visible only to `owner` (the
    # executor thread), and link_st_chain DEFERS instead of blocking on
    # the staging lock they hold (the dispatcher must stay free to
    # seal or abort them)
    speculative: bool = False
    owner: int = 0


class BlockStoreMixin:
    """Shared block-store + ST-staging + pruning plumbing for both ledger
    engines (categorized and v4 — they differ only in keyspace names and
    how a block's updates are staged). Engines set the class attributes
    `_F_BLOCKS`/`_F_MISC`/`_F_ST` and implement `_stage_block(wb,
    block_id, updates) -> Block`; the mixin provides everything keyed off
    the shared block format."""

    _F_BLOCKS: bytes
    _F_MISC: bytes
    _F_ST: bytes

    # blocks adopted per atomic commit inside link_st_chain: bounds the
    # in-memory batch + overlay when a huge staged suffix becomes
    # linkable at once (a slow front range can back the whole rest of a
    # transfer up behind it), and keeps one kvlog record well under the
    # engine's u32 payload limit. Class attribute so tests can shrink it.
    LINK_SEGMENT_BLOCKS = 256

    def _load_head(self) -> None:
        last = self._db.get(_K_LAST, self._F_MISC)
        self._last = int.from_bytes(last, "big") if last else 0
        gen = self._db.get(_K_GENESIS, self._F_MISC)
        self._genesis = int.from_bytes(gen, "big") if gen else 0
        self._listeners: List[Callable[[int, "cat.BlockUpdates"],
                                       None]] = []
        # run listeners see one call per ATOMIC COMMIT (a coalesced
        # execution run, a bulk add_blocks, a link segment) with the
        # whole batch of (block_id, updates) — the thin-replica feed
        # pays one publish hop per sealed run, not one per block
        self._run_listeners: List[Callable[
            [List[Tuple[int, "cat.BlockUpdates"]]], None]] = []
        # serializes the two users of the staged-read redirect — the
        # execution lane's block accumulation (executor thread) and
        # state-transfer linking (dispatcher thread). Held across
        # begin_accumulation..end/abort and for each link_st_chain
        # segment loop.
        self._staging_mu = make_lock("kvbc.staging")
        self._accum: Optional[_Accumulation] = None
        # group-commit durability (tpubft/durability/): the pending
        # overlay store + drain hook, installed by attach_durability;
        # _deferred stages exactly one sealed-run handoff between
        # end_accumulation(defer=True) and take_deferred() — both on
        # the executor thread
        self._pending_store = None
        self._pending_drain = None
        self._deferred = None

    # ---- group-commit durability wiring ----
    def attach_durability(self, store, drain_fn=None) -> "_PendingView":
        """Install the sealed-not-yet-applied read overlay: self._db
        becomes a `_PendingView` over (store, base) so every reader
        observes sealed runs before the io thread lands them.
        `drain_fn(timeout) -> bool` is the pipeline's flush-and-wait
        barrier — the direct-write paths call it, because overlay
        emptiness alone cannot see an applied-but-unsynced group parked
        for an fsync retry. Must run before any accumulation (replica
        wiring time); re-attach (a fresh pipeline over a reused ledger)
        swaps the store."""
        if self._accum is not None:
            raise BlockchainError("attach_durability during accumulation")
        view = _PendingView(raw_base(self._db), store)
        self._db = view
        self._pending_store = store
        self._pending_drain = drain_fn
        self._deferred = None
        # cached merkle trees read through the same view
        for t in getattr(self, "_trees", {}).values():
            t._db = view
        return view

    @property
    def durability_attached(self) -> bool:
        return self._pending_store is not None

    def take_deferred(self):
        """(run_no, master batch, raw base db) of the run just sealed
        by end_accumulation(defer=True) — consumed immediately by the
        executor thread, which hands it to the durability pipeline."""
        d, self._deferred = self._deferred, None
        return d

    def _pending_barrier(self, timeout: float = 30.0) -> None:
        """Direct-write order barrier: bulk ingest, ST link segments
        and pruning write the base db straight — they must never
        interleave with sealed run batches the io thread has not
        DURABLY retired (a group that applied, failed its fsync and
        was requeued for retry would re-apply an OLDER head over
        theirs — overlay emptiness alone cannot see that state, so the
        barrier is the pipeline's own flush-and-wait). These paths
        already run behind the replica's drain discipline; the wait
        here is the loud backstop, and a disk too wedged to drain
        fails the write rather than corrupting the head."""
        store = self._pending_store
        if store is None:
            return
        drain = self._pending_drain
        ok = True
        if drain is not None:
            try:
                ok = bool(drain(timeout))
            except Exception:  # noqa: BLE001 — treat as not drained
                ok = False
        if not ok or not store.wait_empty(
                timeout if drain is None else 1.0):
            raise BlockchainError(
                "durability pipeline failed to drain before a direct "
                "ledger write (sealed runs still pending)")

    # ---- properties ----
    @property
    def last_block_id(self) -> int:
        # a SPECULATIVE accumulation's head bump is private to its
        # executor thread, exactly like its staged reads: every other
        # thread sees the committed head (a non-owner observing the
        # speculative head would try to read blocks that may abort)
        acc = self._accum
        if acc is not None and acc.speculative \
                and threading.get_ident() != acc.owner:
            return acc.base_last
        return self._last

    @property
    def speculation_open(self) -> bool:
        acc = self._accum
        return acc is not None and acc.speculative

    @property
    def genesis_block_id(self) -> int:
        return self._genesis

    # ---- commit-stream listeners (thin-replica publishing; reference:
    # kvbc Replica feeds SubUpdateBuffers from the commit path) ----
    def add_listener(self,
                     fn: Callable[[int, "cat.BlockUpdates"], None]) -> None:
        self._listeners.append(fn)

    def add_run_listener(self, fn: Callable[
            [List[Tuple[int, "cat.BlockUpdates"]]], None]) -> None:
        """Commit-stream listener at RUN granularity: `fn(items)` fires
        once per atomic commit with every (block_id, updates) it sealed,
        in order. A single add_block is a run of one."""
        self._run_listeners.append(fn)

    def _notify(self, block_id: int, updates: "cat.BlockUpdates") -> None:
        self._notify_run([(block_id, updates)])

    def _notify_run(self,
                    items: List[Tuple[int, "cat.BlockUpdates"]]) -> None:
        if not items:
            return
        for fn in self._run_listeners:
            try:
                fn(items)
            except Exception:  # noqa: BLE001 — listeners must not break commit
                pass
        for block_id, updates in items:
            for fn in self._listeners:
                try:
                    fn(block_id, updates)
                except Exception:  # noqa: BLE001 — see above
                    pass

    # ---- write path ----
    def add_block(self, updates: "cat.BlockUpdates") -> int:
        acc = self._accum
        if acc is not None:
            # accumulation mode (execution lane): stage into the shared
            # master batch; reads during staging go through the
            # read-your-writes overlay, so block N+1 sees block N's
            # pending rows. Nothing touches the DB until
            # end_accumulation commits the whole run atomically.
            block_id = self._last + 1
            self._stage_block(acc.master, block_id, updates)
            self._last = block_id
            acc.notifications.append((block_id, updates))
            return block_id
        block_id = self._last + 1
        wb = WriteBatch()
        self._stage_block(wb, block_id, updates)
        self._db.write(wb)
        self._last = block_id
        if self._genesis == 0:
            self._genesis = 1
        self._notify(block_id, updates)
        return block_id

    # ---- block accumulation (execution-lane run commit) ----
    def begin_accumulation(self, speculative: bool = False) -> None:
        """Enter accumulation mode: subsequent add_block calls stage into
        ONE shared WriteBatch (committed by end_accumulation) instead of
        one DB write per block. Reads issued while accumulating — the
        handler's read-your-writes during execution, read-only queries —
        observe the staged blocks through the overlay view. Takes the
        staging lock; the caller MUST reach end/abort_accumulation.

        `speculative=True` (the execution lane's pre-commit runs): the
        overlay + head bump are visible ONLY to the calling thread — a
        speculative run may abort, so other threads (read-only queries,
        proof serving) keep reading the committed base until
        end_accumulation makes the run durable; link_st_chain defers
        instead of blocking while the speculation holds the lock."""
        self._staging_mu.acquire()
        try:
            if self._accum is not None:
                raise BlockchainError("accumulation already active")
            overlay: Dict[bytes, Optional[bytes]] = {}
            view = _StagedReadView(self._db, overlay)
            install = view
            if speculative:
                install = _SpecOverlayView(self._db, view,
                                           threading.get_ident())
            self._accum = _Accumulation(master=_MirroredBatch(overlay),
                                        base_last=self._last,
                                        speculative=speculative,
                                        owner=threading.get_ident())
            self._begin_staged_reads_locked(install)
        except BaseException:
            self._accum = None
            self._staging_mu.release()
            raise

    def end_accumulation(self, extra: Optional[WriteBatch] = None,
                         defer: bool = False) -> int:
        """Commit the accumulated run in one atomic WriteBatch. `extra`
        ops (e.g. the run's reserved-pages/reply rows when they live in
        the same DB) ride the same batch, making apply atomic across
        ledger and reply state. Returns the new head.

        Default mode writes the BASE db while the staged-read view is
        still installed: unsynchronized readers (read-only queries on
        the dispatcher) see the staged values through the overlay right
        up to the moment the same values are durably in the base — no
        torn window where a key's new value momentarily vanishes. A
        failed write rolls the head back (abort semantics) so a retry
        re-stages from the pre-run state instead of double-appending.

        `defer=True` (the durability pipeline's seal path, requires
        attach_durability): nothing touches the base here — the run's
        overlay merges into the pending store BEFORE the staged view
        uninstalls (readers hand over from overlay to pending with no
        torn window, the same invariant as the direct write), and the
        batch is stashed for `take_deferred()`; the pipeline's io
        thread applies it as part of a concatenated group write and
        fsyncs once per group."""
        acc = self._accum
        if acc is None:
            raise BlockchainError("no accumulation active")
        store = self._pending_store if defer else None
        if defer and store is None:
            raise BlockchainError("defer=True without attach_durability")
        try:
            if extra is not None:
                acc.master.ops.extend(extra.ops)
                if store is not None:
                    # extra ops bypassed the mirrored batch: fold them
                    # into the overlay so the pending store carries the
                    # WHOLE run (reply pages included), not just the
                    # staged ledger rows
                    for k, v in extra.ops:
                        acc.master._overlay[k] = v
            if acc.master.ops:
                if store is not None:
                    run_no = store.stage(acc.master._overlay)
                    self._deferred = (run_no, acc.master,
                                      raw_base(self._base_db))
                else:
                    self._base_db.write(acc.master)
        except BaseException:
            self._accum = None
            self._end_staged_reads_locked()
            self._last = acc.base_last
            self._staging_mu.release()
            raise
        self._accum = None
        self._end_staged_reads_locked()
        if self._last and self._genesis == 0:
            self._genesis = 1
        self._staging_mu.release()
        self._notify_run(acc.notifications)
        return self._last

    def abort_accumulation(self) -> None:
        """Drop the staged run (run execution failed): the head rolls
        back to where begin_accumulation found it, nothing was written."""
        acc = self._accum
        if acc is None:
            return
        try:
            self._accum = None
            self._end_staged_reads_locked()
            self._last = acc.base_last
        finally:
            self._staging_mu.release()

    def add_blocks(self, updates_list: List["cat.BlockUpdates"]) -> int:
        """Append N blocks in ONE atomic WriteBatch (the bulk form of
        add_block — engines may override with batched hashing)."""
        if not updates_list:
            return self._last
        self.begin_accumulation()
        try:
            for bu in updates_list:
                self.add_block(bu)
        except BaseException:
            self.abort_accumulation()
            raise
        return self.end_accumulation()

    def _put_block_row(self, wb: WriteBatch, block_id: int,
                       block: "Block") -> None:
        """Tail shared by every engine's _stage_block."""
        wb.put(_bid(block_id), ser.encode_msg(block), self._F_BLOCKS)
        wb.put(_K_LAST, _bid(block_id), self._F_MISC)
        if block_id == 1:
            wb.put(_K_GENESIS, _bid(1), self._F_MISC)

    # ---- read path ----
    def get_block(self, block_id: int) -> Optional["Block"]:
        raw = self._db.get(_bid(block_id), self._F_BLOCKS)
        return ser.decode_msg(raw, Block) if raw is not None else None

    def get_raw_block(self, block_id: int) -> Optional[bytes]:
        return self._db.get(_bid(block_id), self._F_BLOCKS)

    def block_digest(self, block_id: int) -> bytes:
        if block_id == 0:
            return b""
        blk = self.get_block(block_id)
        if blk is None:
            raise BlockchainError(f"missing block {block_id}")
        return blk.digest()

    def state_digest(self) -> bytes:
        """Digest of the whole chain head — what checkpoint certificates
        sign (reference: kv_blockchain state hash). Routed head: a
        non-owner thread asking during an open speculation digests the
        committed chain, not the private overlay."""
        last = self.last_block_id
        return self.block_digest(last) if last else b"\x00" * 32

    # ---- pruning (reference: deleteBlocksUntil / pruning_handler) ----
    def delete_blocks_until(self, until_block_id: int) -> int:
        """Delete block bodies in [genesis, until); latest state is kept.
        Returns the new genesis id."""
        if until_block_id > self._last:
            raise BlockchainError("cannot prune the chain head")
        start = self._genesis if self._genesis else 1
        if until_block_id <= start:
            return self._genesis
        self._pending_barrier()   # direct write: sealed runs land first
        wb = WriteBatch()
        for bid in range(start, until_block_id):
            wb.delete(_bid(bid), self._F_BLOCKS)
        wb.put(_K_GENESIS, _bid(until_block_id), self._F_MISC)
        self._db.write(wb)
        self._genesis = until_block_id
        return self._genesis

    # ---- state-transfer staging (reference v4 st_chain) ----
    # comparisons use the routed `last_block_id`, not `self._last`: the
    # ST plane runs on the dispatcher, which must not observe a
    # speculative head bump (it would silently skip staging real blocks
    # in the speculated range)
    def _durable_db(self) -> IDBClient:
        """The writable committed-base DB. While an accumulation is open
        `self._db` is a read-only staged view; direct writes that are
        NOT part of the accumulation (ST staging rows — a disjoint
        keyspace) must target the base. Racy read of `_db` is safe:
        both branches point at a valid writable base."""
        db = self._db
        if isinstance(db, (_StagedReadView, _SpecOverlayView)):
            return self._base_db
        return db

    def add_raw_st_block(self, block_id: int, raw: bytes) -> None:
        if block_id <= self.last_block_id:
            return
        self._durable_db().put(_bid(block_id), raw, self._F_ST)

    def add_raw_st_blocks(self, blocks: Dict[int, bytes]) -> int:
        """Stage a whole verified window of raw blocks in ONE WriteBatch
        (vs one put per block) — the adoption path of the pipelined state
        transfer. Returns the number of blocks actually staged."""
        wb = WriteBatch()
        n = 0
        head = self.last_block_id
        for block_id in sorted(blocks):
            if block_id <= head:
                continue
            wb.put(_bid(block_id), blocks[block_id], self._F_ST)
            n += 1
        if n:
            self._durable_db().write(wb)
        return n

    def has_st_block(self, block_id: int) -> bool:
        return self._db.has(_bid(block_id), self._F_ST)

    # hooks for read-your-writes during batched linking; the categorized
    # engine overrides them to rebind its cached merkle trees too.
    # `_locked`: every caller holds `kvbc.staging` — lexically
    # (link_st_chain, add_blocks) or across the accumulation bracket
    # (begin/end/abort_accumulation)
    def _begin_staged_reads_locked(self, view: "_StagedReadView") -> None:
        self._base_db = self._db
        self._db = view

    def _end_staged_reads_locked(self) -> None:
        self._db = self._base_db

    def _acquire_staging_for_link(self, timeout: float = 5.0) -> bool:
        """Take the staging lock for a link segment — or DEFER when the
        current holder is a speculative accumulation (only the caller's
        own thread can resolve it; see link_st_chain docstring). A
        non-speculative holder (a normal execution run mid-commit) is
        brief: wait it out within `timeout`."""
        deadline = time.monotonic() + timeout
        while True:
            if self._staging_mu.acquire(timeout=0.05):
                return True
            acc = self._accum       # racy read; deferring is always safe
            if acc is not None and acc.speculative:
                return False
            if time.monotonic() >= deadline:
                return False

    def link_st_chain(self) -> int:
        """Adopt ALL contiguous staged blocks after the head as one
        write_group of per-block batches (one engine record per segment
        on NativeDB), re-executing their updates and verifying
        recorded digests so a Byzantine source can't inject state.

        Staging block N+1 must read state block N just wrote (parent
        block row, merkle nodes, immutable-rewrite checks), so the loop
        stages against a read-your-writes overlay and commits once per
        LINK_SEGMENT_BLOCKS-sized segment of the contiguous prefix
        instead of once per block (bounding batch memory on huge
        suffixes). On a bad staged block the verified prefix before it
        still commits, the bad row is dropped (so retries can re-fetch
        from another source instead of wedging on the same bytes), and
        the error propagates. Returns the new head.

        SPECULATION COMPOSITION: a speculative accumulation holds the
        staging lock for the whole commit-combine window, and only the
        dispatcher — the thread calling THIS function — can seal or
        abort it. Blocking here would deadlock, so the lock acquisition
        defers (returns the current head, nothing linked) whenever the
        holder is speculative; the ST manager retries on its next
        tick/window, after the speculation resolved."""
        nxt: Optional[int] = None
        prev_digest = b""
        bad: Optional[int] = None
        error: Optional[BaseException] = None

        def commit(wbs: List[WriteBatch],
                   adopted: List[Tuple[int, "cat.BlockUpdates"]]) -> None:
            if bad is not None:
                wbs.append(WriteBatch().delete(_bid(bad), self._F_ST))
            group = [wb for wb in wbs if wb.ops]
            if group:
                # per-block batches ride the group-commit apply seam
                # (ISSUE 15): ONE concatenated engine record / CRC /
                # fsync per segment on NativeDB instead of re-copying
                # every block's ops into a master batch here. The
                # durability pending view exposes no write_group on
                # purpose — unwrap to the raw base for the group apply.
                getattr(self._db, "base", self._db).write_group(group)
            if adopted:
                self._last = adopted[-1][0]
                if self._genesis == 0:
                    self._genesis = 1
                self._notify_run(adopted)

        while error is None:
            # one segment at a time under the staging lock: the
            # execution lane's accumulation shares the staged-read
            # redirect and must never interleave with linking. The head
            # snapshot happens under the lock too — an accumulation in
            # another thread moves self._db and self._last.
            if not self._acquire_staging_for_link():
                break                 # speculation open: defer, no link
            try:
                # the segment commit writes the base directly: sealed
                # runs must land before it (ST adoption drained the
                # pipeline already; this is the loud backstop)
                self._pending_barrier()
            except BaseException:
                self._staging_mu.release()
                raise
            base_db = self._db
            if nxt is None:
                nxt = self._last + 1
                prev_digest = (self.block_digest(self._last)
                               if self._last else b"")
            overlay: Dict[bytes, Optional[bytes]] = {}
            view = _StagedReadView(base_db, overlay)
            wbs: List[WriteBatch] = []
            adopted: List[Tuple[int, "cat.BlockUpdates"]] = []
            self._begin_staged_reads_locked(view)
            try:
                while len(adopted) < self.LINK_SEGMENT_BLOCKS:
                    raw = base_db.get(_bid(nxt), self._F_ST)
                    if raw is None:
                        break
                    wb = _MirroredBatch(overlay)
                    try:
                        blk = ser.decode_msg(raw, Block)
                        if blk.block_id != nxt:
                            raise BlockchainError(
                                f"staged block id mismatch: "
                                f"{blk.block_id} != {nxt}")
                        if blk.parent_digest != prev_digest:
                            raise BlockchainError(
                                f"parent digest mismatch at {nxt}")
                        updates = cat.decode_block_updates(blk.updates_blob)
                        rebuilt = self._stage_block(wb, nxt, updates)
                        if rebuilt.category_digests != blk.category_digests:
                            raise BlockchainError(
                                f"category digest mismatch at {nxt}")
                    except Exception as e:  # noqa: BLE001 — commit prefix
                        bad, error = nxt, e
                        break
                    wb.delete(_bid(nxt), self._F_ST)
                    wbs.append(wb)
                    adopted.append((nxt, updates))
                    prev_digest = blk.digest()
                    nxt += 1
            finally:
                try:
                    self._end_staged_reads_locked()
                    commit(wbs, adopted)      # still under the lock: the
                    # segment's adoption (head + db write) must land
                    # before an accumulation can slot blocks after it
                finally:
                    self._staging_mu.release()
            if len(adopted) < self.LINK_SEGMENT_BLOCKS:
                break               # ran out of staged blocks (or hit bad)
        if error is not None:
            raise error
        return self.last_block_id   # routed: a deferred link must not
        # leak the speculation's private head bump to the ST caller


class KeyValueBlockchain(BlockStoreMixin):
    _F_BLOCKS = _BLOCKS
    _F_MISC = _MISC
    _F_ST = _ST

    def __init__(self, db: IDBClient, use_device_hashing: bool = True) -> None:
        self._db = db
        self._use_device = use_device_hashing
        self._trees: Dict[str, SparseMerkleTree] = {}
        self._load_head()

    def _tree(self, category: str) -> SparseMerkleTree:
        t = self._trees.get(category)
        if t is None:
            t = SparseMerkleTree(self._db, family=f"smt.{category}".encode(),
                                 use_device=self._use_device)
            self._trees[category] = t
        return t

    # batched-link read redirection must cover the cached merkle trees:
    # a block's update reads sibling nodes the previous block in the same
    # batch may have written
    def _begin_staged_reads_locked(self, view) -> None:
        super()._begin_staged_reads_locked(view)
        for t in self._trees.values():
            t._db = view

    def _end_staged_reads_locked(self) -> None:
        super()._end_staged_reads_locked()
        # trees created during staging bound to the view; rebind all
        for t in self._trees.values():
            t._db = self._db

    def _stage_block(self, wb: WriteBatch, block_id: int,
                     updates: cat.BlockUpdates) -> Block:
        digests: Dict[str, bytes] = {}
        for name in sorted(updates.categories):
            cat_type, cu = updates.categories[name]
            digests[name] = cat.stage_category(
                self._db, wb, name, cat_type, cu, block_id, self._tree)
        parent = self.block_digest(block_id - 1) if block_id > 1 else b""
        block = Block(block_id=block_id, parent_digest=parent,
                      category_digests=digests,
                      updates_blob=cat.encode_block_updates(updates))
        self._put_block_row(wb, block_id, block)
        return block

    def add_blocks(self, updates_list: List[cat.BlockUpdates]) -> int:
        """Bulk append with cross-block merkle batching: N blocks land in
        ONE WriteBatch, and every block_merkle category's node rehashing
        for the whole run happens level-wise — one `ops/sha256` call per
        tree level spanning ALL blocks' changed nodes
        (SparseMerkleTree.update_batches) — instead of N independent
        per-block host walks. Per-block roots, archive rows, and the
        block rows themselves are byte-identical to N add_block calls."""
        if not updates_list:
            return self._last
        if len(updates_list) == 1:
            return self.add_block(updates_list[0])
        with self._staging_mu:
            if self._accum is not None:
                raise BlockchainError("add_blocks inside accumulation")
            self._pending_barrier()   # bulk ingest writes the base direct
            first = self._last + 1
            overlay: Dict[bytes, Optional[bytes]] = {}
            view = _StagedReadView(self._db, overlay)
            master = _MirroredBatch(overlay)
            self._begin_staged_reads_locked(view)
            try:
                # phase 1: all merkle categories, level-synchronous
                # across the whole run
                merkle: Dict[str, List[Dict[bytes, Optional[bytes]]]] = {}
                for i, bu in enumerate(updates_list):
                    for name, (ct, cu) in bu.categories.items():
                        if ct != cat.BLOCK_MERKLE:
                            continue
                        per_block = merkle.setdefault(
                            name, [{} for _ in updates_list])
                        per_block[i] = {
                            k: (hashlib.sha256(v).digest()
                                if v is not None else None)
                            for k, v in cu.kv.items()}
                roots: Dict[str, List[bytes]] = {}
                for name, per_block in merkle.items():
                    master.put(name.encode(), b"", cat.SMT_REGISTRY_FAMILY)
                    roots[name] = self._tree(name).update_batches(
                        per_block, batch=master, first_version=first)
                # phase 2: per-block data rows + chained block rows
                prev = (self.block_digest(self._last)
                        if self._last else b"")
                last_notified: List[Tuple[int, cat.BlockUpdates]] = []
                for i, bu in enumerate(updates_list):
                    bid = first + i
                    digests: Dict[str, bytes] = {}
                    for name in sorted(bu.categories):
                        ct, cu = bu.categories[name]
                        if ct == cat.BLOCK_MERKLE:
                            digests[name] = roots[name][i]
                            cat.stage_merkle_data(master, name, cu, bid)
                        else:
                            digests[name] = cat.stage_category(
                                self._db, master, name, ct, cu, bid,
                                self._tree)
                    block = Block(block_id=bid, parent_digest=prev,
                                  category_digests=digests,
                                  updates_blob=cat.encode_block_updates(bu))
                    self._put_block_row(master, bid, block)
                    prev = block.digest()
                    last_notified.append((bid, bu))
                # write to the BASE while the view is still installed —
                # same no-torn-window rule as end_accumulation
                self._base_db.write(master)
            finally:
                self._end_staged_reads_locked()
            self._last = first + len(updates_list) - 1
            if self._genesis == 0:
                self._genesis = 1
        self._notify_run(last_notified)
        return self._last

    # ---- categorized reads ----
    def get_latest(self, category: str, key: bytes,
                   cat_type: str = cat.VERSIONED_KV
                   ) -> Optional[Tuple[int, bytes]]:
        return cat.get_latest(self._db, category, cat_type, key)

    def get_versioned(self, category: str, key: bytes,
                      block_id: int) -> Optional[bytes]:
        return cat.get_versioned(self._db, category, key, block_id)

    def prove(self, category: str, key: bytes):
        """Merkle proof for a block_merkle-category key (latest state)."""
        return self._tree(category).prove(key)

    def merkle_root(self, category: str) -> bytes:
        return self._tree(category).root()

    # ---- versioned proofs (reference tree.cpp serves historical
    # versions; roots are anchored in each block's category digests) ----
    def prove_at(self, category: str, key: bytes, block_id: int):
        """Merkle proof for the key AS OF `block_id` (any retained
        block). Verify against `merkle_root_at(category, block_id)`."""
        return self._tree(category).prove_at(key, block_id)

    def merkle_root_at(self, category: str,
                       block_id: int) -> Optional[bytes]:
        """The category's root at a block — read from the BLOCK ROW (the
        agreed chain), not the tree, so a verifier checks proofs against
        consensus-certified state."""
        blk = self.get_block(block_id)
        if blk is not None and category in blk.category_digests:
            return blk.category_digests[category]
        # the category may not have been touched at exactly block_id:
        # its root there is the newest tree version ≤ block_id
        return self._tree(category).root_at(block_id)

    def merkle_value_hash_at(self, category: str, key: bytes,
                             block_id: int) -> Optional[bytes]:
        return self._tree(category).get_value_hash_at(key, block_id)

    def delete_blocks_until(self, until_block_id: int) -> int:
        """Prune block bodies AND the merkle archives' stale nodes: a
        proof can only be asked against a retained block's root, so
        archive rows superseded before the new genesis are garbage
        (reference stale-node GC on pruning). Categories come from the
        durable registry — the in-memory tree cache forgets categories
        untouched since the last restart."""
        genesis = super().delete_blocks_until(until_block_id)
        for name_b, _ in self._db.range_iter(cat.SMT_REGISTRY_FAMILY):
            self._tree(name_b.decode()).prune_versions(genesis)
        return genesis
