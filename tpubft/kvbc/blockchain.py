"""Categorized key-value blockchain.

Rebuild of the reference's `concord::kvbc::categorization::KeyValueBlockchain`
(/root/reference/kvbc/include/categorization/kv_blockchain.h:40,
src/categorization/kv_blockchain.cpp): blocks are maps category→updates,
chained by parent digest; per-category state digests (Merkle root for
block_merkle categories) feed the block digest, which is what consensus
checkpoints sign. Also carries the v4-style `st_chain` staging area
(src/v4blockchain/detail/st_chain.cpp) so state transfer can land blocks
out of order and link them with integrity checks.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tpubft.kvbc import categories as cat
from tpubft.kvbc.sparse_merkle import SparseMerkleTree
from tpubft.storage.interfaces import IDBClient, WriteBatch
from tpubft.utils import serialize as ser

_BLOCKS = b"blk.blocks"
_MISC = b"blk.misc"
_ST = b"blk.st"

_K_LAST = b"last"
_K_GENESIS = b"genesis"


class BlockchainError(Exception):
    pass


@dataclass
class Block:
    block_id: int
    parent_digest: bytes
    category_digests: Dict[str, bytes] = field(default_factory=dict)
    updates_blob: bytes = b""

    SPEC = [("block_id", "u64"), ("parent_digest", "bytes"),
            ("category_digests", ("map", "str", "bytes")),
            ("updates_blob", "bytes")]

    def digest(self) -> bytes:
        return hashlib.sha256(ser.encode_msg(self)).digest()


def _bid(block_id: int) -> bytes:
    return block_id.to_bytes(8, "big")


class BlockStoreMixin:
    """Shared block-store + ST-staging + pruning plumbing for both ledger
    engines (categorized and v4 — they differ only in keyspace names and
    how a block's updates are staged). Engines set the class attributes
    `_F_BLOCKS`/`_F_MISC`/`_F_ST` and implement `_stage_block(wb,
    block_id, updates) -> Block`; the mixin provides everything keyed off
    the shared block format."""

    _F_BLOCKS: bytes
    _F_MISC: bytes
    _F_ST: bytes

    def _load_head(self) -> None:
        last = self._db.get(_K_LAST, self._F_MISC)
        self._last = int.from_bytes(last, "big") if last else 0
        gen = self._db.get(_K_GENESIS, self._F_MISC)
        self._genesis = int.from_bytes(gen, "big") if gen else 0
        self._listeners: List[Callable[[int, "cat.BlockUpdates"],
                                       None]] = []

    # ---- properties ----
    @property
    def last_block_id(self) -> int:
        return self._last

    @property
    def genesis_block_id(self) -> int:
        return self._genesis

    # ---- commit-stream listeners (thin-replica publishing; reference:
    # kvbc Replica feeds SubUpdateBuffers from the commit path) ----
    def add_listener(self,
                     fn: Callable[[int, "cat.BlockUpdates"], None]) -> None:
        self._listeners.append(fn)

    def _notify(self, block_id: int, updates: "cat.BlockUpdates") -> None:
        for fn in self._listeners:
            try:
                fn(block_id, updates)
            except Exception:  # noqa: BLE001 — listeners must not break commit
                pass

    # ---- write path ----
    def add_block(self, updates: "cat.BlockUpdates") -> int:
        block_id = self._last + 1
        wb = WriteBatch()
        self._stage_block(wb, block_id, updates)
        self._db.write(wb)
        self._last = block_id
        if self._genesis == 0:
            self._genesis = 1
        self._notify(block_id, updates)
        return block_id

    def _put_block_row(self, wb: WriteBatch, block_id: int,
                       block: "Block") -> None:
        """Tail shared by every engine's _stage_block."""
        wb.put(_bid(block_id), ser.encode_msg(block), self._F_BLOCKS)
        wb.put(_K_LAST, _bid(block_id), self._F_MISC)
        if block_id == 1:
            wb.put(_K_GENESIS, _bid(1), self._F_MISC)

    # ---- read path ----
    def get_block(self, block_id: int) -> Optional["Block"]:
        raw = self._db.get(_bid(block_id), self._F_BLOCKS)
        return ser.decode_msg(raw, Block) if raw is not None else None

    def get_raw_block(self, block_id: int) -> Optional[bytes]:
        return self._db.get(_bid(block_id), self._F_BLOCKS)

    def block_digest(self, block_id: int) -> bytes:
        if block_id == 0:
            return b""
        blk = self.get_block(block_id)
        if blk is None:
            raise BlockchainError(f"missing block {block_id}")
        return blk.digest()

    def state_digest(self) -> bytes:
        """Digest of the whole chain head — what checkpoint certificates
        sign (reference: kv_blockchain state hash)."""
        return self.block_digest(self._last) if self._last else b"\x00" * 32

    # ---- pruning (reference: deleteBlocksUntil / pruning_handler) ----
    def delete_blocks_until(self, until_block_id: int) -> int:
        """Delete block bodies in [genesis, until); latest state is kept.
        Returns the new genesis id."""
        if until_block_id > self._last:
            raise BlockchainError("cannot prune the chain head")
        start = self._genesis if self._genesis else 1
        if until_block_id <= start:
            return self._genesis
        wb = WriteBatch()
        for bid in range(start, until_block_id):
            wb.delete(_bid(bid), self._F_BLOCKS)
        wb.put(_K_GENESIS, _bid(until_block_id), self._F_MISC)
        self._db.write(wb)
        self._genesis = until_block_id
        return self._genesis

    # ---- state-transfer staging (reference v4 st_chain) ----
    def add_raw_st_block(self, block_id: int, raw: bytes) -> None:
        if block_id <= self._last:
            return
        self._db.put(_bid(block_id), raw, self._F_ST)

    def has_st_block(self, block_id: int) -> bool:
        return self._db.has(_bid(block_id), self._F_ST)

    def link_st_chain(self) -> int:
        """Adopt contiguous staged blocks after the head, re-executing
        their updates and verifying recorded digests so a Byzantine
        source can't inject state. Returns the new head."""
        while True:
            nxt = self._last + 1
            raw = self._db.get(_bid(nxt), self._F_ST)
            if raw is None:
                return self._last
            try:
                blk = ser.decode_msg(raw, Block)
                if blk.block_id != nxt:
                    raise BlockchainError(
                        f"staged block id mismatch: {blk.block_id} != {nxt}")
                expect_parent = (self.block_digest(self._last)
                                 if self._last else b"")
                if blk.parent_digest != expect_parent:
                    raise BlockchainError(f"parent digest mismatch at {nxt}")
                updates = cat.decode_block_updates(blk.updates_blob)
                wb = WriteBatch()
                rebuilt = self._stage_block(wb, nxt, updates)
                if rebuilt.category_digests != blk.category_digests:
                    raise BlockchainError(
                        f"category digest mismatch at {nxt}")
            except Exception:
                # drop the bad staged block so retries can re-fetch it from
                # another source instead of wedging on the same bytes
                self._db.delete(_bid(nxt), self._F_ST)
                raise
            wb.delete(_bid(nxt), self._F_ST)
            self._db.write(wb)
            self._last = nxt
            if self._genesis == 0:
                self._genesis = 1
            self._notify(nxt, updates)


class KeyValueBlockchain(BlockStoreMixin):
    _F_BLOCKS = _BLOCKS
    _F_MISC = _MISC
    _F_ST = _ST

    def __init__(self, db: IDBClient, use_device_hashing: bool = True) -> None:
        self._db = db
        self._use_device = use_device_hashing
        self._trees: Dict[str, SparseMerkleTree] = {}
        self._load_head()

    def _tree(self, category: str) -> SparseMerkleTree:
        t = self._trees.get(category)
        if t is None:
            t = SparseMerkleTree(self._db, family=f"smt.{category}".encode(),
                                 use_device=self._use_device)
            self._trees[category] = t
        return t

    def _stage_block(self, wb: WriteBatch, block_id: int,
                     updates: cat.BlockUpdates) -> Block:
        digests: Dict[str, bytes] = {}
        for name in sorted(updates.categories):
            cat_type, cu = updates.categories[name]
            digests[name] = cat.stage_category(
                self._db, wb, name, cat_type, cu, block_id, self._tree)
        parent = self.block_digest(block_id - 1) if block_id > 1 else b""
        block = Block(block_id=block_id, parent_digest=parent,
                      category_digests=digests,
                      updates_blob=cat.encode_block_updates(updates))
        self._put_block_row(wb, block_id, block)
        return block

    # ---- categorized reads ----
    def get_latest(self, category: str, key: bytes,
                   cat_type: str = cat.VERSIONED_KV
                   ) -> Optional[Tuple[int, bytes]]:
        return cat.get_latest(self._db, category, cat_type, key)

    def get_versioned(self, category: str, key: bytes,
                      block_id: int) -> Optional[bytes]:
        return cat.get_versioned(self._db, category, key, block_id)

    def prove(self, category: str, key: bytes):
        """Merkle proof for a block_merkle-category key (latest state)."""
        return self._tree(category).prove(key)

    def merkle_root(self, category: str) -> bytes:
        return self._tree(category).root()

    # ---- versioned proofs (reference tree.cpp serves historical
    # versions; roots are anchored in each block's category digests) ----
    def prove_at(self, category: str, key: bytes, block_id: int):
        """Merkle proof for the key AS OF `block_id` (any retained
        block). Verify against `merkle_root_at(category, block_id)`."""
        return self._tree(category).prove_at(key, block_id)

    def merkle_root_at(self, category: str,
                       block_id: int) -> Optional[bytes]:
        """The category's root at a block — read from the BLOCK ROW (the
        agreed chain), not the tree, so a verifier checks proofs against
        consensus-certified state."""
        blk = self.get_block(block_id)
        if blk is not None and category in blk.category_digests:
            return blk.category_digests[category]
        # the category may not have been touched at exactly block_id:
        # its root there is the newest tree version ≤ block_id
        return self._tree(category).root_at(block_id)

    def merkle_value_hash_at(self, category: str, key: bytes,
                             block_id: int) -> Optional[bytes]:
        return self._tree(category).get_value_hash_at(key, block_id)

    def delete_blocks_until(self, until_block_id: int) -> int:
        """Prune block bodies AND the merkle archives' stale nodes: a
        proof can only be asked against a retained block's root, so
        archive rows superseded before the new genesis are garbage
        (reference stale-node GC on pruning). Categories come from the
        durable registry — the in-memory tree cache forgets categories
        untouched since the last restart."""
        genesis = super().delete_blocks_until(until_block_id)
        for name_b, _ in self._db.range_iter(cat.SMT_REGISTRY_FAMILY):
            self._tree(name_b.decode()).prune_versions(genesis)
        return genesis
