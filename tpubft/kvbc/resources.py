"""Resources manager — adaptive pruning rate from resource utilization.

Rebuild of the reference's resources-manager
(/root/reference/kvbc/src/resources-manager/: IResourceManager's
``getPruneBlocksPerSecond`` driven by measured resource utilization): the
ledger must not grow without bound, but pruning competes with consensus
for I/O — so the recommended prune rate adapts to how busy the replica
is. Utilization sources are pluggable; the default tracks the add-block
rate (a busy chain prunes gently) and the ledger's block backlog
relative to a configured retention target (a deep backlog prunes
harder).

The consensus-coordinated prune decision stays where it is (the operator
PruneRequest / pruning handler); this component answers "how fast", the
role split the reference has between ResourceManager and the pruning
reserved-pages client.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional


@dataclass
class ResourceConfig:
    # desired retained history depth, in blocks
    retention_blocks: int = 10_000
    # prune-rate bounds (blocks/sec recommended to the operator/cron)
    min_prune_rate: float = 0.0
    max_prune_rate: float = 1000.0
    # consensus write rate (blocks/sec) considered "fully busy" — at or
    # above this, pruning backs off to min_prune_rate
    busy_add_rate: float = 200.0
    # sliding measurement window
    window_s: float = 10.0


class ResourceManager:
    """Thread-safe utilization tracker + prune-rate recommendation."""

    def __init__(self, config: Optional[ResourceConfig] = None) -> None:
        self.cfg = config or ResourceConfig()
        self._lock = threading.Lock()
        self._adds = []                # monotonic timestamps of add-block
        self._pruned = 0

    # ---- signals ----
    def on_block_added(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._adds.append(now)
            horizon = now - self.cfg.window_s
            while self._adds and self._adds[0] < horizon:
                self._adds.pop(0)

    def add_rate(self, now: Optional[float] = None) -> float:
        """Blocks/sec over the sliding window."""
        now = time.monotonic() if now is None else now
        with self._lock:
            horizon = now - self.cfg.window_s
            recent = [t for t in self._adds if t >= horizon]
            return len(recent) / self.cfg.window_s

    # ---- recommendation (IResourceManager::getPruneBlocksPerSecond) ----
    def prune_blocks_per_second(self, genesis_id: int, last_id: int,
                                now: Optional[float] = None) -> float:
        """Backlog pressure scaled down by write-path business."""
        backlog = max(0, (last_id - genesis_id) - self.cfg.retention_blocks)
        if backlog == 0:
            return self.cfg.min_prune_rate
        # pressure: how far past retention we are, saturating at 2x
        pressure = min(1.0, backlog / max(1, self.cfg.retention_blocks))
        # business: 0 (idle) .. 1 (fully busy)
        busy = min(1.0, self.add_rate(now) / self.cfg.busy_add_rate)
        rate = (self.cfg.min_prune_rate
                + (self.cfg.max_prune_rate - self.cfg.min_prune_rate)
                * pressure * (1.0 - busy))
        return max(self.cfg.min_prune_rate,
                   min(self.cfg.max_prune_rate, rate))

    def recommended_prune_until(self, genesis_id: int, last_id: int,
                                interval_s: float,
                                now: Optional[float] = None) -> int:
        """Prune target for one cron interval: genesis + rate*interval,
        clamped so retention is honored."""
        rate = self.prune_blocks_per_second(genesis_id, last_id, now)
        budget = int(rate * interval_s)
        ceiling = max(genesis_id, last_id - self.cfg.retention_blocks)
        return min(genesis_id + budget, ceiling)


def attach(blockchain, config: Optional[ResourceConfig] = None
           ) -> ResourceManager:
    """Wire a ResourceManager to a blockchain's commit stream."""
    rm = ResourceManager(config)
    blockchain.add_listener(lambda _bid, _updates: rm.on_block_added())
    return rm
