"""TLS transport with per-node pinned certificates.

Rebuild of the reference's production transport
(/root/reference/communication/src/TlsTCPCommunication.cpp +
AsyncTlsConnection.cpp): TLS over the length-prefixed TCP framing, with
each node presenting its own self-signed certificate and every peer
pinned by certificate — an attacker with network access but no node key
can neither impersonate a replica nor read traffic.

Authentication model (reference AsyncTlsConnection::verifyCertificate):
  * every node has a key + self-signed cert; the cluster's cert set is
    distributed out of band (keygen writes a certs dir per deployment);
  * both sides request and verify the peer certificate against a trust
    bundle of exactly the cluster's certs (each self-signed cert acts as
    its own CA — nothing outside the bundle can handshake at all);
  * the presented certificate is then BOUND to the claimed node id by
    SHA-256 fingerprint pinning: the dialer checks the acceptor's cert
    is node X's cert, the acceptor checks the id sent in the handshake
    matches the cert that authenticated the connection. A valid cluster
    member can therefore not impersonate another member either.

Threading/framing are inherited from PlainTcpCommunication; the hooks
(_wrap_outbound/_wrap_inbound/_authenticate_inbound) insert the TLS
handshake and pin checks. ssl.SSLError subclasses OSError, so the base
transport's error paths handle refused handshakes as dead connections.
"""
from __future__ import annotations

import hashlib
import os
import socket
import ssl
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from tpubft.comm.interfaces import CommConfig, NodeNum
from tpubft.comm.tcp import PlainTcpCommunication
from tpubft.utils.logging import get_logger

log = get_logger("comm.tls")


def cert_path(certs_dir: str, node: NodeNum) -> str:
    return os.path.join(certs_dir, f"node-{node}.crt")


def key_path(certs_dir: str, node: NodeNum) -> str:
    return os.path.join(certs_dir, f"node-{node}.key")


@dataclass
class TlsConfig(CommConfig):
    """CommConfig + certificate material (reference TlsTcpConfig,
    communication/include/communication/CommDefs.hpp). `certs_dir` holds
    node-<id>.crt for every endpoint and this node's node-<self>.key;
    `key_password` decrypts the private key when it was generated
    encrypted-at-rest (keygen --password, the secretsmanager role)."""
    certs_dir: str = ""
    key_password: Optional[str] = None
    # multiplex mode (reference TlsMultiplexConfig): ids at or above this
    # floor are client-space principals that may share carrier
    # connections; None = plain one-connection-per-pair TLS
    mux_client_floor: Optional[int] = None


def _fingerprint(der: bytes) -> bytes:
    return hashlib.sha256(der).digest()


def _load_cert(path: str) -> Tuple[str, bytes]:
    """One read per cert: (PEM text for the trust bundle, DER for the
    pin)."""
    with open(path) as f:
        pem = f.read()
    return pem, ssl.PEM_cert_to_DER_cert(pem)


class TlsTcpCommunication(PlainTcpCommunication):
    # OpenSSL forbids concurrent SSL_read/SSL_write on one SSL object
    # from two threads; directional legs give each SSL socket exactly
    # one I/O thread (see _Peer's docstring)
    directional = True

    def __init__(self, config: TlsConfig):
        super().__init__(config)
        certs_dir = config.certs_dir
        if not certs_dir:
            raise ValueError(
                "TLS transport requires TlsConfig.certs_dir (a directory "
                "with node-<id>.crt for every endpoint and this node's "
                "node-<id>.key; generate with keygen --tls-certs)")
        # trust bundle = exactly the cluster's certs; pin table binds
        # each node id to its certificate fingerprint
        self._pins: Dict[NodeNum, bytes] = {}
        bundle = []
        for node in config.endpoints:
            pem, der = _load_cert(cert_path(certs_dir, node))
            self._pins[node] = _fingerprint(der)
            bundle.append(pem)
        cadata = "".join(bundle)
        own_cert = cert_path(certs_dir, config.self_id)
        own_key = key_path(certs_dir, config.self_id)

        pw = config.key_password
        self._server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        self._server_ctx.minimum_version = ssl.TLSVersion.TLSv1_3
        self._server_ctx.load_cert_chain(own_cert, own_key, password=pw)
        self._server_ctx.load_verify_locations(cadata=cadata)
        self._server_ctx.verify_mode = ssl.CERT_REQUIRED  # mutual TLS

        self._client_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        self._client_ctx.minimum_version = ssl.TLSVersion.TLSv1_3
        self._client_ctx.load_cert_chain(own_cert, own_key, password=pw)
        self._client_ctx.load_verify_locations(cadata=cadata)
        # identity is the pinned fingerprint, not a DNS name
        self._client_ctx.check_hostname = False
        self._client_ctx.verify_mode = ssl.CERT_REQUIRED

    # ---- hook implementations ----

    def _peer_fp(self, sock: ssl.SSLSocket) -> Optional[bytes]:
        der = sock.getpeercert(binary_form=True)
        return _fingerprint(der) if der else None

    def _wrap_outbound(self, sock: socket.socket,
                       node: NodeNum) -> socket.socket:
        tls = self._client_ctx.wrap_socket(sock)
        if self._peer_fp(tls) != self._pins.get(node):
            log.warning("dialed node %d presented a foreign certificate",
                        node)
            tls.close()
            raise OSError("certificate pin mismatch")
        return tls

    def _wrap_inbound(self, sock: socket.socket) -> socket.socket:
        return self._server_ctx.wrap_socket(sock, server_side=True)

    def _authenticate_inbound(self, sock: socket.socket,
                              peer_id: NodeNum) -> bool:
        ok = (isinstance(sock, ssl.SSLSocket)
              and self._peer_fp(sock) == self._pins.get(peer_id))
        if not ok:
            log.warning("inbound connection claimed id %d but its "
                        "certificate is pinned to a different node", peer_id)
        return ok


def generate_tls_material(certs_dir: str, node_ids,
                          seed: Optional[bytes] = None,
                          password: Optional[str] = None) -> None:
    """Write node-<id>.key / node-<id>.crt for every node (the keygen
    tool's cert role — reference GenerateConcordKeys emits the TLS certs
    alongside the threshold keys). Self-signed ECDSA P-256, CN carries
    the node id. `seed` derives deterministic keys — TESTS ONLY (a TLS
    cert is public, so a derivable key = impersonation); `password`
    encrypts the private keys at rest (secretsmanager role)."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    os.makedirs(certs_dir, exist_ok=True)
    for node in node_ids:
        if seed is not None:
            # same P-256 seed derivation as the signing keyfiles (the
            # scalar engine owns the formula); x509 needs an OpenSSL key
            # object regardless, so build one from the derived value
            from tpubft.crypto.scalar import ecdsa_seed_to_private
            sk = ec.derive_private_key(
                ecdsa_seed_to_private(seed + b"|tls|" + str(node).encode(),
                                      "secp256r1"),
                ec.SECP256R1())
        else:
            sk = ec.generate_private_key(ec.SECP256R1())
        name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME,
                                             f"tpubft-node-{node}")])
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (x509.CertificateBuilder()
                .subject_name(name).issuer_name(name)
                .public_key(sk.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - datetime.timedelta(minutes=5))
                .not_valid_after(now + datetime.timedelta(days=3650))
                .sign(sk, hashes.SHA256()))
        enc = (serialization.BestAvailableEncryption(password.encode())
               if password else serialization.NoEncryption())
        with open(key_path(certs_dir, node), "wb") as f:
            f.write(sk.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8, enc))
        with open(cert_path(certs_dir, node), "wb") as f:
            f.write(cert.public_bytes(serialization.Encoding.PEM))
