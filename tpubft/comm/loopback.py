"""In-process message bus for multi-replica tests.

Plays the role of the reference's client/bftclient/include/bftclient/
fake_comm.h (in-process ICommunication delivering to behavior callbacks) and
of tests/simpleKVBC/TesterReplica/WrapCommunication.cpp (drop/mutate hooks
for byzantine strategies).

Delivery is performed on a single bus thread, which gives tests
deterministic per-message ordering per destination. NOTE: real transports
do NOT guarantee serialized upcalls (TCP delivers from one reader thread
per peer) — receivers must be thread-safe; the replica's incoming-message
queue (the reference's IncomingMsgsStorage) provides the serialization.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Iterable, Optional

from tpubft.utils.racecheck import make_lock
from tpubft.comm.interfaces import (ConnectionStatus, ICommunication,
                                    IReceiver, NodeNum)

# hook(sender, dest, data) -> data' | None (None = drop the message)
Hook = Callable[[NodeNum, NodeNum, bytes], Optional[bytes]]


class LoopbackBus:
    """Shared medium connecting LoopbackCommunication endpoints."""

    def __init__(self) -> None:
        self._endpoints: Dict[NodeNum, "LoopbackCommunication"] = {}
        self._hooks: list[Hook] = []
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = make_lock("loopback_bus")
        self._closed = False

    def create(self, node: NodeNum) -> "LoopbackCommunication":
        comm = LoopbackCommunication(self, node)
        with self._lock:
            self._endpoints[node] = comm
        return comm

    def add_hook(self, hook: Hook) -> None:
        """Byzantine/fault-injection hook applied to every message in order;
        returning None drops it, returning bytes replaces the payload."""
        self._hooks.append(hook)

    def post(self, sender: NodeNum, dest: NodeNum, data: bytes) -> None:
        # lock-free fast path: post() runs for EVERY message in the
        # cluster, and the bus lock here was a measurable global hot spot
        # under load; the lock is only taken when the pump looks dead.
        # _closed guards the shutdown race: a post() that observed a live
        # thread while the None sentinel was already queued would be
        # silently dropped, and a post() after shutdown would resurrect
        # the pump — both drop the message instead.
        if self._closed:
            return
        t = self._thread
        if t is None or not t.is_alive():
            self._ensure_thread()
        self._q.put((sender, dest, data))

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._closed:
                return
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._pump, name="loopback-bus", daemon=True)
                self._thread.start()

    def _pump(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            sender, dest, data = item
            for hook in self._hooks:
                out = hook(sender, dest, data)
                if out is None:
                    data = None
                    break
                data = out
            if data is None:
                continue
            with self._lock:
                ep = self._endpoints.get(dest)
            if ep is not None:
                ep._deliver(sender, data)

    def shutdown(self) -> None:
        with self._lock:
            self._closed = True
            t = self._thread
            self._thread = None
        if t is not None and t.is_alive():
            self._q.put(None)
            t.join(timeout=5)


class LoopbackCommunication(ICommunication):
    def __init__(self, bus: LoopbackBus, node: NodeNum):
        self._bus = bus
        self._node = node
        self._receiver: Optional[IReceiver] = None
        self._running = False

    def start(self, receiver: IReceiver) -> None:
        self._receiver = receiver
        self._running = True

    def stop(self) -> None:
        self._running = False

    def is_running(self) -> bool:
        return self._running

    def send(self, dest: NodeNum, data: bytes) -> None:
        if self._running:
            self._bus.post(self._node, dest, data)

    def get_connection_status(self, node: NodeNum) -> ConnectionStatus:
        return ConnectionStatus.CONNECTED

    def _deliver(self, sender: NodeNum, data: bytes) -> None:
        if self._running and self._receiver is not None:
            self._receiver.on_new_message(sender, data)
