"""TLS multiplex transport: many principals per physical connection.

Rebuild of the reference's TlsMultiplexCommunication
(/root/reference/communication/src/TlsMultiplexCommunication.cpp:22-80):
a client process holding many principals (a pool / clientservice with N
proxies) shares ONE mutually-authenticated connection per peer instead
of N, and replicas demultiplex by an endpoint number carried in each
frame. The fd math this buys: a clientservice with 64 proxy principals
against n=7 replicas needs 7 sockets instead of 448; cluster-wide,
replicas accept one connection per client PROCESS, not per principal.

Frame format on a multiplexed link: u32le endpoint | payload.
Routing rules (the reference's TlsMultiplexReceiver::onNewMessage):
  * replica -> replica:  endpoint = destination replica id; the receiver
    checks it names itself and keeps the transport sender.
  * client principal -> replica: endpoint = the SOURCE principal; the
    receiver adopts it as the sender and remembers which carrier
    connection that principal rides (for routing replies back).
  * replica -> client principal: endpoint = the DESTINATION principal;
    the client-side hub routes to that principal's receiver.

Authenticity: the carrier connection is mutually-TLS-authenticated to
the CARRIER's node id; principals multiplexed over it are only accepted
from client-space carriers and only name client-space endpoints (a
client carrier can never inject replica-sourced frames), and every
client request additionally carries its principal's signature, verified
at admission — same trust chain as the reference.
"""
from __future__ import annotations

import struct
from typing import Callable, Dict, Optional

from tpubft.utils.racecheck import make_lock
from tpubft.comm.interfaces import (ConnectionStatus, ICommunication,
                                    IReceiver, NodeNum)

_EP = struct.Struct("<I")


def client_floor(n_val: int, num_ro: int) -> int:
    """First client-space principal id for a topology — the single
    definition every tls-mux call site derives TlsConfig.mux_client_floor
    from (replicas 0..n-1, then RO replicas, then clients/operator)."""
    return n_val + num_ro


class MultiplexTransport(ICommunication):
    """Replica-side (and single-principal-peer) multiplex wrapper: every
    frame on the wire carries the endpoint header; inbound client frames
    re-source to their principal and the principal->carrier route is
    learned for replies."""

    def __init__(self, inner: ICommunication, self_id: int,
                 is_client: Callable[[int], bool]) -> None:
        self._inner = inner
        self._self = self_id
        self._is_client = is_client
        self._carrier_of: Dict[int, int] = {}   # principal -> carrier

    # ---- lifecycle ----
    def start(self, receiver: IReceiver) -> None:
        self._inner.start(_DemuxReceiver(self, receiver))

    def stop(self) -> None:
        self._inner.stop()

    def is_running(self) -> bool:
        return self._inner.is_running()

    @property
    def max_message_size(self) -> int:
        return self._inner.max_message_size - _EP.size

    def get_connection_status(self, node: NodeNum) -> ConnectionStatus:
        if self._is_client(int(node)):
            carrier = self._carrier_of.get(int(node), int(node))
            return self._inner.get_connection_status(carrier)
        return self._inner.get_connection_status(node)

    # ---- sends ----
    def send(self, dest: NodeNum, data: bytes) -> None:
        dest = int(dest)
        frame = _EP.pack(dest) + data
        if self._is_client(dest):
            # reply path: ride the carrier the principal arrived on
            # (falls back to a direct connection for a principal that
            # dialed with its own id — a 1-principal carrier)
            self._inner.send(self._carrier_of.get(dest, dest), frame)
        else:
            self._inner.send(dest, frame)

    # ---- demux (called from _DemuxReceiver) ----
    def _route(self, src: int, data: bytes,
               receiver: IReceiver) -> None:
        if len(data) < _EP.size:
            return
        (ep,) = _EP.unpack_from(data)
        payload = data[_EP.size:]
        if ep == self._self:
            # peer-addressed traffic (replica<->replica, or a client hub
            # receiving from a replica handles this in MultiplexClientHub)
            receiver.on_new_message(src, payload)
            return
        if self._is_client(ep) and self._is_client(src):
            # a principal multiplexed over an authenticated client-space
            # carrier: adopt it as the sender, learn the return route.
            # Route learning is STICKY while the bound carrier is alive —
            # another carrier naming this principal must not redirect its
            # replies (one authenticated-but-malicious client process
            # could otherwise black-hole every other principal's replies
            # with a single forged frame); re-binding is allowed once the
            # old carrier's connection is gone (process restart/migration)
            cur = self._carrier_of.get(ep)
            if (cur is None or cur == src
                    or self._inner.get_connection_status(cur)
                    != ConnectionStatus.CONNECTED):
                self._carrier_of[ep] = src
            receiver.on_new_message(ep, payload)
            return
        # a replica-space endpoint from the wrong carrier, or a client
        # endpoint claimed by a replica carrier: spoofing — drop


class _DemuxReceiver(IReceiver):
    def __init__(self, mux: MultiplexTransport, inner: IReceiver) -> None:
        self._mux = mux
        self._inner = inner

    def on_new_message(self, sender: NodeNum, data: bytes) -> None:
        self._mux._route(int(sender), data, self._inner)

    def on_connection_status_changed(self, node, status) -> None:
        fn = getattr(self._inner, "on_connection_status_changed", None)
        if fn is not None:
            fn(node, status)


class MultiplexClientHub:
    """Client-process side: N principals share the ONE carrier transport
    (the reference clientservice/pool shape). `endpoint(principal)`
    returns an ICommunication facade for that principal; all facades ride
    the same inner connection set."""

    def __init__(self, inner: ICommunication) -> None:
        self._inner = inner
        self._endpoints: Dict[int, _MuxEndpoint] = {}
        self._started = False
        # principals start/stop from their own (application) threads:
        # endpoint registration and the carrier-start claim must be
        # atomic across them
        self._mu = make_lock("mux_hub")

    def endpoint(self, principal: int) -> "_MuxEndpoint":
        with self._mu:
            ep = self._endpoints.get(principal)
            if ep is None:
                ep = self._endpoints[principal] = _MuxEndpoint(
                    self, principal)
            return ep

    def _ensure_started(self) -> None:
        with self._mu:
            if self._started:
                return
            self._started = True
        # the carrier start itself runs outside the claim: it spawns the
        # receive thread, and a racing second principal only needs the
        # claim decided, not the start completed (sends before the
        # carrier is up drop, exactly as before)
        self._inner.start(_HubReceiver(self))

    def _route(self, src: int, data: bytes) -> None:
        if len(data) < _EP.size:
            return
        (ep_id,) = _EP.unpack_from(data)
        ep = self._endpoints.get(ep_id)
        if ep is not None and ep._receiver is not None and ep._running:
            ep._receiver.on_new_message(src, data[_EP.size:])

    def stop(self) -> None:
        # every principal's facade goes down with the shared carrier —
        # is_running() must not report a transport that silently drops
        for ep in list(self._endpoints.values()):
            ep._running = False
        self._inner.stop()
        with self._mu:
            self._started = False


class _HubReceiver(IReceiver):
    def __init__(self, hub: MultiplexClientHub) -> None:
        self._hub = hub

    def on_new_message(self, sender: NodeNum, data: bytes) -> None:
        self._hub._route(int(sender), data)

    def on_connection_status_changed(self, node, status) -> None:
        # snapshot: endpoint() may register a new principal concurrently
        for ep in list(self._hub._endpoints.values()):
            fn = getattr(ep._receiver, "on_connection_status_changed", None)
            if fn is not None:
                fn(node, status)


class _MuxEndpoint(ICommunication):
    """One principal's view of the shared carrier."""

    def __init__(self, hub: MultiplexClientHub, principal: int) -> None:
        self._hub = hub
        self.principal = principal
        self._receiver: Optional[IReceiver] = None
        self._running = False

    def start(self, receiver: IReceiver) -> None:
        self._receiver = receiver
        self._running = True
        self._hub._ensure_started()

    def stop(self) -> None:
        # the shared carrier stays up for the other principals
        self._running = False

    def is_running(self) -> bool:
        return self._running

    @property
    def max_message_size(self) -> int:
        return self._hub._inner.max_message_size - _EP.size

    def get_connection_status(self, node: NodeNum) -> ConnectionStatus:
        return self._hub._inner.get_connection_status(node)

    def send(self, dest: NodeNum, data: bytes) -> None:
        if self._running:
            self._hub._inner.send(dest, _EP.pack(self.principal) + data)
