"""Abstract transport interface.

Mirrors the reference's communication/include/communication/ICommunication.hpp:
  ICommunication (:42-79) — start/stop, ownership-taking send(NodeNum, bytes),
  broadcast send(set<NodeNum>, bytes), connection status query.
  IReceiver (:26-40) — onNewMessage / onConnectionStatusChanged callbacks.

Node numbering follows the reference convention (ReplicasInfo): replica ids
are 0..n-1, read-only replicas next, then client ids above those.
"""
from __future__ import annotations

import abc
import enum
from dataclasses import dataclass, field
from typing import Dict, Iterable, Optional, Tuple

NodeNum = int

MAX_MESSAGE_SIZE = 64 * 1024  # reference default maxExternalMessageSize


class ConnectionStatus(enum.Enum):
    UNKNOWN = 0
    CONNECTED = 1
    DISCONNECTED = 2


class IReceiver(abc.ABC):
    """Upcall interface; invoked from the transport's receive thread."""

    @abc.abstractmethod
    def on_new_message(self, sender: NodeNum, data: bytes) -> None: ...

    def on_new_messages(self, msgs: "Iterable[Tuple[NodeNum, bytes]]") \
            -> None:
        """Burst upcall: a batch-receiving transport (udp recvmmsg)
        hands one drain's worth of datagrams in a single call, so a
        receiver with its own admission queue can enqueue the burst
        without per-message overhead. Default: per-message delivery."""
        for sender, data in msgs:
            self.on_new_message(sender, data)

    def on_connection_status_changed(self, node: NodeNum,
                                     status: ConnectionStatus) -> None:
        pass


@dataclass
class CommConfig:
    """Endpoint table for socket transports (reference PlainUdpConfig /
    TlsTcpConfig, communication/include/communication/CommDefs.hpp)."""
    self_id: NodeNum
    endpoints: Dict[NodeNum, Tuple[str, int]] = field(default_factory=dict)
    max_message_size: int = MAX_MESSAGE_SIZE
    buffer_capacity: int = 8 * 1024 * 1024


class ICommunication(abc.ABC):
    @abc.abstractmethod
    def start(self, receiver: IReceiver) -> None: ...

    @abc.abstractmethod
    def stop(self) -> None: ...

    @abc.abstractmethod
    def is_running(self) -> bool: ...

    @abc.abstractmethod
    def send(self, dest: NodeNum, data: bytes) -> None:
        """Best-effort async send; must never block the caller on the
        network (reference sends are queued on comm threads)."""

    def broadcast(self, dests: Iterable[NodeNum], data: bytes) -> None:
        for d in dests:
            self.send(d, data)

    def send_burst(self, msgs: "Iterable[Tuple[NodeNum, bytes]]") -> None:
        """Burst send: many (dest, payload) pairs handed to the
        transport in one call, the sending mirror of
        `IReceiver.on_new_messages` — a batching transport (udp
        sendmmsg) can push the whole burst through one syscall. Used by
        the durability pipeline to release a committed group's replies
        as a single wire burst. Default: per-message sends."""
        for dest, data in msgs:
            self.send(dest, data)

    def get_connection_status(self, node: NodeNum) -> ConnectionStatus:
        return ConnectionStatus.UNKNOWN

    @property
    def max_message_size(self) -> int:
        return MAX_MESSAGE_SIZE
