"""Communication layer — node-addressed async message passing.

Rebuild of /root/reference/communication/ (ICommunication.hpp:42, IReceiver
:26): UDP datagrams, length-prefixed TCP, cert-pinned TLS, a factory
(CommFactory.cpp), and an in-process loopback bus (the reference's
fake_comm.h role) with byzantine hooks for tests.
"""
from tpubft.comm.factory import create_communication
from tpubft.comm.interfaces import (CommConfig, ConnectionStatus,
                                    ICommunication, IReceiver)
from tpubft.comm.loopback import LoopbackBus, LoopbackCommunication
from tpubft.comm.tcp import PlainTcpCommunication
from tpubft.comm.tls import TlsConfig, TlsTcpCommunication
from tpubft.comm.udp import PlainUdpCommunication

__all__ = [
    "CommConfig", "ConnectionStatus", "ICommunication", "IReceiver",
    "LoopbackBus", "LoopbackCommunication",
    "PlainTcpCommunication", "PlainUdpCommunication",
    "TlsConfig", "TlsTcpCommunication", "create_communication",
]
