"""Plain UDP transport — the reference's default.

Rebuild of communication/src/PlainUDPCommunication.cpp: connectionless
datagrams, one receive thread, sender identified by source endpoint lookup
in the static endpoint table. Messages above the datagram-safe size are
dropped with a metric bump, as in the reference.
"""
from __future__ import annotations

import ctypes
import socket
import threading
from typing import Optional

from tpubft.comm.interfaces import (CommConfig, ConnectionStatus,
                                    ICommunication, IReceiver, NodeNum)

# 4-byte LE sender-id prefix (same width as TCP's handshake id); source
# (ip, port) can be rewritten by NAT in odd topologies, so carry the id
# explicitly.
_HDR = 4


def _load_netio():
    try:
        from tpubft.native.build import load
        lib = load("netio")
        lib.net_sendmmsg.restype = ctypes.c_int
        lib.net_sendmmsg.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                     ctypes.c_uint32, ctypes.c_int]
        lib.net_recvmmsg.restype = ctypes.c_int
        lib.net_recvmmsg.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                     ctypes.c_uint32, ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_uint32)]
        return lib
    except Exception:  # noqa: BLE001 — transport must work without g++
        return None


class PlainUdpCommunication(ICommunication):
    def __init__(self, config: CommConfig):
        self._cfg = config
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._receiver: Optional[IReceiver] = None
        self._running = False
        # batched-send plane: the consensus dispatcher produces ~10
        # datagrams per ordered op; per-sendto syscall overhead was a top
        # profiler entry. Sends from the flusher thread (the first thread
        # to call flush(), i.e. the dispatcher) buffer here and go out as
        # ONE sendmmsg at iteration end; other threads send immediately.
        self._netio = _load_netio()
        self._flush_tid: Optional[int] = None
        self._batch: list = []
        # dest -> packed "ipv4(4, network) + port(2, little-endian)"
        # record prefix. Little-endian is the DEFINED wire order of the
        # netio record (netio.cpp assembles the field byte-by-byte), not
        # an assumption about the host.
        self._addr_pfx = {}
        for node, (host, port) in self._cfg.endpoints.items():
            try:
                self._addr_pfx[node] = (socket.inet_aton(host)
                                        + port.to_bytes(2, "little"))
            except OSError:
                pass  # non-IPv4 endpoint: always takes the sendto path

    def start(self, receiver: IReceiver) -> None:
        if self._running:
            return
        self._receiver = receiver
        host, port = self._cfg.endpoints[self._cfg.self_id]
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                              self._cfg.buffer_capacity)
        self._sock.bind((host, port))
        self._sock.settimeout(0.2)
        self._running = True
        self._thread = threading.Thread(
            target=self._recv_loop, name=f"udp-recv-{self._cfg.self_id}",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def is_running(self) -> bool:
        return self._running

    @property
    def max_message_size(self) -> int:
        return min(self._cfg.max_message_size, 65507 - _HDR)

    def send(self, dest: NodeNum, data: bytes) -> None:
        if not self._running or self._sock is None:
            return
        if len(data) > self.max_message_size:
            return  # oversize datagram: dropped (reference logs + drops)
        pkt = self._cfg.self_id.to_bytes(_HDR, "little") + data
        if (self._flush_tid == threading.get_ident()
                and self._netio is not None):
            pfx = self._addr_pfx.get(dest)
            if pfx is not None:
                self._batch.append(pfx + len(pkt).to_bytes(4, "little")
                                   + pkt)
                if len(self._batch) >= 256:
                    self._drain()       # bound buffered memory
                return
        addr = self._cfg.endpoints.get(dest)
        if addr is None:
            return
        try:
            self._sock.sendto(pkt, addr)
        except OSError:
            pass  # best-effort, like UDP itself

    def send_burst(self, msgs) -> None:
        """Burst send from ANY thread (the durability pipeline's io
        thread releases a committed group's replies here): builds the
        sendmmsg record batch locally — no shared buffer, so it never
        races the flusher thread's `_batch` — and pushes it through the
        same one-syscall path as the dispatcher's flush. Destinations
        without a packed IPv4 prefix (or without netio) fall back to
        per-datagram sendto, same as send()."""
        if not self._running or self._sock is None:
            return
        records: list = []
        for dest, data in msgs:
            if len(data) > self.max_message_size:
                continue  # oversize datagram: dropped (reference drops)
            pkt = self._cfg.self_id.to_bytes(_HDR, "little") + data
            pfx = self._addr_pfx.get(dest)
            if self._netio is not None and pfx is not None:
                records.append(pfx + len(pkt).to_bytes(4, "little") + pkt)
                if len(records) >= 256:
                    self._send_records(records)  # bound buffered memory
                    records = []
                continue
            addr = self._cfg.endpoints.get(dest)
            if addr is None:
                continue
            try:
                self._sock.sendto(pkt, addr)
            except OSError:
                pass  # best-effort, like UDP itself
        if records:
            self._send_records(records)

    def flush(self) -> None:
        """Called by the owning dispatcher at the end of each iteration;
        the first caller becomes the (single) batching thread."""
        if self._flush_tid is None:
            self._flush_tid = threading.get_ident()
        if self._batch:
            self._drain()

    def _drain(self) -> None:
        batch, self._batch = self._batch, []
        self._send_records(batch)

    def _send_records(self, batch: list) -> None:
        if not self._running or self._sock is None:
            return
        blob = b"".join(batch)
        try:
            rc = self._netio.net_sendmmsg(self._sock.fileno(), blob,
                                          len(blob), len(batch))
        except Exception:  # noqa: BLE001 — treat like a malformed buffer
            rc = -1
        if rc < 0:
            # -1 = malformed record buffer (not an exception): the batch
            # must not be silently dropped — re-send per datagram
            for rec in batch:
                try:
                    ip = socket.inet_ntoa(rec[:4])
                    port = int.from_bytes(rec[4:6], "little")
                    self._sock.sendto(rec[10:], (ip, port))
                except OSError:
                    pass

    def get_connection_status(self, node: NodeNum) -> ConnectionStatus:
        return (ConnectionStatus.CONNECTED if node in self._cfg.endpoints
                else ConnectionStatus.UNKNOWN)

    # datagrams drained per recvmmsg call (mirrors netio.cpp kMaxBatch)
    RECV_BATCH = 64

    def _recv_loop(self) -> None:
        assert self._sock is not None
        if self._netio is not None:
            self._recv_loop_batched()
        else:
            # fallback path when _netio.so is unavailable (no g++ on the
            # host): one recvfrom syscall per datagram, as the reference
            self._recv_loop_scalar()

    def _recv_loop_scalar(self) -> None:
        while self._running:
            try:
                pkt, _ = self._sock.recvfrom(self._cfg.max_message_size + _HDR)
            except socket.timeout:
                continue
            except OSError:
                return
            msg = self._accept(pkt)
            if msg is not None and self._receiver is not None:
                self._receiver.on_new_message(*msg)

    def _recv_loop_batched(self) -> None:
        """recvmmsg plane: ONE syscall drains a whole burst, and the
        receiver gets it as one on_new_messages upcall (the admission
        pipeline enqueues the burst in one go). Readiness via
        selectors (epoll on Linux) — select(2) would silently fail for
        fds >= FD_SETSIZE on a process with many open files."""
        import selectors
        slot = self._cfg.max_message_size + _HDR
        buf = ctypes.create_string_buffer(slot * self.RECV_BATCH)
        lens = (ctypes.c_uint32 * self.RECV_BATCH)()
        sock0 = self._sock
        sel = selectors.DefaultSelector()
        try:
            sel.register(sock0, selectors.EVENT_READ)
        except (OSError, ValueError):
            sel.close()
            return
        try:
            self._recv_loop_batched_body(sel, buf, lens, slot)
        finally:
            sel.close()

    def _recv_loop_batched_body(self, sel, buf, lens, slot) -> None:
        while self._running:
            sock = self._sock
            if sock is None:
                return
            try:
                ready = sel.select(0.2)
            except (OSError, ValueError):
                if self._running:
                    from tpubft.utils.logging import get_logger
                    get_logger("udp").exception(
                        "receive poll failed; receive thread exiting")
                return
            if not ready:
                continue
            try:
                n = self._netio.net_recvmmsg(sock.fileno(), buf, slot,
                                             self.RECV_BATCH, lens)
            except Exception:  # noqa: BLE001 — treat like a socket error
                n = -1
            if n < 0:
                return
            burst = []
            for i in range(n):
                ln = min(lens[i], slot)
                msg = self._accept(buf[i * slot:i * slot + ln])
                if msg is not None:
                    burst.append(msg)
            if burst and self._receiver is not None:
                self._receiver.on_new_messages(burst)

    def _accept(self, pkt: bytes):
        """Shared per-datagram shape check: (sender, payload) or None."""
        if len(pkt) < _HDR:
            return None
        sender = int.from_bytes(pkt[:_HDR], "little")
        if sender not in self._cfg.endpoints or sender == self._cfg.self_id:
            return None  # unknown/spoofed sender id: drop
        return sender, pkt[_HDR:]
