"""Plain UDP transport — the reference's default.

Rebuild of communication/src/PlainUDPCommunication.cpp: connectionless
datagrams, one receive thread, sender identified by source endpoint lookup
in the static endpoint table. Messages above the datagram-safe size are
dropped with a metric bump, as in the reference.
"""
from __future__ import annotations

import socket
import threading
from typing import Optional

from tpubft.comm.interfaces import (CommConfig, ConnectionStatus,
                                    ICommunication, IReceiver, NodeNum)

# 4-byte LE sender-id prefix (same width as TCP's handshake id); source
# (ip, port) can be rewritten by NAT in odd topologies, so carry the id
# explicitly.
_HDR = 4


class PlainUdpCommunication(ICommunication):
    def __init__(self, config: CommConfig):
        self._cfg = config
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._receiver: Optional[IReceiver] = None
        self._running = False

    def start(self, receiver: IReceiver) -> None:
        if self._running:
            return
        self._receiver = receiver
        host, port = self._cfg.endpoints[self._cfg.self_id]
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                              self._cfg.buffer_capacity)
        self._sock.bind((host, port))
        self._sock.settimeout(0.2)
        self._running = True
        self._thread = threading.Thread(
            target=self._recv_loop, name=f"udp-recv-{self._cfg.self_id}",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def is_running(self) -> bool:
        return self._running

    @property
    def max_message_size(self) -> int:
        return min(self._cfg.max_message_size, 65507 - _HDR)

    def send(self, dest: NodeNum, data: bytes) -> None:
        if not self._running or self._sock is None:
            return
        if len(data) > self.max_message_size:
            return  # oversize datagram: dropped (reference logs + drops)
        addr = self._cfg.endpoints.get(dest)
        if addr is None:
            return
        pkt = self._cfg.self_id.to_bytes(_HDR, "little") + data
        try:
            self._sock.sendto(pkt, addr)
        except OSError:
            pass  # best-effort, like UDP itself

    def get_connection_status(self, node: NodeNum) -> ConnectionStatus:
        return (ConnectionStatus.CONNECTED if node in self._cfg.endpoints
                else ConnectionStatus.UNKNOWN)

    def _recv_loop(self) -> None:
        assert self._sock is not None
        while self._running:
            try:
                pkt, _ = self._sock.recvfrom(self._cfg.max_message_size + _HDR)
            except socket.timeout:
                continue
            except OSError:
                return
            if len(pkt) < _HDR:
                continue
            sender = int.from_bytes(pkt[:_HDR], "little")
            if sender not in self._cfg.endpoints or sender == self._cfg.self_id:
                continue  # unknown/spoofed sender id: drop
            if self._receiver is not None:
                self._receiver.on_new_message(sender, pkt[_HDR:])
