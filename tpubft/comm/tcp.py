"""Plain TCP transport with length-prefixed framing.

Rebuild of communication/src/PlainTcpCommunication.cpp: persistent
connections, 4-byte LE length prefix per message, an id handshake on
connect so the acceptor learns the peer's NodeNum, per-peer write queues
drained by a writer thread (the reference's ASIO write queue), lazy
reconnect. One connection per pair: the higher-id node dials, the lower-id
node accepts (the reference connection manager's convention), so
simultaneous first-sends cannot race into crossed half-open connections.
"""
from __future__ import annotations

import queue
import socket
import struct
import threading
import time
from typing import Dict, Optional

from tpubft.comm.interfaces import (CommConfig, ConnectionStatus,
                                    ICommunication, IReceiver, NodeNum)

_LEN = struct.Struct("<I")
_ID = struct.Struct("<I")
_SEND_DEADLINE_S = 3.0   # per-message connect+write budget before dropping
_HANDSHAKE_DEADLINE_S = 2.0


class _Peer:
    def __init__(self, comm: "PlainTcpCommunication", node: NodeNum):
        self.comm = comm
        self.node = node
        self.sock: Optional[socket.socket] = None
        self.q: "queue.Queue[Optional[bytes]]" = queue.Queue(maxsize=4096)
        self.lock = threading.Lock()
        self.writer = threading.Thread(target=self._write_loop, daemon=True,
                                       name=f"tcp-write-{self.node}")
        self.writer.start()
        self.reader: Optional[threading.Thread] = None

    def attach(self, sock: socket.socket) -> None:
        # Newest connection wins: a fresh inbound leg from an authenticated
        # peer replaces a possibly-dead stale socket (a partitioned peer
        # leaves no FIN behind; without this, redials would be refused
        # forever). Closing the old socket unblocks its reader, whose
        # detach(old) is a no-op because self.sock has moved on.
        sock.settimeout(None)  # blocking I/O; close() unblocks threads
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        except OSError:
            pass
        with self.lock:
            old, self.sock = self.sock, sock
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        self.reader = threading.Thread(target=self._read_loop, args=(sock,),
                                       daemon=True,
                                       name=f"tcp-read-{self.node}")
        self.reader.start()
        self.comm._notify(self.node, ConnectionStatus.CONNECTED)

    def detach(self, sock: Optional[socket.socket] = None) -> None:
        """Tear down `sock` (or whatever is current). A reader/writer that
        lost a replaced socket must not clobber the replacement."""
        with self.lock:
            if sock is not None and self.sock is not sock:
                return  # already replaced by a newer connection
            s, self.sock = self.sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass
            self.comm._notify(self.node, ConnectionStatus.DISCONNECTED)

    def enqueue(self, data: bytes) -> None:
        try:
            self.q.put_nowait(data)
        except queue.Full:
            pass  # backpressure: drop, like the reference's bounded queues

    def _write_loop(self) -> None:
        while self.comm.is_running():
            try:
                data = self.q.get(timeout=0.2)
            except queue.Empty:
                continue
            if data is None:
                return
            deadline = time.monotonic() + _SEND_DEADLINE_S
            while self.comm.is_running() and time.monotonic() < deadline:
                sock = self.sock
                if sock is None:
                    # the connector thread (or the peer's) re-establishes
                    time.sleep(0.02)
                    continue
                try:
                    sock.sendall(_LEN.pack(len(data)) + data)
                except OSError:
                    self.detach(sock)
                    continue
                break
            # deadline expired with no connection: message dropped

    def _read_loop(self, sock: socket.socket) -> None:
        while self.comm.is_running():
            if self.sock is not sock:
                return  # replaced: the new socket has its own reader
            hdr = _recv_exact(sock, _LEN.size)
            if hdr is None:
                self.detach(sock)
                return
            (n,) = _LEN.unpack(hdr)
            if n > self.comm._cfg.max_message_size:
                self.detach(sock)
                return
            body = _recv_exact(sock, n)
            if body is None:
                self.detach(sock)
                return
            self.comm._deliver(self.node, body)


def _recv_exact(sock: socket.socket, n: int,
                deadline: Optional[float] = None) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        if deadline is not None and time.monotonic() > deadline:
            return None
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            continue
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


class PlainTcpCommunication(ICommunication):
    def __init__(self, config: CommConfig):
        self._cfg = config
        self._receiver: Optional[IReceiver] = None
        self._running = False
        self._peers: Dict[NodeNum, _Peer] = {}
        self._lock = threading.Lock()
        self._server: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._connect_thread: Optional[threading.Thread] = None

    # ---- ICommunication ----

    def start(self, receiver: IReceiver) -> None:
        if self._running:
            return
        self._receiver = receiver
        self._running = True
        host, port = self._cfg.endpoints[self._cfg.self_id]
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(64)
        srv.settimeout(0.2)
        self._server = srv
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"tcp-accept-{self._cfg.self_id}")
        self._accept_thread.start()
        self._connect_thread = threading.Thread(
            target=self._connect_loop, daemon=True,
            name=f"tcp-connect-{self._cfg.self_id}")
        self._connect_thread.start()

    def stop(self) -> None:
        # Graceful: give writer threads a moment to drain queued sends
        # (the reference drains its ASIO write queues on shutdown).
        deadline = time.monotonic() + 1.0
        while time.monotonic() < deadline:
            with self._lock:
                pending = any(not p.q.empty() for p in self._peers.values())
            if not pending:
                break
            time.sleep(0.02)
        self._running = False
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None
        if self._connect_thread is not None:
            self._connect_thread.join(timeout=5)
            self._connect_thread = None
        if self._server is not None:
            self._server.close()
            self._server = None
        with self._lock:
            peers, self._peers = list(self._peers.values()), {}
        for p in peers:
            p.detach()

    def is_running(self) -> bool:
        return self._running

    def send(self, dest: NodeNum, data: bytes) -> None:
        if not self._running or dest not in self._cfg.endpoints:
            return
        if len(data) > self._cfg.max_message_size:
            return  # oversize: drop here instead of poisoning the connection
        self._peer(dest).enqueue(data)

    def get_connection_status(self, node: NodeNum) -> ConnectionStatus:
        with self._lock:
            p = self._peers.get(node)
        if p is None:
            return ConnectionStatus.UNKNOWN
        return (ConnectionStatus.CONNECTED if p.sock is not None
                else ConnectionStatus.DISCONNECTED)

    @property
    def max_message_size(self) -> int:
        return self._cfg.max_message_size

    # ---- internals ----

    def _dials(self, node: NodeNum) -> bool:
        """This side initiates iff it has the higher id."""
        return self._cfg.self_id > node

    def _connect_loop(self) -> None:
        """Proactively establish + maintain connections to all lower-id
        peers (the reference maintains the full mesh from startup; the
        lower-id side is the server)."""
        while self._running:
            for node in self._cfg.endpoints:
                if not self._running:
                    return
                if self._dials(node) and self._peer(node).sock is None:
                    self._dial(node)
            time.sleep(0.25)

    def _peer(self, node: NodeNum) -> _Peer:
        with self._lock:
            p = self._peers.get(node)
            if p is None:
                p = self._peers[node] = _Peer(self, node)
        return p

    def _dial(self, node: NodeNum) -> None:
        addr = self._cfg.endpoints.get(node)
        if addr is None:
            return
        try:
            sock = socket.create_connection(addr, timeout=1.0)
            sock.sendall(_ID.pack(self._cfg.self_id))
        except OSError:
            return
        self._peer(node).attach(sock)

    def _accept_loop(self) -> None:
        assert self._server is not None
        while self._running:
            try:
                sock, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            sock.settimeout(0.2)
            hdr = _recv_exact(sock, _ID.size,
                              time.monotonic() + _HANDSHAKE_DEADLINE_S)
            if hdr is None:
                sock.close()
                continue
            (peer_id,) = _ID.unpack(hdr)
            if peer_id not in self._cfg.endpoints or peer_id == self._cfg.self_id:
                sock.close()  # unknown/spoofed id: refuse
                continue
            self._peer(peer_id).attach(sock)

    def _deliver(self, sender: NodeNum, data: bytes) -> None:
        if self._running and self._receiver is not None:
            self._receiver.on_new_message(sender, data)

    def _notify(self, node: NodeNum, status: ConnectionStatus) -> None:
        if self._receiver is not None:
            self._receiver.on_connection_status_changed(node, status)
