"""Plain TCP transport with length-prefixed framing.

Rebuild of communication/src/PlainTcpCommunication.cpp: persistent
connections, 4-byte LE length prefix per message, an id handshake on
connect so the acceptor learns the peer's NodeNum, per-peer write queues
drained by a writer thread (the reference's ASIO write queue), lazy
reconnect. One connection per pair: the higher-id node dials, the lower-id
node accepts (the reference connection manager's convention), so
simultaneous first-sends cannot race into crossed half-open connections.
"""
from __future__ import annotations

import queue
import socket
import struct
import threading
import time
from typing import Dict, Optional

from tpubft.comm.interfaces import (CommConfig, ConnectionStatus,
                                    ICommunication, IReceiver, NodeNum)

_LEN = struct.Struct("<I")
_ID = struct.Struct("<I")
_SEND_DEADLINE_S = 3.0   # per-message connect+write budget before dropping
_HANDSHAKE_DEADLINE_S = 2.0


class _Peer:
    """One peer's connection state.

    Plain TCP runs ONE bidirectional socket per pair (wsock is rsock);
    the TLS transport runs DIRECTIONAL legs — the socket we dialed is
    write-only, the socket the peer dialed into us is read-only — because
    OpenSSL forbids concurrent SSL_read/SSL_write on one SSL object from
    two threads (the reference's ASIO model serializes on a strand
    instead; directional legs are the thread-per-socket equivalent)."""

    def __init__(self, comm: "PlainTcpCommunication", node: NodeNum):
        self.comm = comm
        self.node = node
        self.wsock: Optional[socket.socket] = None   # we write here
        self.rsock: Optional[socket.socket] = None   # we read here
        self.q: "queue.Queue[Optional[bytes]]" = queue.Queue(maxsize=4096)
        self.lock = threading.Lock()
        self.writer = threading.Thread(target=self._write_loop, daemon=True,
                                       name=f"tcp-write-{self.node}")
        self.writer.start()

    @staticmethod
    def _prep(sock: socket.socket) -> None:
        sock.settimeout(None)  # blocking I/O; close() unblocks threads
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        except OSError:
            pass

    def attach(self, sock: socket.socket) -> None:
        """Bidirectional attach (plain TCP). Newest connection wins: a
        fresh inbound leg from an authenticated peer replaces a possibly-
        dead stale socket (a partitioned peer leaves no FIN behind;
        without this, redials would be refused forever)."""
        self._prep(sock)
        with self.lock:
            old_w, self.wsock = self.wsock, sock
            old_r, self.rsock = self.rsock, sock
        for old in {old_w, old_r} - {None}:
            _close(old)
        self._spawn_reader(sock)
        self.comm._notify(self.node, ConnectionStatus.CONNECTED)

    def attach_write(self, sock: socket.socket) -> None:
        """Directional write leg (the connection WE dialed)."""
        self._prep(sock)
        with self.lock:
            old, self.wsock = self.wsock, sock
        if old is not None:
            _close(old)
        self.comm._notify(self.node, ConnectionStatus.CONNECTED)

    def attach_read(self, sock: socket.socket) -> None:
        """Directional read leg (the connection the peer dialed). No
        status notification: connection status tracks WRITEABILITY (can
        we reach the peer), carried by the write leg alone."""
        self._prep(sock)
        with self.lock:
            old, self.rsock = self.rsock, sock
        if old is not None:
            _close(old)
        self._spawn_reader(sock)

    def _spawn_reader(self, sock: socket.socket) -> None:
        threading.Thread(target=self._read_loop, args=(sock,), daemon=True,
                         name=f"tcp-read-{self.node}").start()

    def detach(self, sock: Optional[socket.socket] = None) -> None:
        """Tear down `sock` (or everything). A reader/writer that lost a
        replaced socket must not clobber the replacement. DISCONNECTED is
        notified only when the WRITE leg is lost, matching
        get_connection_status (a dead read leg alone does not make the
        peer unreachable)."""
        closing = []
        lost_write = False
        with self.lock:
            if sock is None:
                closing = [s for s in (self.wsock, self.rsock)
                           if s is not None]
                lost_write = self.wsock is not None
                self.wsock = self.rsock = None
            else:
                if self.wsock is sock:
                    self.wsock = None
                    lost_write = True
                    closing.append(sock)
                if self.rsock is sock:
                    self.rsock = None
                    if sock not in closing:
                        closing.append(sock)
        if not closing:
            return  # already replaced by a newer connection
        for s in closing:
            _close(s)
        if lost_write:
            self.comm._notify(self.node, ConnectionStatus.DISCONNECTED)

    def enqueue(self, data: bytes) -> None:
        try:
            self.q.put_nowait(data)
        except queue.Full:
            pass  # backpressure: drop, like the reference's bounded queues

    def _write_loop(self) -> None:
        while self.comm.is_running():
            try:
                data = self.q.get(timeout=0.2)
            except queue.Empty:
                continue
            if data is None:
                return
            deadline = time.monotonic() + _SEND_DEADLINE_S
            while self.comm.is_running() and time.monotonic() < deadline:
                sock = self.wsock
                if sock is None:
                    # the connector thread (or the peer's) re-establishes
                    time.sleep(0.02)
                    continue
                try:
                    sock.sendall(_LEN.pack(len(data)) + data)
                except OSError:
                    self.detach(sock)
                    continue
                break
            # deadline expired with no connection: message dropped

    def _read_loop(self, sock: socket.socket) -> None:
        while self.comm.is_running():
            if self.rsock is not sock:
                return  # replaced: the new socket has its own reader
            hdr = _recv_exact(sock, _LEN.size)
            if hdr is None:
                self.detach(sock)
                return
            (n,) = _LEN.unpack(hdr)
            if n > self.comm._cfg.max_message_size:
                self.detach(sock)
                return
            body = _recv_exact(sock, n)
            if body is None:
                self.detach(sock)
                return
            self.comm._deliver(self.node, body)


def _close(sock: socket.socket) -> None:
    try:
        sock.close()
    except OSError:
        pass


def _recv_exact(sock: socket.socket, n: int,
                deadline: Optional[float] = None) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        if deadline is not None and time.monotonic() > deadline:
            return None
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            continue
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


class PlainTcpCommunication(ICommunication):
    def __init__(self, config: CommConfig):
        self._cfg = config
        self._receiver: Optional[IReceiver] = None
        self._running = False
        self._peers: Dict[NodeNum, _Peer] = {}
        self._lock = threading.Lock()
        self._server: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._connect_thread: Optional[threading.Thread] = None

    # ---- ICommunication ----

    def start(self, receiver: IReceiver) -> None:
        if self._running:
            return
        self._receiver = receiver
        self._running = True
        host, port = self._cfg.endpoints[self._cfg.self_id]
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(64)
        srv.settimeout(0.2)
        self._server = srv
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"tcp-accept-{self._cfg.self_id}")
        self._accept_thread.start()
        self._connect_thread = threading.Thread(
            target=self._connect_loop, daemon=True,
            name=f"tcp-connect-{self._cfg.self_id}")
        self._connect_thread.start()

    def stop(self) -> None:
        # Graceful: give writer threads a moment to drain queued sends
        # (the reference drains its ASIO write queues on shutdown).
        deadline = time.monotonic() + 1.0
        while time.monotonic() < deadline:
            with self._lock:
                pending = any(not p.q.empty() for p in self._peers.values())
            if not pending:
                break
            time.sleep(0.02)
        self._running = False
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None
        if self._connect_thread is not None:
            self._connect_thread.join(timeout=5)
            self._connect_thread = None
        if self._server is not None:
            self._server.close()
            self._server = None
        with self._lock:
            peers, self._peers = list(self._peers.values()), {}
        for p in peers:
            p.detach()

    def is_running(self) -> bool:
        return self._running

    def send(self, dest: NodeNum, data: bytes) -> None:
        if not self._running or dest not in self._cfg.endpoints:
            return
        if len(data) > self._cfg.max_message_size:
            return  # oversize: drop here instead of poisoning the connection
        self._peer(dest).enqueue(data)

    def get_connection_status(self, node: NodeNum) -> ConnectionStatus:
        with self._lock:
            p = self._peers.get(node)
        if p is None:
            return ConnectionStatus.UNKNOWN
        return (ConnectionStatus.CONNECTED if p.wsock is not None
                else ConnectionStatus.DISCONNECTED)

    @property
    def max_message_size(self) -> int:
        return self._cfg.max_message_size

    # ---- internals ----

    # True for transports whose connections are one-way (TLS): every node
    # dials its OWN write leg to every peer; inbound legs are read-only
    directional = False

    def _dials(self, node: NodeNum) -> bool:
        """Who initiates: everyone (directional) or the higher id (one
        shared bidirectional connection per pair)."""
        if self.directional:
            return node != self._cfg.self_id
        return self._cfg.self_id > node

    def _connect_loop(self) -> None:
        """Proactively establish + maintain this node's outbound legs
        (the reference maintains the full mesh from startup). Dials run
        on per-peer threads: one byzantine acceptor dribbling handshake
        bytes must not delay redials to every other peer."""
        dialing: set = set()
        dial_lock = threading.Lock()

        def dial_one(node: NodeNum) -> None:
            try:
                self._dial(node)
            finally:
                with dial_lock:
                    dialing.discard(node)

        while self._running:
            for node in self._cfg.endpoints:
                if not self._running:
                    return
                if self._dials(node) and self._peer(node).wsock is None:
                    with dial_lock:
                        if node in dialing:
                            continue
                        dialing.add(node)
                    threading.Thread(target=dial_one, args=(node,),
                                     daemon=True,
                                     name=f"tcp-dial-{node}").start()
            time.sleep(0.25)

    def _peer(self, node: NodeNum) -> _Peer:
        with self._lock:
            p = self._peers.get(node)
            if p is None:
                p = self._peers[node] = _Peer(self, node)
        return p

    # ---- security hooks (identity here; TlsTcpCommunication overrides) ----

    def _wrap_outbound(self, sock: socket.socket,
                       node: NodeNum) -> socket.socket:
        """Post-connect wrap of a dialed socket (TLS handshake + server
        authentication in the TLS transport). Raise OSError to refuse."""
        return sock

    def _wrap_inbound(self, sock: socket.socket) -> socket.socket:
        """Post-accept wrap (TLS handshake). Raise OSError to refuse."""
        return sock

    def _authenticate_inbound(self, sock: socket.socket,
                              peer_id: NodeNum) -> bool:
        """Bind the transport-level identity to the claimed node id (the
        TLS transport checks the certificate pin for `peer_id`)."""
        return True

    def _dial(self, node: NodeNum) -> None:
        addr = self._cfg.endpoints.get(node)
        if addr is None:
            return
        try:
            sock = socket.create_connection(addr, timeout=1.0)
        except OSError:
            return
        # absolute bound on the outbound handshake: a byzantine acceptor
        # dribbling handshake bytes must not stall the connect loop
        raw = sock
        killer = threading.Timer(2 * _HANDSHAKE_DEADLINE_S,
                                 lambda: _close(raw))
        killer.daemon = True
        killer.start()
        try:
            sock = self._wrap_outbound(sock, node)
            sock.sendall(_ID.pack(self._cfg.self_id))
        except OSError:
            _close(sock)
            return
        finally:
            killer.cancel()
        if self.directional:
            self._peer(node).attach_write(sock)
        else:
            self._peer(node).attach(sock)

    # cap on concurrent inbound handshakes: beyond this, new connections
    # are refused outright (bounds the handshake-thread count under a
    # connection flood; legitimate peers redial)
    _MAX_INFLIGHT_HANDSHAKES = 64

    def _accept_loop(self) -> None:
        assert self._server is not None
        inflight = threading.Semaphore(self._MAX_INFLIGHT_HANDSHAKES)
        while self._running:
            try:
                sock, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            if not inflight.acquire(blocking=False):
                sock.close()
                continue
            # per-connection handshake thread with an ABSOLUTE deadline
            # (a timer closes the socket, aborting a dribbled handshake):
            # one slow/malicious client must not block the accept loop
            threading.Thread(target=self._inbound_handshake,
                             args=(sock, inflight), daemon=True,
                             name="tcp-handshake").start()

    def _inbound_handshake(self, sock: socket.socket, inflight) -> None:
        # pin the RAW socket for the killer: closing the SSL wrapper from
        # the timer thread would race the handshake thread's SSL_read on
        # the same SSL object (closing the raw fd is thread-safe abort)
        raw = sock
        killer = threading.Timer(2 * _HANDSHAKE_DEADLINE_S,
                                 lambda: _close(raw))
        killer.daemon = True
        killer.start()
        try:
            sock.settimeout(_HANDSHAKE_DEADLINE_S)
            sock = self._wrap_inbound(sock)
            sock.settimeout(0.2)
            hdr = _recv_exact(sock, _ID.size,
                              time.monotonic() + _HANDSHAKE_DEADLINE_S)
            if hdr is None:
                _close(sock)
                return
            (peer_id,) = _ID.unpack(hdr)
            if peer_id not in self._cfg.endpoints \
                    or peer_id == self._cfg.self_id:
                _close(sock)  # unknown/spoofed id: refuse
                return
            if not self._authenticate_inbound(sock, peer_id):
                _close(sock)  # transport identity != claimed id: refuse
                return
            killer.cancel()
            if not self._running:
                _close(sock)
                return
            if self.directional:
                self._peer(peer_id).attach_read(sock)
            else:
                self._peer(peer_id).attach(sock)
        except OSError:
            _close(sock)
        finally:
            killer.cancel()
            inflight.release()

    def _deliver(self, sender: NodeNum, data: bytes) -> None:
        if self._running and self._receiver is not None:
            self._receiver.on_new_message(sender, data)

    def _notify(self, node: NodeNum, status: ConnectionStatus) -> None:
        if self._receiver is not None:
            self._receiver.on_connection_status_changed(node, status)
