"""Transport factory: build an ICommunication from a config.

Rebuild of the reference's CommFactory
(/root/reference/communication/src/CommFactory.cpp — `create` dispatches
on the config struct type: PlainUdpConfig / PlainTcpConfig /
TlsTcpConfig, CommDefs.hpp). Same pattern: a TlsConfig selects the TLS
transport by type; the string form serves flag-driven app wiring
(reference CONCORD_BFT_CMAKE_TRANSPORT selects at build time — here it's
a runtime choice)."""
from __future__ import annotations

from tpubft.comm.interfaces import CommConfig, ICommunication
from tpubft.comm.tcp import PlainTcpCommunication
from tpubft.comm.udp import PlainUdpCommunication


def create_communication(config: CommConfig,
                         transport: str = "") -> ICommunication:
    """Type-dispatch (TlsConfig => TLS) with an optional string override:
    "udp" | "tcp" | "tls"."""
    from tpubft.comm.tls import TlsConfig, TlsTcpCommunication
    if transport == "" and isinstance(config, TlsConfig):
        transport = "tls"
    transport = transport or "udp"
    if transport == "udp":
        return PlainUdpCommunication(config)
    if transport == "tcp":
        return PlainTcpCommunication(config)
    if transport == "tls":
        if not isinstance(config, TlsConfig):
            raise TypeError("tls transport needs a TlsConfig "
                            "(certs_dir with node keys/certs)")
        return TlsTcpCommunication(config)
    if transport == "tls-mux":
        # reference TlsMultiplexCommunication: endpoint-numbered frames
        # over the TLS transport so many principals share connections
        if not isinstance(config, TlsConfig):
            raise TypeError("tls-mux transport needs a TlsConfig")
        if config.mux_client_floor is None:
            raise ValueError("tls-mux needs TlsConfig.mux_client_floor "
                             "(first client-space principal id)")
        from tpubft.comm.multiplex import MultiplexTransport
        floor = config.mux_client_floor
        return MultiplexTransport(TlsTcpCommunication(config),
                                  self_id=config.self_id,
                                  is_client=lambda i: i >= floor)
    raise ValueError(f"unknown transport {transport!r}")
