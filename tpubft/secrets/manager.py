"""Secrets managers. File format (versioned, self-describing):

  b"TPUBFTSEC1" | salt(16) | iv(16) | ciphertext | hmac-sha256(32)

where the hmac covers salt|iv|ciphertext under a key derived separately
from the same password (encrypt-then-MAC).
"""
from __future__ import annotations

import ctypes
import hashlib
import hmac as hmac_mod
import os
from typing import Optional

from tpubft.native.build import load

_MAGIC = b"TPUBFTSEC1"
_PBKDF2_ITERS = 100_000


class SecretsError(Exception):
    pass


def _lib():
    lib = load("aescbc")
    if getattr(lib, "_aes_typed", False):
        return lib
    for fn in (lib.aes256_cbc_encrypt, lib.aes256_cbc_decrypt):
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
                       ctypes.c_char_p, ctypes.c_uint32]
    lib._aes_typed = True
    return lib


def _derive_keys(password: bytes, salt: bytes) -> tuple:
    material = hashlib.pbkdf2_hmac("sha256", password, salt, _PBKDF2_ITERS,
                                   dklen=64)
    return material[:32], material[32:]  # (aes key, hmac key)


def _pad(data: bytes) -> bytes:
    n = 16 - len(data) % 16
    return data + bytes([n]) * n


def _unpad(data: bytes) -> bytes:
    if not data or data[-1] < 1 or data[-1] > 16 \
            or data[-data[-1]:] != bytes([data[-1]]) * data[-1]:
        raise SecretsError("bad padding")
    return data[:-data[-1]]


class SecretsManagerEnc:
    """Encrypted secrets at rest (reference secrets_manager_enc.h)."""

    def __init__(self, password: bytes) -> None:
        if not password:
            raise SecretsError("empty password")
        self._password = password

    def encrypt(self, plaintext: bytes) -> bytes:
        salt = os.urandom(16)
        iv = os.urandom(16)
        aes_key, mac_key = _derive_keys(self._password, salt)
        padded = _pad(plaintext)
        out = ctypes.create_string_buffer(len(padded))
        rc = _lib().aes256_cbc_encrypt(aes_key, iv, padded, out,
                                       len(padded))
        if rc != 0:
            raise SecretsError("encryption failed")
        body = salt + iv + out.raw
        tag = hmac_mod.new(mac_key, body, hashlib.sha256).digest()
        return _MAGIC + body + tag

    def decrypt(self, blob: bytes) -> bytes:
        if not blob.startswith(_MAGIC) or len(blob) < len(_MAGIC) + 64:
            raise SecretsError("not a tpubft secret blob")
        body, tag = blob[len(_MAGIC):-32], blob[-32:]
        salt, iv, ct = body[:16], body[16:32], body[32:]
        aes_key, mac_key = _derive_keys(self._password, salt)
        expect = hmac_mod.new(mac_key, body, hashlib.sha256).digest()
        if not hmac_mod.compare_digest(tag, expect):
            raise SecretsError("integrity check failed (wrong password "
                               "or tampered file)")
        if len(ct) % 16:
            raise SecretsError("truncated ciphertext")
        out = ctypes.create_string_buffer(len(ct))
        rc = _lib().aes256_cbc_decrypt(aes_key, iv, ct, out, len(ct))
        if rc != 0:
            raise SecretsError("decryption failed")
        return _unpad(out.raw)

    # file helpers (reference encryptFile/decryptFile)
    def encrypt_file(self, path: str, plaintext: bytes) -> None:
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(self.encrypt(plaintext))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)

    def decrypt_file(self, path: str) -> bytes:
        with open(path, "rb") as fh:
            return self.decrypt(fh.read())


class SecretsManagerPlain:
    """Plaintext variant for tests (reference secrets_manager_plain.h)."""

    def encrypt(self, plaintext: bytes) -> bytes:
        return plaintext

    def decrypt(self, blob: bytes) -> bytes:
        return blob

    def encrypt_file(self, path: str, plaintext: bytes) -> None:
        with open(path, "wb") as fh:
            fh.write(plaintext)

    def decrypt_file(self, path: str) -> bytes:
        with open(path, "rb") as fh:
            return fh.read()
