"""Secrets manager — private key material encrypted at rest.

Rebuild of /root/reference/secretsmanager/ (secrets_manager_enc.h,
secrets_manager_plain.h, aes.cpp, base64.cpp): AES-256-CBC (native C++
engine, tpubft/native/aescbc.cpp) with PBKDF2-HMAC-SHA256 key derivation,
PKCS#7 padding, and encrypt-then-MAC integrity; plus the plaintext
variant for tests.
"""
from tpubft.secrets.manager import (SecretsManagerEnc, SecretsManagerPlain,
                                    SecretsError)

__all__ = ["SecretsManagerEnc", "SecretsManagerPlain", "SecretsError"]
