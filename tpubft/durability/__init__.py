"""Group-commit durability plane — fsync off the execution lane.

The execution lane seals each coalesced run (its ledger WriteBatch +
reply pages + completion record) into a `DurabilityPipeline` and moves
straight on to the next run; a dedicated io thread drains the queue,
applies the sealed batches as ONE concatenated group write, pays ONE
fsync per group, and publishes a monotone durability watermark.
Replies, `last_executed`, and the at-most-once reply cache all advance
off that watermark — never off a per-run fsync. See
docs/OPERATIONS.md "Durability pipeline".
"""
from tpubft.durability.pipeline import (DurabilityPipeline, PendingStore,
                                        SealedRun)

__all__ = ["DurabilityPipeline", "PendingStore", "SealedRun"]
