"""DurabilityPipeline — group-commit fsync off the execution lane.

Every `bench_e2e` round since the execution lane landed records the
shared disk's nonstationary fsync (2-21ms probed) as the dominant
run-to-run variance source, and each coalesced run still paid one full
durable apply on the write path. This module decouples durability from
execution the way group-commit databases do:

  * the execution lane finishes a run's staging, hands the sealed
    WriteBatch (ledger + folded reply pages) plus the run's completion
    record to `seal()`, and moves straight on to the next run — it
    never touches the disk again;
  * sealed-but-not-yet-applied writes stay readable through the
    `PendingStore` overlay the blockchain's read path consults
    (point gets AND merged range scans), so execution, proofs, digests
    and read-only queries observe the logical head, not the disk's;
  * a dedicated io thread drains the seal queue and group-commits
    ACROSS runs: up to `group_max` runs (or whatever sealed inside
    `window_us` of the group's first run) apply as ONE concatenated
    group write (`IDBClient.write_group` — one engine record on
    NativeDB) followed by ONE `sync()` per distinct DB;
  * after the group's fsync the pipeline publishes a monotone
    **durability watermark** and only then makes each run visible to
    the dispatcher (reply send, `last_executed` advance) and the
    at-most-once reply cache — a reply can never precede its group's
    fsync.

The consensus-metadata family carve-out (`CONSENSUS_META_FAMILIES`,
`sync_families` in storage/native.py) is untouched: those batches stay
synchronous on the dispatcher — losing a vote is a safety hazard,
losing a tail of re-derivable blocks is not. Checkpoint-stable, view
change, ST adoption and wedge paths drain the pipeline first
(`Replica._drain_exec_lane` extends the lane's own barrier), and the
`dur.group_fsync` crashpoint sits between the group's apply and its
fsync — the widest crash window the exactly-once replay drills must
cover (group maybe-applied, never acknowledged).

`group_max=1` degenerates to the per-run durable apply (one batch, one
fsync per run) — the A/B control `bench_e2e --durability-off` pairs
against.
"""
from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tpubft.storage.interfaces import WriteBatch
from tpubft.testing.crashpoints import crashpoint
from tpubft.utils import flight
from tpubft.utils.logging import get_logger
from tpubft.utils.metrics import Component
from tpubft.utils.racecheck import get_watchdog, make_lock

log = get_logger("durability")


class PendingStore:
    """Sealed-but-not-yet-applied write overlay.

    Physical key -> (run_no, value-or-None) for every op of every
    sealed batch the io thread has not applied yet. The blockchain's
    permanently-installed `_PendingView` consults it on every point get
    and merges it into every range scan, so readers on ANY thread see
    sealed state exactly as if the batch had been applied — the only
    thing deferred is the disk.

    Mutations: `stage` (execution lane, inside the accumulation
    bracket) and `mark_applied` (io thread, or the lane's barrier
    paths) — both under the store lock. `lookup`/`snapshot_range` are
    safe from any thread.
    """

    def __init__(self, name: str = "dur") -> None:
        self._mu = make_lock(f"{name}.pending")
        self._cond = threading.Condition(self._mu)
        self._d: Dict[bytes, Tuple[int, Optional[bytes]]] = {}
        self._staged_no = 0

    # ---- staging (execution lane) ----
    def stage(self, overlay: Dict[bytes, Optional[bytes]]) -> int:
        """Adopt one sealed run's overlay (physical key -> value-or-
        None); returns the run's pending ticket number. Later runs
        overwrite earlier runs' entries for the same key — last writer
        wins, exactly like the applies they stand in for."""
        with self._cond:
            self._staged_no += 1
            no = self._staged_no
            for k, v in overlay.items():
                self._d[k] = (no, v)
            return no

    # ---- application (io thread / barrier paths) ----
    def mark_applied(self, run_no: int, batch: WriteBatch) -> None:
        """The batch for ticket `run_no` reached the base DB: drop its
        keys from the overlay UNLESS a later run overwrote them (the
        later value must stay visible until ITS apply lands)."""
        with self._cond:
            for k, _v in batch.ops:
                ent = self._d.get(k)
                if ent is not None and ent[0] <= run_no:
                    del self._d[k]
            self._cond.notify_all()

    def wait_empty(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._d:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.2))
        return True

    @property
    def empty(self) -> bool:
        return not self._d

    @property
    def depth(self) -> int:
        return len(self._d)

    # ---- read side (any thread) ----
    def lookup(self, physical_key: bytes
               ) -> Optional[Tuple[int, Optional[bytes]]]:
        """(run_no, value-or-None) or None when the key is not pending.
        Lock-free: a dict point read is GIL-atomic and the value tuple
        is immutable — a racy miss just falls through to the base,
        which is where the key is headed anyway."""
        return self._d.get(physical_key)

    def snapshot_range(self, lo: bytes, hi: Optional[bytes]
                       ) -> List[Tuple[bytes, Optional[bytes]]]:
        """Sorted (physical_key, value-or-None) snapshot of the pending
        keys in [lo, hi) — merged into `_PendingView.range_iter` so
        range readers (versioned reads, pages digests, ST summaries)
        see sealed state too. The overlay is bounded by the seal
        queue, so the scan is small."""
        with self._cond:
            items = [(k, v[1]) for k, v in self._d.items()
                     if k >= lo and (hi is None or k < hi)]
        items.sort()
        return items


@dataclass
class SealedRun:
    """One durably-pending execution run, exactly as the lane sealed it.

    `batch`/`run_no` carry the deferred ledger(+folded pages) write
    (None when the handler applied irreversibly during execution — the
    run is then a sync-only ticket). `sync_dbs` are the stores whose
    dirty buffers the group fsync must land; `executed_now` is the
    at-most-once visibility the dispatcher's reply cache gains only
    after the fsync."""
    run: object                              # execution.CompletedRun
    executed_now: List[Tuple[int, int, object]]
    batch: Optional[WriteBatch] = None
    run_no: Optional[int] = None
    db: Optional[object] = None              # target of `batch`
    sync_dbs: Tuple = ()
    sealed_mono: float = field(default_factory=time.monotonic)


class DurabilityPipeline:
    """The io thread + the lane->dispatcher durability handoff.

    Lane-side API: seal / watermark / drain / flush / hold / release.
    The io thread owns every disk touch: group apply (write_group),
    group fsync (sync), watermark publication, and the post-durability
    completion (reply-cache visibility + the lane's completed queue +
    the dispatcher wakeup)."""

    RETRY_DELAY_S = 0.5                      # backoff after a failed group

    def __init__(self, replica, group_max: int = 8,
                 window_us: int = 1000) -> None:
        self._r = replica
        self._mu = make_lock("dur.pipeline")
        self._cond = threading.Condition(self._mu)
        self._queue: List[SealedRun] = []
        self._queue_max = max(8, int(group_max) * 4)
        self._group_max = max(1, int(group_max))
        self._window_us = max(0, int(window_us))
        self._busy = False                   # a group is mid-apply/fsync
        self._held = False                   # test hook: freeze the io lane
        self._flush = False                  # cut the window now
        self._retry_at = 0.0
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._name = f"dur-{replica.id}"
        self.pending = PendingStore(self._name)
        # monotone durability watermark: highest seq whose group fsync
        # landed. Reads are lock-free (int attribute); the io thread is
        # the only writer.
        self.watermark = int(getattr(replica, "last_executed", 0))
        self._sealed_head = self.watermark   # highest seq sealed so far

        agg = getattr(replica, "aggregator", None)
        self.metrics = Component("durability", agg)
        self.m_groups = self.metrics.register_counter("dur_groups")
        self.m_runs = self.metrics.register_counter("dur_runs")
        self.m_fsyncs = self.metrics.register_counter("dur_fsyncs")
        self.m_fsync_us = self.metrics.register_counter("dur_fsync_us")
        self.m_wm = self.metrics.register_gauge("dur_wm")
        self.m_wm_lag = self.metrics.register_gauge("dur_wm_lag")
        self.m_retries = self.metrics.register_counter("dur_retries")
        # replies signed through the group-boundary batched sign
        # (optimistic replies: execution defers per-reply signatures to
        # one sign_batch per committed group)
        self.m_signed = self.metrics.register_counter(
            "dur_replies_signed")
        from tpubft.diagnostics import get_registrar
        diag = get_registrar()
        self._h_group_len = diag.histogram(
            f"replica{replica.id}.dur_group_len", unit="runs")
        self._h_fsync_ms = diag.histogram(
            f"replica{replica.id}.dur_fsync_ms")

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self._name)
        self._thread.start()

    def stop(self) -> None:
        """Clean stop flushes: the io thread drains whatever is sealed
        (apply + fsync + complete) before exiting — a clean shutdown
        should leave the disk at the logical head. A wedged disk bounds
        the wait at the join timeout; whatever did not land is exactly
        the crash case recovery already replays."""
        with self._cond:
            self._running = False
            self._flush = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        get_watchdog().unregister(self._name)

    # ------------------------------------------------------------------
    # autotuner actuators
    # ------------------------------------------------------------------
    def set_group_max(self, n: int) -> None:
        with self._cond:
            self._group_max = max(1, int(n))
            self._queue_max = max(8, self._group_max * 4)
            self._cond.notify_all()

    def set_window_us(self, us: int) -> None:
        with self._cond:
            self._window_us = max(0, int(us))
            self._cond.notify_all()

    @property
    def group_max(self) -> int:
        return self._group_max

    @property
    def window_us(self) -> int:
        return self._window_us

    # ------------------------------------------------------------------
    # lane-side API
    # ------------------------------------------------------------------
    def seal(self, sealed: SealedRun) -> None:
        """Hand one finished run to the io thread (execution lane). A
        full queue blocks the lane — natural backpressure: execution
        must not outrun durability without bound. Stop-racing seals
        enqueue anyway (crash-equivalent: they simply never fsync)."""
        with self._cond:
            while self._running and len(self._queue) >= self._queue_max:
                self._cond.wait(0.2)
            self._queue.append(sealed)
            if sealed.run.last > self._sealed_head:
                self._sealed_head = sealed.run.last
            self._cond.notify_all()
        self.m_wm_lag.set(max(0, self._sealed_head - self.watermark))

    def flush(self) -> None:
        """Cut the group window now — the next group forms from
        whatever is sealed, without waiting out `window_us`."""
        with self._cond:
            self._flush = True
            self._cond.notify_all()

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until everything sealed so far is durable (queue empty,
        no group in flight) — the barrier checkpoint-stable, view
        change, ST adoption and wedge paths take after draining the
        lane. Returns False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._flush = True
            self._cond.notify_all()
            while self._queue or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.2))
            # drained: clear the flush request — a stale flag would
            # make the NEXT sealed run commit as an unamortized group
            # of one, silently discarding the window once per barrier
            self._flush = False
        return True

    def idle(self) -> bool:
        with self._cond:
            return not self._queue and not self._busy

    @property
    def lag(self) -> int:
        """Sealed-but-not-yet-durable runs (the health probe's busy
        signal and the `dur_wm_lag` sensor's queue form)."""
        with self._cond:
            return len(self._queue) + (1 if self._busy else 0)

    # test hooks: freeze the io thread BEFORE it forms the next group,
    # so reply-gating tests can hold runs executed-but-not-durable
    def hold(self) -> None:
        with self._cond:
            self._held = True

    def release(self) -> None:
        with self._cond:
            self._held = False
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # io thread
    # ------------------------------------------------------------------
    def _take_group_locked(self) -> List[SealedRun]:
        return [self._queue.pop(0)
                for _ in range(min(self._group_max, len(self._queue)))]

    def _lane_quiet(self) -> bool:
        """True when no further seal can be in flight (the lane is
        idle): holding a partial group open would only delay its
        replies — cut the window early. A missing/opaque lane reads as
        busy, preserving the window semantics."""
        lane = getattr(self._r, "exec_lane", None)
        idle = getattr(lane, "idle", None)
        if not callable(idle):
            return False
        try:
            return bool(idle())
        except Exception:  # noqa: BLE001 — window semantics win
            return False

    def _loop(self) -> None:
        watchdog = get_watchdog()
        flight.set_thread_rid(self._r.id)
        health = getattr(self._r, "health", None)
        while True:
            watchdog.beat(self._name)
            group: List[SealedRun] = []
            with self._cond:
                while True:
                    now = time.monotonic()
                    if self._queue and not self._held \
                            and now >= self._retry_at:
                        deadline = (self._queue[0].sealed_mono
                                    + self._window_us / 1e6)
                        if (len(self._queue) >= self._group_max
                                or now >= deadline or self._flush
                                or not self._running
                                or self._lane_quiet()):
                            self._flush = False
                            group = self._take_group_locked()
                            self._busy = True
                            break
                        wait = min(deadline - now, 0.2)
                    elif not self._running and (not self._queue
                                                or self._held):
                        # stop: a held pipeline exits without touching
                        # the disk (the crash analog the drills park)
                        return
                    else:
                        wait = 0.2
                        if health is not None and not self._queue:
                            health.beat("durability")
                    self._cond.wait(wait)
                    watchdog.beat(self._name)
            try:
                self._commit_group(group)
                if health is not None:
                    health.beat("durability")
            except Exception:  # noqa: BLE001 — the runs are committed
                # state: durability MUST eventually land (or the health
                # plane reports the stall); requeue the whole group at
                # the head and retry — never drop, never complete
                log.exception("group commit failed (%d runs); retrying",
                              len(group))
                self.m_retries.inc()
                with self._cond:
                    self._queue[:0] = group
                    self._retry_at = time.monotonic() + self.RETRY_DELAY_S
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()
            if not self._running:
                with self._cond:
                    if not self._queue:
                        return

    def _sign_group_replies(self, group: List[SealedRun]) -> None:
        """Optimistic-reply signatures, one batched sign per committed
        group (ISSUE 19 satellite / ROADMAP 4b): execution built the
        group's external replies UNSIGNED (CompletedRun.unsigned) —
        here the io thread signs them all in ONE SigManager.sign_batch
        (the self-hosted engine amortizes the per-signature field
        inversion across the batch; scalar.ed25519_sign_batch), stamps
        the signatures, and appends the packed wire bytes to each run's
        reply list so the group burst below carries them. Runs behind
        the group fsync the reply send already waits on, so the
        deferral costs zero client-visible latency. `device_section`
        brackets the sign so the kernel profiler grows an
        `ed25519.sign` row the RESULTS profile and future autotuner
        policies can read. A sign failure is swallowed per group —
        replies are best-effort (the client retries; the durable state
        is untouched) — and never reaches the _loop retry, which would
        re-apply committed batches."""
        r = self._r
        pending: List[Tuple[object, int, object]] = []
        for s in group:
            unsigned = getattr(s.run, "unsigned", None)
            if unsigned:
                pending.extend((s.run, client, reply)
                               for client, reply in unsigned)
                s.run.unsigned = []
        if not pending:
            return
        try:
            from tpubft.ops.dispatch import device_section
            with device_section("ed25519.sign", batch=len(pending)):
                sigs = r.sig.sign_batch(
                    [reply.signed_payload() for _, _, reply in pending])
            for (run, client, reply), sig in zip(pending, sigs):
                reply.signature = sig
                run.replies.append((client, reply.pack()))
            self.m_signed.inc(len(pending))
        except Exception:  # noqa: BLE001 — see docstring
            log.exception("group reply signing failed (%d replies "
                          "dropped from the burst)", len(pending))

    def _commit_group(self, group: List[SealedRun]) -> None:
        """ONE group: concatenated apply per target DB, the
        `dur.group_fsync` seam, one fsync per distinct DB, watermark
        publication, then per-run completion."""
        r = self._r
        # 1. apply deferred batches, in seal order, one write_group per
        # distinct DB (one concatenated engine record on NativeDB)
        per_db: List[Tuple[object, List[SealedRun]]] = []
        for s in group:
            if s.batch is None or s.db is None or not s.batch.ops:
                continue
            if per_db and per_db[-1][0] is s.db:
                per_db[-1][1].append(s)
            else:
                per_db.append((s.db, [s]))
        for db, seals in per_db:
            db.write_group([s.batch for s in seals])
            for s in seals:
                self.pending.mark_applied(s.run_no, s.batch)
        # 2. the crash seam: group applied (maybe durable, maybe not —
        # the OS owns the buffers), watermark NOT yet published, no
        # reply sent. A kill here must replay the suffix exactly once.
        crashpoint("dur.group_fsync", rid=r.id)
        # 3. one fsync per distinct store
        t0 = time.perf_counter()
        synced = []
        n_syncs = 0
        for s in group:
            for db in (s.db,) + tuple(s.sync_dbs):
                if db is None or any(db is d for d in synced):
                    continue
                # sync_writes-mode stores fsynced the group apply
                # already — one boundary per group, never two
                if not getattr(db, "syncs_on_write", False):
                    db.sync()
                    n_syncs += 1
                synced.append(db)
        fsync_ms = (time.perf_counter() - t0) * 1e3
        # 4. publish: watermark first (monotone, single-writer), then
        # the per-run completions the dispatcher integrates
        wm = max((s.run.last for s in group), default=self.watermark)
        if wm > self.watermark:
            self.watermark = wm
        flight.record(flight.EV_DUR_GROUP, seq=wm, arg=len(group))
        self.m_groups.inc()
        self.m_runs.inc(len(group))
        self.m_fsyncs.inc(n_syncs)
        self.m_fsync_us.inc(int(fsync_ms * 1000))
        self.m_wm.set(self.watermark)
        self.m_wm_lag.set(max(0, self._sealed_head - self.watermark))
        self._h_group_len.record(len(group))
        self._h_fsync_ms.record(fsync_ms)
        # 5. completion — the group IS durable from here: a bookkeeping
        # failure must be swallowed per run, never reach the _loop retry
        # (requeueing a completed run would re-apply its batch and hand
        # it to the dispatcher twice — duplicate replies, double
        # checkpoint votes). Same discipline as the lane's post-commit
        # swallow.
        lane = getattr(r, "exec_lane", None)
        # batched reply signing (ROADMAP 4b): the whole group's deferred
        # reply signatures in ONE sign_batch, BEFORE the reply cache
        # publishes the reply objects (a retransmit answered from the
        # cache must never see an unsigned reply)
        self._sign_group_replies(group)
        burst: List[Tuple[int, bytes]] = []
        for s in group:
            try:
                # at-most-once/reply-cache visibility strictly AFTER
                # the fsync: a retransmit must never be answered from a
                # cache entry whose run could still be lost
                for client, req_seq, reply in s.executed_now:
                    r.clients.on_request_executed(client, req_seq, reply)
            except Exception:  # noqa: BLE001 — see above
                log.exception("post-durability reply-cache publish "
                              "failed for run [%d..%d]",
                              s.run.first, s.run.last)
            # group reply release (ISSUE 16): collect the whole
            # committed group's replies into ONE transport burst —
            # per-run sends from the dispatcher paid a syscall per
            # datagram per run even when a group committed many runs at
            # one fsync boundary. The flag must be set BEFORE
            # complete_durable hands the run over (the lane's lock gives
            # the happens-before), or the dispatcher double-sends.
            burst.extend(getattr(s.run, "replies", ()))
            s.run.replies_sent = True
        comm = getattr(r, "comm", None)
        if burst and comm is not None:
            try:
                comm.send_burst(burst)
            except Exception:  # noqa: BLE001 — replies are best-effort;
                log.exception("group reply burst failed "  # retransmits
                              "(%d replies)", len(burst))  # recover
        for s in group:
            if lane is not None:
                try:
                    lane.complete_durable(s.run)
                except Exception:  # noqa: BLE001 — see above
                    log.exception("completion handoff failed for run "
                                  "[%d..%d]", s.run.first, s.run.last)
        try:
            r.incoming.push_internal_once("exec_done")
        except Exception:  # noqa: BLE001 — the dispatcher's timers
            log.exception("exec_done wakeup failed")  # re-pump anyway

    # ------------------------------------------------------------------
    # telemetry surfaces
    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        """Monotone counters for the autotuner's per-interval deltas."""
        return {"dur_groups": self.m_groups.value,
                "dur_runs": self.m_runs.value,
                "dur_fsync_us": self.m_fsync_us.value}

    def state(self) -> Dict:
        with self._cond:
            depth = len(self._queue)
            busy = self._busy
            held = self._held
        return {"watermark": self.watermark,
                "sealed_head": self._sealed_head,
                "queue_depth": depth, "in_flight": busy, "held": held,
                "group_max": self._group_max,
                "window_us": self._window_us,
                "groups": self.m_groups.value,
                "runs": self.m_runs.value,
                "fsyncs": self.m_fsyncs.value,
                "fsync_us_total": self.m_fsync_us.value,
                "retries": self.m_retries.value,
                "pending_keys": self.pending.depth}

    def render(self) -> str:
        """`status get durability` payload."""
        return json.dumps(self.state(), sort_keys=True)
