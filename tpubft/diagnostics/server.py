"""Localhost TCP diagnostics admin server (reference
diagnostics_server.h:14,129 + the concord-ctl CLI). Line protocol:

  status list            -> registered status handler names
  status get <name>      -> handler output
  perf list              -> histogram names
  perf show <name>       -> count/avg/p50/p95/p99/max
  quit
"""
from __future__ import annotations

import json
import socket
import threading
from typing import Optional

from tpubft.diagnostics.registrar import Registrar, get_registrar


class DiagnosticsServer:
    def __init__(self, registrar: Optional[Registrar] = None,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self._reg = registrar or get_registrar()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._running = False

    def start(self) -> None:
        self._running = True
        self._sock.listen(4)
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"diag-{self.port}").start()

    def stop(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            fh = conn.makefile("rw", encoding="utf-8", newline="\n")
            for line in fh:
                reply = self._handle(line.strip())
                if reply is None:
                    break
                fh.write(reply + "\n.\n")
                fh.flush()
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, line: str) -> Optional[str]:
        parts = line.split()
        if not parts or parts[0] == "quit":
            return None
        if parts[0] == "status":
            if len(parts) == 2 and parts[1] == "list":
                return "\n".join(self._reg.status_keys()) or "(none)"
            if len(parts) == 3 and parts[1] == "get":
                return self._reg.get_status(parts[2])
        if parts[0] == "perf":
            if len(parts) == 2 and parts[1] == "list":
                return "\n".join(self._reg.histogram_keys()) or "(none)"
            if len(parts) == 3 and parts[1] == "show":
                snap = self._reg.histogram_snapshot(parts[2])
                return (json.dumps(snap) if snap is not None
                        else f"unknown histogram: {parts[2]}")
        return f"bad command: {line!r} (try: status list | status get X | " \
               f"perf list | perf show X | quit)"
