"""Diagnostics — in-process status registry + performance histograms +
localhost admin server.

Rebuild of /root/reference/diagnostics/ (diagnostics.h:25 Registrar,
performance_handler.h histogram recorders, diagnostics_server.h:14 the
localhost TCP admin server driven by the concord-ctl CLI). Components
register status handlers and histograms; operators query them live over
a line-based TCP protocol (tpubft/tools/ctl.py).
"""
from tpubft.diagnostics.registrar import (PerfHistogram, Registrar,
                                          TimeRecorder, get_registrar)
from tpubft.diagnostics.server import DiagnosticsServer

__all__ = ["Registrar", "PerfHistogram", "TimeRecorder", "get_registrar",
           "DiagnosticsServer"]
