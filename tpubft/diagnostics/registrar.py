"""Status handlers + latency histograms (reference diagnostics.h:25-32,
performance_handler.h)."""
from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Callable, Dict, List, Optional


class PerfHistogram:
    """Log-bucketed latency histogram (the HDR-histogram role of the
    reference's recorders): sub-microsecond to minutes, ~5% bucket
    resolution, constant memory, lock-free-enough recording."""

    _BUCKETS_PER_DECADE = 48
    _MIN_US = 0.1

    def __init__(self, name: str, unit: str = "us") -> None:
        self.name = name
        self.unit = unit
        self._counts: Dict[int, int] = {}
        self._total = 0
        self._sum = 0.0
        self._max = 0.0
        self._lock = threading.Lock()

    def record(self, value_us: float) -> None:
        if value_us <= 0:
            value_us = self._MIN_US
        b = int(math.log10(value_us / self._MIN_US)
                * self._BUCKETS_PER_DECADE)
        with self._lock:
            self._counts[b] = self._counts.get(b, 0) + 1
            self._total += 1
            self._sum += value_us
            self._max = max(self._max, value_us)

    def _bucket_value(self, b: int) -> float:
        return self._MIN_US * 10 ** ((b + 0.5) / self._BUCKETS_PER_DECADE)

    def percentile(self, p: float) -> float:
        with self._lock:
            if not self._total:
                return 0.0
            target = self._total * p / 100.0
            acc = 0
            for b in sorted(self._counts):
                acc += self._counts[b]
                if acc >= target:
                    return self._bucket_value(b)
            return self._max

    def snapshot(self) -> Dict:
        with self._lock:
            total, s, mx = self._total, self._sum, self._max
        return {"count": total, "avg": (s / total if total else 0.0),
                "max": mx, "p50": self.percentile(50),
                "p95": self.percentile(95), "p99": self.percentile(99),
                "unit": self.unit}


class TimeRecorder:
    """`with TimeRecorder(hist): ...` — records elapsed microseconds
    (reference TimeRecorder, e.g. ReplicaImp.cpp:5367)."""

    def __init__(self, hist: Optional[PerfHistogram]) -> None:
        self._hist = hist
        self._t0 = 0.0

    def __enter__(self) -> "TimeRecorder":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        if self._hist is not None:
            self._hist.record((time.perf_counter() - self._t0) * 1e6)


class Registrar:
    """Process-wide registry of status handlers + histograms
    (reference concord::diagnostics::Registrar)."""

    def __init__(self) -> None:
        self._status: Dict[str, Callable[[], str]] = {}
        self._hists: Dict[str, PerfHistogram] = {}
        self._lock = threading.Lock()

    # status handlers
    def register_status(self, name: str, fn: Callable[[], str]) -> None:
        with self._lock:
            self._status[name] = fn

    def status_keys(self) -> List[str]:
        with self._lock:
            return sorted(self._status)

    def get_status(self, name: str) -> str:
        with self._lock:
            fn = self._status.get(name)
        if fn is None:
            return f"unknown status handler: {name}"
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — diag must not crash host
            return f"<status handler error: {e}>"

    # histograms
    def histogram(self, name: str, unit: str = "us") -> PerfHistogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = PerfHistogram(name, unit)
            return h

    def histogram_keys(self) -> List[str]:
        with self._lock:
            return sorted(self._hists)

    def histogram_snapshot(self, name: str) -> Optional[Dict]:
        with self._lock:
            h = self._hists.get(name)
        return h.snapshot() if h else None


_global = Registrar()


def get_registrar() -> Registrar:
    return _global
