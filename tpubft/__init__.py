"""tpubft — a TPU-native Byzantine fault tolerant SMR framework.

A from-scratch rebuild of the capabilities of Concord-BFT (reference:
/root/reference, vmware/concord-bft) designed TPU-first: the consensus
control plane is host code, while the cryptographic data plane (signature
verification, BLS threshold-share accumulation, multi-scalar multiplication,
pairing checks, digest trees) runs as batched, vmapped JAX/XLA/Pallas
kernels behind the same plugin boundaries the reference uses
(SigManager, IThresholdSigner/Verifier/Accumulator, Cryptosystem).

Layer map (mirrors SURVEY.md §1):
  tpubft.utils       — foundation: config registry, metrics, serialization (L1/L2)
  tpubft.crypto      — crypto interfaces + CPU reference backends (L4)
  tpubft.ops         — JAX/TPU kernels: bignum limb engine, ed25519, ecdsa,
                       BLS12-381 towers/pairing/MSM (L4 data plane)
  tpubft.parallel    — device mesh / shard_map sharding of crypto batches
  tpubft.comm        — ICommunication + UDP/loopback transports (L3)
  tpubft.consensus   — SBFT engine: messages, replica, collectors, view change (L5)
  tpubft.storage     — IDBClient abstraction + memory/file backends
  tpubft.kvbc        — categorized key-value blockchain + sparse merkle tree (L6)
  tpubft.statetransfer — block/state synchronisation for lagging replicas
  tpubft.client      — BFT client with quorum matching (L7)
  tpubft.models      — replicated state machines (counter, KV) used by apps/tests
"""

__version__ = "0.1.0"
