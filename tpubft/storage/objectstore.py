"""Object store for read-only-replica ledger archival.

Rebuild of the reference's S3/object-store layer
(/root/reference/storage/src/s3/client.cpp, consumed by the read-only
replica for ledger archival with integrity checks): a flat key→blob
store with S3-ish semantics (put/get/exists/delete/list-by-prefix).

Integrity model: every object is stored as sha256(data) || data, and
`get` verifies the digest before returning — a corrupted or truncated
object read returns None instead of poisoning the reader (the reference
performs the analogous checksum validation on its archival reads). The
filesystem backend writes atomically (tmp + rename) so a crash can't
leave a half-written object that passes existence checks.
"""
from __future__ import annotations

import abc
import hashlib
import os
import tempfile
from typing import Dict, Iterator, Optional


class IObjectStore(abc.ABC):
    @abc.abstractmethod
    def put(self, key: str, data: bytes) -> None: ...

    @abc.abstractmethod
    def get(self, key: str) -> Optional[bytes]:
        """None if absent OR integrity-corrupt."""

    @abc.abstractmethod
    def exists(self, key: str) -> bool: ...

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    @abc.abstractmethod
    def list(self, prefix: str = "") -> Iterator[str]: ...


def _seal(data: bytes) -> bytes:
    return hashlib.sha256(data).digest() + data


def _unseal(blob: Optional[bytes]) -> Optional[bytes]:
    if blob is None or len(blob) < 32:
        return None
    digest, data = blob[:32], blob[32:]
    if hashlib.sha256(data).digest() != digest:
        return None
    return data


class InMemoryObjectStore(IObjectStore):
    """Test double (the reference's tests run against a fake S3)."""

    def __init__(self) -> None:
        self._objs: Dict[str, bytes] = {}

    def put(self, key: str, data: bytes) -> None:
        self._objs[key] = _seal(data)

    def get(self, key: str) -> Optional[bytes]:
        return _unseal(self._objs.get(key))

    def exists(self, key: str) -> bool:
        return key in self._objs

    def delete(self, key: str) -> None:
        self._objs.pop(key, None)

    def list(self, prefix: str = "") -> Iterator[str]:
        return iter(sorted(k for k in self._objs if k.startswith(prefix)))

    def corrupt(self, key: str) -> None:
        """Test hook: flip a byte so integrity verification must fail."""
        blob = bytearray(self._objs[key])
        blob[-1] ^= 0xFF
        self._objs[key] = bytes(blob)


class FsObjectStore(IObjectStore):
    """Directory-backed store; '/' in keys maps to subdirectories."""

    def __init__(self, root: str) -> None:
        self._root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        root = os.path.abspath(self._root)
        path = os.path.abspath(os.path.join(root, key))
        if path != root and not path.startswith(root + os.sep):
            raise ValueError(f"key escapes store root: {key!r}")
        return path

    def put(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(_seal(data))
            os.replace(tmp, path)       # atomic: never a torn object
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return _unseal(f.read())
        except OSError:
            return None

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except OSError:
            pass

    def list(self, prefix: str = "") -> Iterator[str]:
        out = []
        for dirpath, _, files in os.walk(self._root):
            for fn in files:
                rel = os.path.relpath(os.path.join(dirpath, fn), self._root)
                key = rel.replace(os.sep, "/")
                if key.startswith(prefix):
                    out.append(key)
        return iter(sorted(out))
