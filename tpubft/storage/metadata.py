"""Consensus-metadata object store over IDBClient.

Rebuild of the reference's DBMetadataStorage
(/root/reference/bftengine/src/bftengine/DbMetadataStorage.cpp): numbered
metadata objects with atomic multi-object transactions, used by the
consensus engine's persistent state. Also provides DBPersistentStorage,
which plugs the consensus `PersistentStorage` interface
(tpubft/consensus/persistent.py) into any IDBClient backend — with the
native kvlog engine this gives the crash-consistent WAL semantics of
PersistentStorageImp.
"""
from __future__ import annotations

import json
import struct
from typing import Dict, Optional

from tpubft.consensus.persistent import (PersistedSeqState, PersistedState,
                                         PersistentStorage)
from tpubft.storage.interfaces import IDBClient, WriteBatch
from tpubft.utils.serialize import (SerializeError, read_bytes, read_uint,
                                    write_bytes, write_uint)

_FAMILY = b"metadata"


class MetadataStorage:
    """Keyed object store with atomic transactions
    (reference storage/include/storage/db_metadata_storage.h)."""

    def __init__(self, db: IDBClient) -> None:
        self._db = db
        self._tran: Optional[WriteBatch] = None
        self._pending: Dict[int, bytes] = {}

    @staticmethod
    def _key(object_id: int) -> bytes:
        return object_id.to_bytes(4, "big")

    def read(self, object_id: int) -> Optional[bytes]:
        if self._tran is not None and object_id in self._pending:
            return self._pending[object_id]
        return self._db.get(self._key(object_id), _FAMILY)

    def write(self, object_id: int, data: bytes) -> None:
        if self._tran is not None:
            self._tran.put(self._key(object_id), data, _FAMILY)
            self._pending[object_id] = data
        else:
            self._db.put(self._key(object_id), data, _FAMILY)

    def begin_atomic_write(self) -> None:
        assert self._tran is None, "nested metadata transaction"
        self._tran = WriteBatch()
        self._pending = {}

    def commit_atomic_write(self) -> None:
        assert self._tran is not None
        try:
            self._db.write(self._tran)
        finally:
            self._tran = None
            self._pending = {}


# Object ids (reference PersistentStorageImp constants)
_OBJ_STATE = 1

# incremental layout: descriptors + VC blobs in _FAMILY, one row per
# in-window seq in _SEQ_FAMILY (8-byte big-endian key → ordered scans).
# Row codec: the repo-standard length-prefixed primitives from
# utils/serialize (bounds-checked; corrupt rows raise, not garbage).
_SEQ_FAMILY = b"metaseq"

# the families a NativeDB must keep durable even with db_sync_writes
# off (ReplicaConfig.db_sync_metadata → open_db sync_families): losing
# a vote/prepare this replica persisted here is a protocol-safety
# hazard under correlated power loss; everything else is re-derivable
CONSENSUS_META_FAMILIES = (_FAMILY, _SEQ_FAMILY)
_KEY_DESC = b"\x00\x00\x00\x02"
_KEY_VC = b"\x00\x00\x00\x03"


def _pack_blobs(buf: bytearray, blobs) -> None:
    write_uint(buf, len(blobs), 4)
    for b in blobs:
        write_bytes(buf, b)


def _unpack_blobs(buf: memoryview, off: int = 0):
    n, off = read_uint(buf, off, 4)
    out = []
    for _ in range(n):
        b, off = read_bytes(buf, off)
        out.append(b)
    return out, off


def _pack_opt(buf: bytearray, b) -> None:
    if b is None:
        buf += b"\x00"
    else:
        buf += b"\x01"
        write_bytes(buf, b)


def _unpack_opt(buf: memoryview, off: int):
    if off >= len(buf):
        raise SerializeError("truncated optional")
    if buf[off] == 0:
        return None, off + 1
    return read_bytes(buf, off + 1)


class DBPersistentStorage(PersistentStorage):
    """Consensus PersistentStorage over IDBClient, persisted
    INCREMENTALLY: each end_write_tran writes one atomic batch holding
    only the rows the transaction touched (descriptor scalars, VC blobs,
    dirty/deleted seq entries) in a compact binary form — the reference
    PersistentStorageImp likewise persists per-seq keys, not the whole
    window (PersistentStorageImp.cpp setSeqNumDataElement). Profiling
    showed the previous whole-state-JSON-per-commit design spending more
    dispatcher time base64-encoding the window than verifying
    signatures."""

    def __init__(self, db: IDBClient) -> None:
        self._db = db
        self._legacy = False
        self._state = self._load_initial()
        self._last_desc: bytes = self._pack_desc()
        self._last_vc: bytes = self._pack_vc()
        self._depth = 0
        if self._legacy:
            self._migrate_legacy()

    def _migrate_legacy(self) -> None:
        """One-shot rewrite of a legacy whole-state-JSON DB into the
        incremental layout (and removal of the legacy object, so a later
        open can never resurrect the stale JSON over newer rows)."""
        batch = WriteBatch()
        batch.put(_KEY_DESC, self._last_desc, _FAMILY)
        batch.put(_KEY_VC, self._last_vc, _FAMILY)
        for seq, entry in self._state.seq_states.items():
            batch.put(seq.to_bytes(8, "big"), self._pack_seq(entry),
                      _SEQ_FAMILY)
        batch.delete(MetadataStorage._key(_OBJ_STATE), _FAMILY)
        self._db.write(batch)
        self._desc_on_disk = True

    # ---- codecs ----
    def _pack_desc(self) -> bytes:
        st = self._state
        return struct.pack("<qqqqB", st.last_view, st.last_executed_seq,
                           st.last_stable_seq, st.pending_view,
                           1 if st.in_view_change else 0)

    def _pack_vc(self) -> bytes:
        st = self._state
        buf = bytearray()
        _pack_blobs(buf, st.restrictions)
        _pack_blobs(buf, st.carried_certs)
        _pack_blobs(buf, st.carried_bodies)
        return bytes(buf)

    @staticmethod
    def _pack_seq(e: PersistedSeqState) -> bytes:
        buf = bytearray(b"\x01" if e.slow_started else b"\x00")
        _pack_opt(buf, e.pre_prepare)
        _pack_opt(buf, e.prepare_full)
        _pack_opt(buf, e.commit_full)
        _pack_opt(buf, e.full_commit_proof)
        return bytes(buf)

    @staticmethod
    def _unpack_seq(raw: bytes) -> PersistedSeqState:
        buf = memoryview(raw)
        e = PersistedSeqState(slow_started=buf[0] == 1)
        off = 1
        e.pre_prepare, off = _unpack_opt(buf, off)
        e.prepare_full, off = _unpack_opt(buf, off)
        e.commit_full, off = _unpack_opt(buf, off)
        e.full_commit_proof, off = _unpack_opt(buf, off)
        return e

    # ---- load ----
    def _load_initial(self) -> PersistedState:
        desc = self._db.get(_KEY_DESC, _FAMILY)
        self._desc_on_disk = desc is not None
        if desc is None:
            # legacy layout: whole state as one JSON object (object id 1)
            raw = self._db.get(MetadataStorage._key(_OBJ_STATE), _FAMILY)
            if raw is None:
                return PersistedState()
            from tpubft.consensus.persistent import FilePersistentStorage
            st = FilePersistentStorage._decode(json.loads(raw.decode()))
            st.clear_tracking()
            self._legacy = True
            return st
        if len(desc) == struct.calcsize("<qqqB"):   # pre-pending_view row
            v, e, s, ivc = struct.unpack("<qqqB", desc)
            pv = 0
        else:
            v, e, s, pv, ivc = struct.unpack("<qqqqB", desc)
        st = PersistedState(last_view=v, last_executed_seq=e,
                            last_stable_seq=s, pending_view=pv,
                            in_view_change=ivc == 1)
        vc = self._db.get(_KEY_VC, _FAMILY)
        if vc:
            mv = memoryview(vc)
            st.restrictions, off = _unpack_blobs(mv, 0)
            st.carried_certs, off = _unpack_blobs(mv, off)
            st.carried_bodies, _ = _unpack_blobs(mv, off)
        for key, val in self._db.range_iter(_SEQ_FAMILY):
            st.seq_states[int.from_bytes(key, "big")] = self._unpack_seq(val)
        st.clear_tracking()
        return st

    # ---- transactions ----
    def begin_write_tran(self) -> PersistedState:
        self._depth += 1
        return self._state

    def end_write_tran(self) -> None:
        assert self._depth > 0
        self._depth -= 1
        if self._depth != 0:
            return
        st = self._state
        batch = WriteBatch()
        vc = self._pack_vc()
        if vc != self._last_vc:
            batch.put(_KEY_VC, vc, _FAMILY)
        for seq in st.dirty_seqs:
            entry = st.seq_states.get(seq)
            if entry is not None:
                batch.put(seq.to_bytes(8, "big"), self._pack_seq(entry),
                          _SEQ_FAMILY)
        for seq in st.deleted_seqs:
            batch.delete(seq.to_bytes(8, "big"), _SEQ_FAMILY)
        desc = self._pack_desc()
        # the desc row doubles as the layout marker _load_initial keys on:
        # ANY first write must include it, or a crash before the scalars
        # first change would recover a blank state over live seq rows
        if desc != self._last_desc or (batch.ops and not self._desc_on_disk):
            batch.put(_KEY_DESC, desc, _FAMILY)
        if batch.ops:
            # tracking + caches update only after the write lands — a
            # failed batch must leave the dirt in place for the next
            # commit to retry, not diverge disk from memory silently
            self._db.write(batch)
            self._last_desc = desc
            self._last_vc = vc
            self._desc_on_disk = True
        st.clear_tracking()

    def load(self) -> PersistedState:
        return self._state
