"""Consensus-metadata object store over IDBClient.

Rebuild of the reference's DBMetadataStorage
(/root/reference/bftengine/src/bftengine/DbMetadataStorage.cpp): numbered
metadata objects with atomic multi-object transactions, used by the
consensus engine's persistent state. Also provides DBPersistentStorage,
which plugs the consensus `PersistentStorage` interface
(tpubft/consensus/persistent.py) into any IDBClient backend — with the
native kvlog engine this gives the crash-consistent WAL semantics of
PersistentStorageImp.
"""
from __future__ import annotations

import json
from typing import Dict, Optional

from tpubft.consensus.persistent import (PersistedState, PersistentStorage)
from tpubft.storage.interfaces import IDBClient, WriteBatch

_FAMILY = b"metadata"


class MetadataStorage:
    """Keyed object store with atomic transactions
    (reference storage/include/storage/db_metadata_storage.h)."""

    def __init__(self, db: IDBClient) -> None:
        self._db = db
        self._tran: Optional[WriteBatch] = None
        self._pending: Dict[int, bytes] = {}

    @staticmethod
    def _key(object_id: int) -> bytes:
        return object_id.to_bytes(4, "big")

    def read(self, object_id: int) -> Optional[bytes]:
        if self._tran is not None and object_id in self._pending:
            return self._pending[object_id]
        return self._db.get(self._key(object_id), _FAMILY)

    def write(self, object_id: int, data: bytes) -> None:
        if self._tran is not None:
            self._tran.put(self._key(object_id), data, _FAMILY)
            self._pending[object_id] = data
        else:
            self._db.put(self._key(object_id), data, _FAMILY)

    def begin_atomic_write(self) -> None:
        assert self._tran is None, "nested metadata transaction"
        self._tran = WriteBatch()
        self._pending = {}

    def commit_atomic_write(self) -> None:
        assert self._tran is not None
        try:
            self._db.write(self._tran)
        finally:
            self._tran = None
            self._pending = {}


# Object ids (reference PersistentStorageImp constants)
_OBJ_STATE = 1


class DBPersistentStorage(PersistentStorage):
    """Consensus PersistentStorage over MetadataStorage/IDBClient. The
    whole PersistedState is one metadata object committed atomically per
    end_write_tran — the backend's batch atomicity supplies the WAL
    guarantee."""

    def __init__(self, db: IDBClient) -> None:
        self._meta = MetadataStorage(db)
        self._state = self._load_initial()
        self._depth = 0

    def _load_initial(self) -> PersistedState:
        from tpubft.consensus.persistent import FilePersistentStorage
        raw = self._meta.read(_OBJ_STATE)
        if raw is None:
            return PersistedState()
        return FilePersistentStorage._decode(json.loads(raw.decode()))

    def begin_write_tran(self) -> PersistedState:
        self._depth += 1
        return self._state

    def end_write_tran(self) -> None:
        assert self._depth > 0
        self._depth -= 1
        if self._depth == 0:
            from tpubft.consensus.persistent import FilePersistentStorage
            raw = json.dumps(FilePersistentStorage._encode(self._state),
                             separators=(",", ":")).encode()
            self._meta.begin_atomic_write()
            self._meta.write(_OBJ_STATE, raw)
            self._meta.commit_atomic_write()

    def load(self) -> PersistedState:
        return self._state
