"""Persistent IDBClient backed by the native C++ kvlog engine
(tpubft/native/kvlog.cpp) — the RocksDB role of the reference's storage
layer (/root/reference/storage/src/rocksdb_client.cpp), via ctypes."""
from __future__ import annotations

import ctypes
from typing import Iterator, List, Optional, Sequence, Tuple

from tpubft.native.build import load
from tpubft.storage.interfaces import (DEFAULT_FAMILY, IDBClient, StorageError,
                                       WriteBatch, family_upper_bound, fkey)

_U8P = ctypes.POINTER(ctypes.c_uint8)


def _lib():
    lib = load("kvlog")
    if getattr(lib, "_kvlog_typed", False):
        return lib
    lib.kvlog_open.restype = ctypes.c_void_p
    lib.kvlog_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.kvlog_close.argtypes = [ctypes.c_void_p]
    lib.kvlog_apply.restype = ctypes.c_int
    lib.kvlog_apply.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint32]
    lib.kvlog_get.restype = ctypes.c_int
    lib.kvlog_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_uint32, ctypes.POINTER(_U8P),
                              ctypes.POINTER(ctypes.c_uint32)]
    lib.kvlog_free.argtypes = [_U8P]
    lib.kvlog_count.restype = ctypes.c_uint64
    lib.kvlog_count.argtypes = [ctypes.c_void_p]
    lib.kvlog_wal_bytes.restype = ctypes.c_uint64
    lib.kvlog_wal_bytes.argtypes = [ctypes.c_void_p]
    lib.kvlog_scan.restype = ctypes.c_int
    lib.kvlog_scan.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_uint32, ctypes.c_char_p,
                               ctypes.c_uint32, ctypes.POINTER(_U8P),
                               ctypes.POINTER(ctypes.c_uint32)]
    lib.kvlog_compact.restype = ctypes.c_int
    lib.kvlog_compact.argtypes = [ctypes.c_void_p]
    lib.kvlog_sync.restype = ctypes.c_int
    lib.kvlog_sync.argtypes = [ctypes.c_void_p]
    lib.kvlog_checkpoint.restype = ctypes.c_int
    lib.kvlog_checkpoint.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib._kvlog_typed = True
    return lib


def _decode_scan(buf: bytes) -> List[Tuple[bytes, bytes]]:
    out, off, n = [], 0, len(buf)
    while off < n:
        klen = int.from_bytes(buf[off + 1:off + 5], "little")
        off += 5
        k = buf[off:off + klen]
        off += klen
        vlen = int.from_bytes(buf[off:off + 4], "little")
        off += 4
        out.append((k, buf[off:off + vlen]))
        off += vlen
    return out


class NativeDB(IDBClient):
    """Crash-consistent persistent KV store. `sync_writes=False` trades
    durability-per-batch for throughput (recovery still sees a prefix of
    committed batches — record CRCs stop replay at the torn tail).

    `sync_families` carves out families that stay durable anyway: a batch
    touching any of them is fsync'd after apply even when
    sync_writes=False (the consensus-metadata carve-out — losing a
    prepare this replica voted on is a safety hazard; block data is
    re-derivable from the quorum). Ignored when sync_writes=True (every
    batch already syncs)."""

    def __init__(self, path: str, sync_writes: bool = True,
                 compact_bytes: int = 64 << 20,
                 sync_families: Sequence[bytes] = ()) -> None:
        self._lib = _lib()
        self._h = self._lib.kvlog_open(path.encode(), 1 if sync_writes else 0)
        if not self._h:
            raise StorageError(f"kvlog_open failed for {path}")
        self._compact_bytes = compact_bytes
        self._sync_writes = sync_writes
        self._sync_prefixes: Tuple[bytes, ...] = () if sync_writes else \
            tuple(bytes([len(f)]) + f for f in sync_families)
        # ctypes releases the GIL around C calls, and the execution lane
        # writes ledger/pages batches concurrently with the dispatcher's
        # metadata batches on the SAME handle. The C engine is not
        # audited for lock-free concurrent access, so EVERY handle
        # operation — reads and scans included — serializes here. This
        # is a deliberate latency trade: a dispatcher point read can
        # block behind the lane's run commit (one buffered batch apply;
        # fsync only for sync-family batches, which originate on the
        # dispatcher itself). Relaxing reads requires a C-side
        # concurrency audit first.
        import threading
        self._write_mu = threading.Lock()

    def _handle(self):
        if not self._h:
            raise StorageError("NativeDB is closed")
        return self._h

    def get(self, key: bytes,
            family: bytes = DEFAULT_FAMILY) -> Optional[bytes]:
        self._handle()
        k = fkey(family, key)
        val = _U8P()
        vlen = ctypes.c_uint32()
        with self._write_mu:
            rc = self._lib.kvlog_get(self._handle(), k, len(k),
                                     ctypes.byref(val),
                                     ctypes.byref(vlen))
            if rc == 1:
                return None
            if rc != 0:
                raise StorageError(f"kvlog_get rc={rc}")
            try:
                return ctypes.string_at(val, vlen.value)
            finally:
                self._lib.kvlog_free(val)

    def write(self, batch: WriteBatch) -> None:
        self._handle()
        payload = batch.encode()
        with self._write_mu:
            rc = self._lib.kvlog_apply(self._handle(), payload,
                                       len(payload))
            if rc != 0:
                raise StorageError(f"kvlog_apply rc={rc}")
            if self._sync_prefixes and any(
                    k.startswith(self._sync_prefixes)
                    for k, _ in batch.ops):
                rc = self._lib.kvlog_sync(self._h)
                if rc != 0:
                    raise StorageError(f"kvlog_sync rc={rc}")
            need_compact = (self._lib.kvlog_wal_bytes(self._h)
                            > self._compact_bytes)
        if need_compact:
            self.compact()

    def write_group(self, batches) -> None:
        """Group-commit apply seam (tpubft/durability/): concatenate the
        group's batches into ONE kvlog record — one payload encode, one
        apply under the handle lock, one CRC (so the whole group is
        atomic under torn-tail recovery), and in sync_writes mode one
        fsync instead of one per batch. The consensus-metadata carve-out
        applies to the union of the group's ops, exactly as if they had
        been one batch."""
        merged = WriteBatch()
        for b in batches:
            merged.ops.extend(b.ops)
        if merged.ops:
            self.write(merged)

    @property
    def syncs_on_write(self) -> bool:
        """True in sync_writes mode: every apply already fsyncs, so the
        durability pipeline's explicit group `sync()` would pay the
        disk twice per group — the pipeline skips it."""
        return self._sync_writes

    def sync(self) -> None:
        """One fsync covering every batch applied so far — the
        durability pipeline's group-commit boundary. Held under the
        handle lock: kvlog_sync only reads the fd, but close() frees
        the handle and must never race an in-flight C call (same rule
        as every other handle op). Writers queued behind a slow sync
        pay the disk once per GROUP, not per run — the amortization the
        pipeline exists to buy."""
        with self._write_mu:
            rc = self._lib.kvlog_sync(self._handle())
            if rc != 0:
                raise StorageError(f"kvlog_sync rc={rc}")

    def range_iter(self, family: bytes = DEFAULT_FAMILY,
                   start: Optional[bytes] = None,
                   end: Optional[bytes] = None
                   ) -> Iterator[Tuple[bytes, bytes]]:
        self._handle()
        lo = fkey(family, start if start is not None else b"")
        hi = fkey(family, end) if end is not None else family_upper_bound(family)
        out = _U8P()
        outlen = ctypes.c_uint32()
        with self._write_mu:
            rc = self._lib.kvlog_scan(
                self._handle(), lo, len(lo),
                hi if hi is not None else b"",
                0xFFFFFFFF if hi is None else len(hi),
                ctypes.byref(out), ctypes.byref(outlen))
            if rc != 0:
                raise StorageError(f"kvlog_scan rc={rc}")
            try:
                buf = ctypes.string_at(out, outlen.value)
            finally:
                self._lib.kvlog_free(out)
        prefix = 1 + len(family)
        for k, v in _decode_scan(buf):
            yield k[prefix:], v

    def scan_all(self):
        from tpubft.storage.interfaces import split_fkey
        self._handle()
        out = _U8P()
        outlen = ctypes.c_uint32()
        with self._write_mu:
            rc = self._lib.kvlog_scan(self._handle(), b"", 0, b"",
                                      0xFFFFFFFF, ctypes.byref(out),
                                      ctypes.byref(outlen))
            if rc != 0:
                raise StorageError(f"kvlog_scan rc={rc}")
            try:
                buf = ctypes.string_at(out, outlen.value)
            finally:
                self._lib.kvlog_free(out)
        for k, v in _decode_scan(buf):
            fam, key = split_fkey(k)
            yield fam, key, v

    def compact(self) -> None:
        with self._write_mu:
            rc = self._lib.kvlog_compact(self._handle())
            if rc != 0:
                raise StorageError(f"kvlog_compact rc={rc}")

    def checkpoint_to(self, path: str) -> None:
        """Consistent snapshot for operator backups (reference:
        DbCheckpointManager RocksDB checkpoints). The snapshot file is a
        valid kvlog — openable with NativeDB directly."""
        with self._write_mu:
            rc = self._lib.kvlog_checkpoint(self._handle(), path.encode())
            if rc != 0:
                raise StorageError(f"kvlog_checkpoint rc={rc}")

    def count(self) -> int:
        with self._write_mu:
            return self._lib.kvlog_count(self._handle())

    def close(self) -> None:
        # under the handle lock: a lane thread that outlived its join
        # timeout could still be inside a C call on this handle — close
        # must never free it mid-operation
        with self._write_mu:
            if self._h:
                self._lib.kvlog_close(self._h)
                self._h = None
