"""Storage layer — abstract KV DB + backends + consensus metadata store.

Rebuild of /root/reference/storage/ (IDBClient, memorydb, RocksDB client)
and bftengine's DBMetadataStorage. The persistent backend here is a
native C++ log-structured engine (tpubft/native/kvlog.cpp) instead of
RocksDB, loaded via ctypes.
"""
from tpubft.storage.interfaces import (DEFAULT_FAMILY, IDBClient, StorageError,
                                       WriteBatch)
from tpubft.storage.memorydb import MemoryDB

__all__ = ["IDBClient", "WriteBatch", "MemoryDB", "StorageError",
           "DEFAULT_FAMILY"]
