"""S3 wire-protocol object store.

Rebuild of the reference's S3 client
(/root/reference/storage/src/s3/client.cpp, libs3-based, consumed by the
read-only replica for ledger archival): an `IObjectStore` speaking the
S3 REST API over HTTP — PUT/GET/HEAD/DELETE object plus ListObjectsV2 —
with AWS Signature Version 4 request signing, against any S3-compatible
endpoint (AWS, MinIO, or the in-repo test server,
`tpubft.testing.s3server`).

The integrity model of the archival layer (sha256 seal per object,
`objectstore._seal/_unseal`) is preserved on top of the wire protocol:
a corrupted object read returns None exactly like the filesystem
backend, so `ReadOnlyReplica` consumes either interchangeably.

Connections are pooled per thread (http.client keep-alive); transient
transport errors retry once with a fresh connection — the reference
client's retry-on-broken-connection behavior.
"""
from __future__ import annotations

import datetime
import hashlib
import hmac
import http.client
import threading
import urllib.parse
from typing import Iterator, Optional
from xml.etree import ElementTree

from tpubft.storage.objectstore import IObjectStore, _seal, _unseal

_ALGO = "AWS4-HMAC-SHA256"


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sigv4_headers(method: str, host: str, path: str, query: str,
                  payload: bytes, access_key: str, secret_key: str,
                  region: str = "us-east-1", service: str = "s3",
                  now: Optional[datetime.datetime] = None) -> dict:
    """AWS Signature Version 4 for one request (the auth scheme every
    S3-compatible store speaks). Returns the headers to attach.
    Deterministic given `now` — the test server re-derives and compares.
    """
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(payload).hexdigest()
    headers = {
        "host": host,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    signed = ";".join(sorted(headers))
    canonical_qs = "&".join(sorted(query.split("&"))) if query else ""
    canonical = "\n".join([
        method,
        urllib.parse.quote(path, safe="/-_.~"),
        canonical_qs,
        "".join(f"{k}:{headers[k].strip()}\n" for k in sorted(headers)),
        signed,
        payload_hash,
    ])
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    to_sign = "\n".join([
        _ALGO, amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest(),
    ])
    k = _hmac(_hmac(_hmac(_hmac(("AWS4" + secret_key).encode(), datestamp),
                          region), service), "aws4_request")
    signature = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    headers["authorization"] = (
        f"{_ALGO} Credential={access_key}/{scope}, "
        f"SignedHeaders={signed}, Signature={signature}")
    return headers


class S3Error(Exception):
    pass


class S3ObjectStore(IObjectStore):
    """S3-REST `IObjectStore`. `endpoint` is "host:port" (plain HTTP —
    the reference's deployment terminates TLS in front; an https variant
    would swap HTTPSConnection in)."""

    def __init__(self, endpoint: str, bucket: str,
                 access_key: str = "", secret_key: str = "",
                 region: str = "us-east-1", prefix: str = "",
                 timeout_s: float = 10.0):
        self._endpoint = endpoint
        self._bucket = bucket
        self._access, self._secret = access_key, secret_key
        self._region = region
        self._prefix = prefix
        self._timeout = timeout_s
        self._local = threading.local()

    # ---- transport ----
    def _conn(self, fresh: bool = False) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None or fresh:
            if conn is not None:
                conn.close()
            conn = http.client.HTTPConnection(self._endpoint,
                                              timeout=self._timeout)
            self._local.conn = conn
        return conn

    def _request(self, method: str, key: str, query: str = "",
                 body: bytes = b""):
        # sigv4_headers canonicalizes (quotes) the RAW path itself —
        # passing a pre-quoted path would double-encode the signature
        raw_path = "/" + (f"{self._bucket}/{self._prefix}{key}" if key
                          else self._bucket)
        headers = sigv4_headers(method, self._endpoint, raw_path, query,
                                body, self._access, self._secret,
                                self._region)
        if body:
            headers["content-length"] = str(len(body))
        url = (urllib.parse.quote(raw_path, safe="/-_.~")
               + ("?" + query if query else ""))
        for attempt in (0, 1):      # one retry on a broken keep-alive conn
            conn = self._conn(fresh=attempt > 0)
            try:
                conn.request(method, url, body=body or None,
                             headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                return resp.status, data
            except (http.client.HTTPException, OSError):
                if attempt:
                    raise
        raise AssertionError("unreachable")

    # ---- IObjectStore ----
    def put(self, key: str, data: bytes) -> None:
        status, body = self._request("PUT", key, body=_seal(data))
        if status not in (200, 201, 204):
            raise S3Error(f"PUT {key}: HTTP {status} {body[:200]!r}")

    def get(self, key: str) -> Optional[bytes]:
        status, body = self._request("GET", key)
        if status == 404:
            return None
        if status != 200:
            raise S3Error(f"GET {key}: HTTP {status}")
        return _unseal(body)

    def exists(self, key: str) -> bool:
        status, _ = self._request("HEAD", key)
        if status in (200,):
            return True
        if status in (404,):
            return False
        raise S3Error(f"HEAD {key}: HTTP {status}")

    def delete(self, key: str) -> None:
        status, _ = self._request("DELETE", key)
        if status not in (200, 204, 404):
            raise S3Error(f"DELETE {key}: HTTP {status}")

    def list(self, prefix: str = "") -> Iterator[str]:
        """ListObjectsV2 with continuation tokens."""
        token = None
        out = []
        while True:
            q = ("list-type=2&prefix="
                 + urllib.parse.quote(self._prefix + prefix, safe=""))
            if token:
                q += "&continuation-token=" + urllib.parse.quote(token,
                                                                 safe="")
            status, body = self._request("GET", "", query=q)
            if status != 200:
                raise S3Error(f"LIST: HTTP {status}")
            root = ElementTree.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[:root.tag.index("}") + 1]
            for el in root.iter(f"{ns}Key"):
                k = el.text or ""
                if k.startswith(self._prefix):
                    out.append(k[len(self._prefix):])
            trunc = root.find(f"{ns}IsTruncated")
            if trunc is not None and (trunc.text or "").lower() == "true":
                tok = root.find(f"{ns}NextContinuationToken")
                token = tok.text if tok is not None else None
                if not token:
                    break
            else:
                break
        return iter(sorted(out))
