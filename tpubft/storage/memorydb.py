"""In-memory IDBClient for unit tests (reference:
/root/reference/storage/src/memorydb_client.cpp). Ordered via a bisect-
maintained key list so range iteration matches the persistent backends."""
from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterator, List, Optional, Tuple

from tpubft.storage.interfaces import (DEFAULT_FAMILY, IDBClient, WriteBatch,
                                       family_upper_bound, fkey)


class MemoryDB(IDBClient):
    def __init__(self) -> None:
        self._map: Dict[bytes, bytes] = {}
        self._keys: List[bytes] = []        # sorted physical keys
        self._lock = threading.RLock()

    def get(self, key: bytes,
            family: bytes = DEFAULT_FAMILY) -> Optional[bytes]:
        with self._lock:
            return self._map.get(fkey(family, key))

    def write(self, batch: WriteBatch) -> None:
        with self._lock:
            for k, v in batch.ops:
                if v is None:
                    if k in self._map:
                        del self._map[k]
                        i = bisect.bisect_left(self._keys, k)
                        del self._keys[i]
                else:
                    if k not in self._map:
                        bisect.insort(self._keys, k)
                    self._map[k] = v

    def range_iter(self, family: bytes = DEFAULT_FAMILY,
                   start: Optional[bytes] = None,
                   end: Optional[bytes] = None
                   ) -> Iterator[Tuple[bytes, bytes]]:
        lo = fkey(family, start if start is not None else b"")
        hi = fkey(family, end) if end is not None else family_upper_bound(family)
        with self._lock:
            i = bisect.bisect_left(self._keys, lo)
            snap = []
            while i < len(self._keys):
                k = self._keys[i]
                if hi is not None and k >= hi:
                    break
                snap.append((k, self._map[k]))
                i += 1
        prefix = 1 + len(family)
        for k, v in snap:
            yield k[prefix:], v

    def scan_all(self):
        from tpubft.storage.interfaces import split_fkey
        with self._lock:
            snap = [(k, self._map[k]) for k in self._keys]
        for k, v in snap:
            fam, key = split_fkey(k)
            yield fam, key, v

    def close(self) -> None:
        pass
