"""Abstract key-value DB interface.

Rebuild of the reference's `concord::storage::IDBClient`
(/root/reference/storage/include/storage/db_interface.h:55): get / put /
del / multiGet / range iteration / atomic write batches, plus RocksDB-style
column families ("families" here). Families are encoded as a
length-prefixed key prefix so every backend gets them for free and range
scans stay contiguous per family.
"""
from __future__ import annotations

import abc
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

DEFAULT_FAMILY = b"default"


class StorageError(Exception):
    pass


def fkey(family: bytes, key: bytes) -> bytes:
    """Compose the physical key. Family names are <=255 bytes, so the
    1-byte length prefix keeps families disjoint and contiguous."""
    if len(family) > 255:
        raise StorageError("family name too long")
    return bytes([len(family)]) + family + key


def split_fkey(physical: bytes) -> Tuple[bytes, bytes]:
    n = physical[0]
    return physical[1:1 + n], physical[1 + n:]


def family_upper_bound(family: bytes) -> Optional[bytes]:
    """Smallest physical key strictly greater than every key in `family`
    (None = unbounded, i.e. family is the last possible)."""
    prefix = bytes([len(family)]) + family
    b = bytearray(prefix)
    for i in reversed(range(len(b))):
        if b[i] != 0xFF:
            b[i] += 1
            return bytes(b[:i + 1])
    return None


class WriteBatch:
    """Ordered, atomic batch of put/delete ops across families
    (reference: ITransaction / rocksdb::WriteBatch)."""

    def __init__(self) -> None:
        # (physical_key, value-or-None)
        self.ops: List[Tuple[bytes, Optional[bytes]]] = []

    def put(self, key: bytes, value: bytes,
            family: bytes = DEFAULT_FAMILY) -> "WriteBatch":
        self.ops.append((fkey(family, key), bytes(value)))
        return self

    def delete(self, key: bytes,
               family: bytes = DEFAULT_FAMILY) -> "WriteBatch":
        self.ops.append((fkey(family, key), None))
        return self

    def __len__(self) -> int:
        return len(self.ops)

    # Canonical wire encoding shared with the native engine (kvlog.cpp):
    # repeat{ u8 op(1=put,2=del) | u32le klen | key | [u32le vlen | val] }
    def encode(self) -> bytes:
        out = bytearray()
        for k, v in self.ops:
            if v is None:
                out += b"\x02" + len(k).to_bytes(4, "little") + k
            else:
                out += (b"\x01" + len(k).to_bytes(4, "little") + k
                        + len(v).to_bytes(4, "little") + v)
        return bytes(out)


class IDBClient(abc.ABC):
    """Abstract ordered KV store (db_interface.h:55)."""

    @abc.abstractmethod
    def get(self, key: bytes,
            family: bytes = DEFAULT_FAMILY) -> Optional[bytes]: ...

    @abc.abstractmethod
    def write(self, batch: WriteBatch) -> None: ...

    @abc.abstractmethod
    def range_iter(self, family: bytes = DEFAULT_FAMILY,
                   start: Optional[bytes] = None,
                   end: Optional[bytes] = None
                   ) -> Iterator[Tuple[bytes, bytes]]:
        """Iterate (key, value) for start <= key < end within a family."""

    @abc.abstractmethod
    def close(self) -> None: ...

    def sync(self) -> None:
        """Force everything written so far onto stable storage (the
        group-commit fsync seam — one call durably lands every batch
        applied since the previous sync). Backends without a durability
        boundary (memory stores) are a no-op; NativeDB overrides with a
        real fsync. Callers outside tpubft/durability/ are lint-banned
        (tools/tpulint fsync-seam pass): amortizing this call is the
        durability pipeline's whole job, and a stray per-write sync
        silently reintroduces the per-run disk tax."""

    def write_group(self, batches: Sequence[WriteBatch]) -> None:
        """Apply several batches as one group, in order (the durability
        pipeline's group-concatenation seam). The default preserves
        per-batch atomicity only; NativeDB overrides by concatenating
        the group into ONE engine record — one apply, one CRC, and (in
        sync_writes mode) one fsync for the whole group."""
        for b in batches:
            if b.ops:
                self.write(b)

    def scan_all(self) -> "Iterator[Tuple[bytes, bytes, bytes]]":
        """Iterate EVERY (family, key, value) in the store — the
        whole-state snapshot walk (reference: RocksDB checkpoint /
        state-snapshot streaming). Backends with a physical-order scan
        override this."""
        raise NotImplementedError

    # ---- conveniences built on the primitives ----
    def put(self, key: bytes, value: bytes,
            family: bytes = DEFAULT_FAMILY) -> None:
        self.write(WriteBatch().put(key, value, family))

    def delete(self, key: bytes, family: bytes = DEFAULT_FAMILY) -> None:
        self.write(WriteBatch().delete(key, family))

    def has(self, key: bytes, family: bytes = DEFAULT_FAMILY) -> bool:
        return self.get(key, family) is not None

    def multi_get(self, keys: Sequence[bytes],
                  family: bytes = DEFAULT_FAMILY) -> List[Optional[bytes]]:
        return [self.get(k, family) for k in keys]

    def last_in_range(self, family: bytes = DEFAULT_FAMILY,
                      start: Optional[bytes] = None,
                      end: Optional[bytes] = None
                      ) -> Optional[Tuple[bytes, bytes]]:
        out = None
        for kv in self.range_iter(family, start, end):
            out = kv
        return out

    def family_dict(self, family: bytes = DEFAULT_FAMILY
                    ) -> Dict[bytes, bytes]:
        return dict(self.range_iter(family))
