"""Reconfiguration dispatcher + the standard handler set.

Rebuild of /root/reference/reconfiguration/src/dispatcher.cpp: an ordered
RECONFIG request is authenticated (operator principal), decoded, and
offered to each registered IReconfigurationHandler in order; the first
handler claiming the command produces the reply. All of this happens
inside `_execute_committed`, i.e. at the same sequence point on every
replica — determinism comes from ordering, exactly like the reference.
"""
from __future__ import annotations

from typing import List, Optional

from tpubft.reconfiguration import messages as rm
from tpubft.utils import serialize as ser


def compute_stop_point(seq_num: int, cfg) -> int:
    """Deterministic wedge stop point that clears the in-flight ordering
    window: seqs up to last_stable + work_window may already be ordered,
    and last_stable <= seq_num at execution time — so seq_num +
    work_window (rounded up to a checkpoint boundary) is safely beyond
    anything in flight."""
    w = cfg.checkpoint_window_size
    floor = seq_num + cfg.work_window_size
    return ((floor // w) + 1) * w


class IReconfigurationHandler:
    """Handler chain element (reference IReconfigurationHandler)."""

    def handle(self, cmd, seq_num: int, replica) -> Optional[rm.ReconfigReply]:
        """Return a reply to claim the command, None to pass."""
        return None


class ReconfigurationDispatcher:
    def __init__(self) -> None:
        self._handlers: List[IReconfigurationHandler] = []

    def register(self, handler: IReconfigurationHandler) -> None:
        self._handlers.append(handler)

    # commands allowed on the unordered direct path: must be per-replica
    # idempotent and safe without consensus (unwedging a cluster that can
    # no longer order, and status reads)
    DIRECT_ALLOWED = (rm.UnwedgeCommand, rm.GetStatusCommand)

    def execute(self, replica, req, seq_num: int,
                direct: bool = False) -> bytes:
        """Called from the replica execution path for RECONFIG requests.
        The sender's signature was verified on admission AND in PrePrepare
        batch validation (client-sig checks); here we enforce the
        principal: everything requires the operator except the read-only
        status query, which any client may poll (the CRE's
        poll_based_state_client does exactly that in the reference)."""
        try:
            cmd = rm.unpack_command(req.request)
        except ser.SerializeError:
            return rm.pack_reply(rm.ReconfigReply(
                success=False, data="bad command"))
        if not isinstance(cmd, rm.GetStatusCommand) \
                and req.sender_id != replica.info.operator_id:
            return rm.pack_reply(rm.ReconfigReply(
                success=False, data="not the operator"))
        if direct and not isinstance(cmd, self.DIRECT_ALLOWED):
            # mutating commands on the unordered path would diverge state
            # (each replica would execute at its own height)
            return rm.pack_reply(rm.ReconfigReply(
                success=False, data="command requires ordering"))
        for handler in self._handlers:
            reply = handler.handle(cmd, seq_num, replica)
            if reply is not None:
                return rm.pack_reply(reply)
        return rm.pack_reply(rm.ReconfigReply(
            success=False, data="unhandled command"))


# ---------------- standard handlers ----------------

class WedgeHandler(IReconfigurationHandler):
    """WedgeCommand/UnwedgeCommand → ControlStateManager."""

    def handle(self, cmd, seq_num, replica):
        if isinstance(cmd, rm.WedgeCommand):
            stop = max(cmd.stop_seq, compute_stop_point(seq_num,
                                                        replica.cfg))
            replica.control.set_wedge_point(stop)
            return rm.ReconfigReply(success=True, data=str(stop))
        if isinstance(cmd, rm.UnwedgeCommand):
            replica.unwedge()       # control state + restart election
            return rm.ReconfigReply(success=True)
        return None


class KeyExchangeHandler(IReconfigurationHandler):
    def handle(self, cmd, seq_num, replica):
        if not isinstance(cmd, rm.KeyExchangeCommand):
            return None
        targets = cmd.targets or list(replica.info.replica_ids)
        if replica.id in targets:
            replica.key_exchange.initiate()
        return rm.ReconfigReply(success=True, data=str(sorted(targets)))


class RestartHandler(IReconfigurationHandler):
    """Marks restart-ready; the process wrapper/operator performs the
    actual restart once wedged (reference ReplicaRestartReady n/n flow)."""

    def handle(self, cmd, seq_num, replica):
        if not isinstance(cmd, rm.RestartCommand):
            return None
        # the restart boundary starts a new era: the bumped GLOBAL epoch
        # rides reserved pages; each replica adopts it when it comes back
        # up past the wedge (reference EpochManager startNewEpoch flow)
        effective = (replica.control.wedge_point
                     if replica.control.wedge_point is not None
                     else compute_stop_point(seq_num, replica.cfg))
        replica.epoch_mgr.bump_global_at(seq_num, effective)
        replica.control.mark_restart_ready()
        return rm.ReconfigReply(success=True)


class StatusHandler(IReconfigurationHandler):
    def handle(self, cmd, seq_num, replica):
        if not isinstance(cmd, rm.GetStatusCommand):
            return None
        return rm.ReconfigReply(success=True, data=replica.control.status())


class PruneHandler(IReconfigurationHandler):
    """Consensus-coordinated pruning over the categorized blockchain
    (reference kvbc pruning_handler.cpp). The effective prune point is
    clamped identically on every replica (ordered execution + same chain
    state), so genesis stays in agreement."""

    def __init__(self, blockchain) -> None:
        self._bc = blockchain

    def handle(self, cmd, seq_num, replica):
        if not isinstance(cmd, rm.PruneRequest):
            return None
        until = min(cmd.until_block, self._bc.last_block_id)
        try:
            genesis = self._bc.delete_blocks_until(until)
        except Exception as e:  # noqa: BLE001 — deterministic failure reply
            return rm.ReconfigReply(success=False, data=str(e))
        return rm.ReconfigReply(success=True, data=str(genesis))


class AddRemoveWithWedgeHandler(IReconfigurationHandler):
    """Records the new configuration descriptor in reserved pages (so it
    survives restart + state transfer) and wedges at the next checkpoint."""

    CATEGORY = "reconfig"

    def handle(self, cmd, seq_num, replica):
        if not isinstance(cmd, rm.AddRemoveWithWedgeCommand):
            return None
        replica.res_pages.save(self.CATEGORY, 0,
                               cmd.config_descriptor.encode())
        # new configuration = new era. Live replicas keep ordering in the
        # old epoch until the wedge point; whoever restarts into the new
        # config past it adopts the bumped global number from reserved
        # pages and rejects pre-epoch traffic (reference EpochManager).
        stop = compute_stop_point(seq_num, replica.cfg)
        replica.epoch_mgr.bump_global_at(seq_num, stop)
        replica.control.set_wedge_point(stop)
        return rm.ReconfigReply(success=True, data=str(stop))


class DbCheckpointHandler(IReconfigurationHandler):
    """Operator DB snapshots (reference DbCheckpointManager). Only DBs
    exposing `checkpoint_to` (the native engine) can snapshot; others
    report failure deterministically."""

    def __init__(self, db, directory: str) -> None:
        self._db = db
        self._dir = directory

    def handle(self, cmd, seq_num, replica):
        if not isinstance(cmd, rm.DbCheckpointCommand):
            return None
        import os
        import re
        if not re.fullmatch(r"[A-Za-z0-9._-]{1,64}", cmd.checkpoint_id):
            return rm.ReconfigReply(success=False, data="bad checkpoint id")
        fn = getattr(self._db, "checkpoint_to", None)
        if fn is None:
            return rm.ReconfigReply(success=False, data="unsupported db")
        os.makedirs(self._dir, exist_ok=True)
        path = os.path.join(self._dir, f"ckpt-{cmd.checkpoint_id}.kvlog")
        # snapshot off the dispatcher thread — a large DB serialized
        # inline would stall execution past the view-change timer (the
        # reference checkpoints RocksDB asynchronously too)
        import threading
        threading.Thread(target=lambda: self._try_checkpoint(fn, path),
                         daemon=True, name="db-checkpoint").start()
        # reply must be identical across replicas (client quorum matching),
        # so echo the id, not the per-replica path
        return rm.ReconfigReply(success=True, data=cmd.checkpoint_id)

    @staticmethod
    def _try_checkpoint(fn, path: str) -> None:
        try:
            fn(path)
        except Exception as e:  # noqa: BLE001 — async: report, don't crash
            import sys
            print(f"[tpubft] DB checkpoint to {path} FAILED: {e}",
                  file=sys.stderr, flush=True)


class KvbcRecorderHandler(IReconfigurationHandler):
    """Records ordered reconfiguration commands on-chain in an immutable
    category (reference reconfiguration_kvbc_handler.cpp) so clients and
    late joiners can observe the command history through normal reads /
    thin-replica streams. Never claims a command — the functional handler
    further down the chain produces the reply."""

    CATEGORY = "reconfig"

    def __init__(self, blockchain) -> None:
        self._bc = blockchain

    def handle(self, cmd, seq_num, replica):
        from tpubft.kvbc import IMMUTABLE, BlockUpdates
        if isinstance(cmd, (rm.GetStatusCommand, rm.UnwedgeCommand)):
            return None  # direct-path/read commands are not on-chain
        bu = BlockUpdates().put(
            self.CATEGORY, f"cmd-{seq_num}".encode(), rm.pack_command(cmd),
            cat_type=IMMUTABLE, tags=["reconfig"])
        # no exception swallowing: a replica whose chain diverges from the
        # ordered history must fail-stop, not keep running silently wrong
        self._bc.add_block(bu)
        return None


def standard_dispatcher(blockchain=None, db=None,
                        db_checkpoint_dir: str = "db_checkpoints"
                        ) -> ReconfigurationDispatcher:
    """The default handler chain (reference Dispatcher construction in
    kvbc Replica wiring)."""
    d = ReconfigurationDispatcher()
    if blockchain is not None:
        d.register(KvbcRecorderHandler(blockchain))
    d.register(WedgeHandler())
    d.register(KeyExchangeHandler())
    d.register(RestartHandler())
    d.register(StatusHandler())
    if blockchain is not None:
        d.register(PruneHandler(blockchain))
    if db is not None:
        d.register(DbCheckpointHandler(db, db_checkpoint_dir))
    d.register(AddRemoveWithWedgeHandler())
    return d
