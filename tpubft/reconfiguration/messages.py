"""Reconfiguration command set (reference: CMF ReconfigurationRequest
oneof in the reconfiguration .cmf definitions — WedgeCommand,
PruneRequest, KeyExchangeCommand, AddRemoveWithWedgeCommand,
RestartCommand, db_checkpoint_msg.cmf)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from tpubft.utils import serialize as ser


@dataclass
class WedgeCommand:
    """Stop ordering at the next checkpoint boundary (reference
    WedgeCommand → ControlStateManager stop point)."""
    ID = 1
    stop_seq: int = 0  # 0 = next checkpoint boundary after execution seq
    SPEC = [("stop_seq", "u64")]


@dataclass
class UnwedgeCommand:
    ID = 2
    SPEC = []


@dataclass
class PruneRequest:
    """Consensus-coordinated deletion of old blocks (kvbc pruning)."""
    ID = 3
    until_block: int = 0
    SPEC = [("until_block", "u64")]


@dataclass
class KeyExchangeCommand:
    """Ask target replicas to rotate their signing keys."""
    ID = 4
    targets: List[int] = field(default_factory=list)  # empty = all
    SPEC = [("targets", ("list", "u32"))]


@dataclass
class AddRemoveWithWedgeCommand:
    """Record a new cluster configuration and wedge; operators restart
    replicas with the new config (reference AddRemoveWithWedgeCommand)."""
    ID = 5
    config_descriptor: str = ""
    SPEC = [("config_descriptor", "str")]


@dataclass
class RestartCommand:
    """Signal replicas to restart once wedged (reference RestartCommand /
    ReplicaRestartReady flow)."""
    ID = 6
    SPEC = []


@dataclass
class DbCheckpointCommand:
    """Operator-triggered DB snapshot (reference DbCheckpointManager)."""
    ID = 7
    checkpoint_id: str = ""
    SPEC = [("checkpoint_id", "str")]


@dataclass
class GetStatusCommand:
    """Read-only status query (wedge state, genesis, last block)."""
    ID = 8
    SPEC = []


@dataclass
class ReconfigReply:
    success: bool = False
    data: str = ""
    SPEC = [("success", "bool"), ("data", "str")]


_TYPES = {cls.ID: cls for cls in
          (WedgeCommand, UnwedgeCommand, PruneRequest, KeyExchangeCommand,
           AddRemoveWithWedgeCommand, RestartCommand, DbCheckpointCommand,
           GetStatusCommand)}


def pack_command(cmd) -> bytes:
    return bytes([cmd.ID]) + ser.encode_msg(cmd)


def unpack_command(data: bytes):
    if not data or data[0] not in _TYPES:
        raise ser.SerializeError(f"unknown reconfig command {data[:1]!r}")
    return ser.decode_msg(data[1:], _TYPES[data[0]])


def pack_reply(reply: ReconfigReply) -> bytes:
    return ser.encode_msg(reply)


def unpack_reply(data: bytes) -> ReconfigReply:
    return ser.decode_msg(data, ReconfigReply)
