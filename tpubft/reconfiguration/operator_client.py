"""Operator client — submits reconfiguration commands through consensus
(reference: the operator tooling driving reconfiguration requests, e.g.
concord-ctl / apollo's operator helper)."""
from __future__ import annotations

from typing import Optional

from tpubft.consensus.messages import RequestFlag
from tpubft.reconfiguration import messages as rm


class OperatorClient:
    """Wraps a BftClient whose client_id is the operator principal."""

    def __init__(self, bft_client) -> None:
        self._client = bft_client

    def send(self, cmd, timeout_ms: Optional[int] = None,
             quorum=None) -> rm.ReconfigReply:
        from tpubft.bftclient.client import Quorum
        raw = self._client._send(rm.pack_command(cmd),
                                 flags=int(RequestFlag.RECONFIG),
                                 quorum=quorum or Quorum.LINEARIZABLE,
                                 timeout_ms=timeout_ms)
        return rm.unpack_reply(raw)

    def send_direct(self, cmd, timeout_ms: Optional[int] = None
                    ) -> rm.ReconfigReply:
        """Non-ordered operator command delivered to every replica
        directly (READ_ONLY|RECONFIG) — required for unwedge/status on a
        cluster that can no longer order requests."""
        from tpubft.bftclient.client import Quorum
        raw = self._client._send(
            rm.pack_command(cmd),
            flags=int(RequestFlag.RECONFIG) | int(RequestFlag.READ_ONLY),
            quorum=Quorum.ALL, timeout_ms=timeout_ms)
        return rm.unpack_reply(raw)

    # conveniences
    def wedge(self, stop_seq: int = 0, **kw) -> rm.ReconfigReply:
        return self.send(rm.WedgeCommand(stop_seq=stop_seq), **kw)

    def unwedge(self, timeout_ms: Optional[int] = None) -> rm.ReconfigReply:
        return self.send_direct(rm.UnwedgeCommand(), timeout_ms=timeout_ms)

    def prune(self, until_block: int, **kw) -> rm.ReconfigReply:
        return self.send(rm.PruneRequest(until_block=until_block), **kw)

    def key_exchange(self, targets=None, **kw) -> rm.ReconfigReply:
        return self.send(rm.KeyExchangeCommand(targets=targets or []), **kw)

    def db_checkpoint(self, checkpoint_id: str, **kw) -> rm.ReconfigReply:
        return self.send(rm.DbCheckpointCommand(
            checkpoint_id=checkpoint_id), **kw)

    def add_remove_with_wedge(self, config_descriptor: str,
                              **kw) -> rm.ReconfigReply:
        return self.send(rm.AddRemoveWithWedgeCommand(
            config_descriptor=config_descriptor), **kw)

    def status(self, **kw) -> rm.ReconfigReply:
        return self.send(rm.GetStatusCommand(), **kw)
