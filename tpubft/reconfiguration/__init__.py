"""Reconfiguration — operator-driven cluster control through consensus.

Rebuild of /root/reference/reconfiguration/ (dispatcher.cpp,
reconfiguration_handler.cpp) + the control plumbing it drives:
ControlStateManager/EpochManager wedging (include/bftengine/EpochManager.hpp),
consensus-coordinated pruning (kvbc pruning_handler.cpp), operator DB
checkpoints (DbCheckpointManager), targeted key exchange, and
add/remove-with-wedge scale changes.

Commands are ordered as RECONFIG-flagged client requests signed by the
operator principal; execution dispatches through a handler chain, so the
same command runs identically on every replica at the same sequence
point.
"""
from tpubft.reconfiguration.dispatcher import (IReconfigurationHandler,
                                               ReconfigurationDispatcher)
from tpubft.reconfiguration.messages import (AddRemoveWithWedgeCommand,
                                             DbCheckpointCommand,
                                             GetStatusCommand,
                                             KeyExchangeCommand,
                                             PruneRequest, ReconfigReply,
                                             RestartCommand, UnwedgeCommand,
                                             WedgeCommand, pack_command,
                                             unpack_command)
from tpubft.reconfiguration.operator_client import OperatorClient

__all__ = ["ReconfigurationDispatcher", "IReconfigurationHandler",
           "WedgeCommand", "UnwedgeCommand", "PruneRequest",
           "KeyExchangeCommand", "AddRemoveWithWedgeCommand",
           "DbCheckpointCommand", "RestartCommand", "GetStatusCommand",
           "ReconfigReply", "pack_command", "unpack_command",
           "OperatorClient"]
