"""Pre-execution — optimistic parallel execution before ordering.

Rebuild of /root/reference/bftengine/src/preprocessor/ (PreProcessor.hpp:126,
PreProcessor.cpp: sendPreProcessRequestToAllReplicas :1690,
launchAsyncReqPreProcessingJob :1008): a PRE_PROCESS-flagged client
request is speculatively executed on all replicas BEFORE ordering; the
primary collects f+1 matching signed result digests, then orders a
PreProcessResult wrapper (original request + result + signatures) instead
of the raw request. At commit, the handler applies the pre-executed
result with conflict detection — execution cost is off the ordering
critical path.

Speculative execution runs on a thread pool (the reference's preprocessor
pool); all protocol state lives on the consensus dispatcher thread, with
completions re-entering through the internal message queue.
"""
from tpubft.preprocessor.preprocessor import PreProcessor

__all__ = ["PreProcessor"]
