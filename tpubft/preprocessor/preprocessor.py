"""PreProcessor protocol logic. See package docstring."""
from __future__ import annotations

import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from tpubft.consensus import messages as m
from tpubft.utils import flight
from tpubft.utils import serialize as ser


@dataclass
class _Session:
    """Primary-side state for one in-flight pre-execution
    (reference RequestProcessingState)."""
    original: m.ClientRequestMsg
    retry_id: int
    started: float
    last_broadcast: float = 0.0
    my_result: Optional[bytes] = None
    # replica -> (digest, sig) of agreeing replies
    replies: Dict[int, Tuple[bytes, bytes]] = field(default_factory=dict)
    done: bool = False


class PreProcessor:
    """Attached to a Replica when cfg.pre_execution_enabled. All methods
    except the pool callbacks run on the dispatcher thread."""

    SESSION_TIMEOUT_S = 10.0

    def __init__(self, replica, num_threads: int = 4) -> None:
        self.replica = replica
        self._pool = ThreadPoolExecutor(max_workers=num_threads,
                                        thread_name_prefix="preexec")
        self._sessions: Dict[Tuple[int, int], _Session] = {}
        # backup-side reply cache: (client, req_seq, retry_id) -> packed
        # PreProcessReplyMsg — rebroadcasts must not re-execute the app.
        # Bounded LRU (the SigManager verify-memo discipline): real
        # client traffic over millions of principals must not grow it
        # without bound; hits refresh recency, inserts evict the oldest.
        self._reply_cache: "OrderedDict[Tuple[int, int, int], bytes]" = \
            OrderedDict()
        self._reply_cache_max = max(
            1, getattr(replica.cfg, "preexec_reply_cache_max", 512))
        # metrics ride the replica's `preexec` component (conflict /
        # apply counters already live there, ticked by the exec path)
        comp = replica.preexec_metrics
        self.m_sessions = comp.register_counter("preexec_sessions")
        self.m_agreed = comp.register_counter("preexec_agreed")
        self.m_fallbacks = comp.register_counter("preexec_fallbacks")
        self.m_cache_hits = comp.register_counter(
            "preexec_reply_cache_hits")
        self.m_cache_evictions = comp.register_counter(
            "preexec_reply_cache_evictions")
        self._retry_counter = 0
        # primary-side broadcast micro-batching: sessions created while
        # one external message is being handled (e.g. the elements of a
        # ClientBatchRequestMsg) group into ONE PreProcessBatchRequestMsg
        # per client, flushed via the internal queue (which drains only
        # after the current external message completes)
        self._pending_broadcast: list = []
        self._batch_counter = 0
        # backup-side reply folding: (primary, batch_id) -> group state
        self._reply_groups: Dict[Tuple[int, int], dict] = {}
        replica.dispatcher.register_internal("preexec", self._on_internal)
        replica.dispatcher.add_timer(1.0, self._expire_sessions)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)

    # ---- bounded reply cache (dispatcher-thread only) ----
    def _cache_get(self, key: Tuple[int, int, int]) -> Optional[bytes]:
        raw = self._reply_cache.get(key)
        if raw is not None:
            self._reply_cache.move_to_end(key)
            self.m_cache_hits.inc()
        return raw

    def _cache_put(self, key: Tuple[int, int, int], raw: bytes) -> None:
        self._reply_cache[key] = raw
        self._reply_cache.move_to_end(key)
        while len(self._reply_cache) > self._reply_cache_max:
            self._reply_cache.popitem(last=False)
            self.m_cache_evictions.inc()

    # ------------------------------------------------------------------
    # primary side
    # ------------------------------------------------------------------
    REBROADCAST_PERIOD_S = 1.0

    def on_client_request(self, req: m.ClientRequestMsg) -> None:
        """Primary receives a PRE_PROCESS request
        (onClientPreProcessRequestMsg)."""
        key = (req.sender_id, req.req_seq_num)
        sess = self._sessions.get(key)
        if sess is not None:
            # client retransmission: the original broadcast may have been
            # lost — re-send the PreProcessRequest (bounded rate) so a
            # stuck session can still reach its reply quorum
            now = time.monotonic()
            if now - sess.last_broadcast >= self.REBROADCAST_PERIOD_S:
                sess.last_broadcast = now
                self._broadcast_request(sess)
            return
        if not self.replica.clients.can_become_pending(*key):
            return
        self._retry_counter += 1
        sess = _Session(original=req, retry_id=self._retry_counter,
                        started=time.monotonic(),
                        last_broadcast=time.monotonic())
        self._sessions[key] = sess
        self.m_sessions.inc()
        # defer the broadcast to the flush point: sessions created while
        # this dispatcher turn runs (a client batch admits its elements
        # in one loop) ship as ONE grouped wire message per client
        if not self._pending_broadcast:
            self.replica.incoming.push_internal(
                "preexec", ("flush", None, 0, False, None, None))
        self._pending_broadcast.append(sess)
        self._launch(req, sess.retry_id, primary=True)

    def _packed_request(self, sess: _Session) -> bytes:
        return m.PreProcessRequestMsg(
            sender_id=self.replica.id, client_id=sess.original.sender_id,
            req_seq_num=sess.original.req_seq_num, retry_id=sess.retry_id,
            request=sess.original.pack()).pack()

    def _broadcast_request(self, sess: _Session) -> None:
        raw = self._packed_request(sess)
        for r in self.replica.info.other_replicas(self.replica.id):
            self.replica.comm.send(r, raw)

    def _flush_broadcasts(self) -> None:
        """Group pending sessions per client into PreProcessBatchRequestMsg
        (singletons go out as plain PreProcessRequestMsg)."""
        pending, self._pending_broadcast = self._pending_broadcast, []
        by_client: Dict[int, list] = {}
        for sess in pending:
            if sess.done:
                continue
            by_client.setdefault(sess.original.sender_id, []).append(sess)
        cap = m.ClientBatchRequestMsg.MAX_BATCH
        for client, group in by_client.items():
            if len(group) == 1:
                self._broadcast_request(group[0])
                continue
            for i in range(0, len(group), cap):
                chunk = group[i:i + cap]
                self._batch_counter += 1
                msg = m.PreProcessBatchRequestMsg(
                    sender_id=self.replica.id, client_id=client,
                    batch_id=self._batch_counter,
                    requests=[self._packed_request(s) for s in chunk])
                raw = msg.pack()
                for r in self.replica.info.other_replicas(self.replica.id):
                    self.replica.comm.send(r, raw)

    def _launch(self, req: m.ClientRequestMsg, retry_id: int,
                primary: bool, reply_to: Optional[int] = None,
                group: Optional[Tuple[int, int]] = None) -> None:
        """Run handler.pre_execute on the pool; result re-enters the
        dispatcher as an internal msg (launchAsyncReqPreProcessingJob)."""
        handler = self.replica.handler
        flight.record(flight.EV_PREEXEC_LAUNCH, seq=req.req_seq_num,
                      arg=retry_id)

        def job():
            try:
                result = handler.pre_execute(req.sender_id, req.req_seq_num,
                                             req.request)
            except Exception:
                result = None
            self.replica.incoming.push_internal(
                "preexec", ("done", req, retry_id, primary, reply_to,
                            result, group))
        self._pool.submit(job)

    def _on_internal(self, item) -> None:
        kind, req, retry_id, primary, reply_to, result = item[:6]
        group = item[6] if len(item) > 6 else None
        if kind == "flush":
            self._flush_broadcasts()
            return
        key = (req.sender_id, req.req_seq_num)
        if primary:
            sess = self._sessions.get(key)
            if sess is None or sess.retry_id != retry_id or sess.done:
                return
            if result is None:
                # unsupported/failed: fall back to normal ordering with
                # the request untouched (flags are client-signed)
                sess.done = True
                del self._sessions[key]
                self.m_fallbacks.inc()
                self.replica._admit_request(req)
                return
            sess.my_result = result
            digest = m.preexec_digest(key[0], key[1], req.pack(), result)
            sig = self.replica.sig.sign(digest)
            sess.replies[self.replica.id] = (digest, sig)
            self._maybe_finish(key)
        else:
            # backup: sign our digest and reply to the primary
            if result is None:
                status, digest, sig = 1, b"", b""
            else:
                digest = m.preexec_digest(key[0], key[1], req.pack(), result)
                sig = self.replica.sig.sign(digest)
                status = 0
            reply = m.PreProcessReplyMsg(
                sender_id=self.replica.id, client_id=key[0],
                req_seq_num=key[1], retry_id=retry_id,
                result_digest=digest, status=status, signature=sig)
            raw = reply.pack()
            self._cache_put((key[0], key[1], retry_id), raw)
            if group is not None:
                self._fold_group_reply(group, raw, reply_to)
            else:
                self.replica.comm.send(reply_to, raw)

    def _send_group_reply(self, batch_id: int, st: dict) -> None:
        msg = m.PreProcessBatchReplyMsg(
            sender_id=self.replica.id, client_id=st["client"],
            batch_id=batch_id, replies=st["got"])
        self.replica.comm.send(st["reply_to"], msg.pack())

    def _fold_group_reply(self, group: Tuple[int, int], raw_reply: bytes,
                          reply_to: Optional[int]) -> None:
        """Collect a batch element's reply; when the whole group is in,
        send ONE PreProcessBatchReplyMsg to the primary."""
        st = self._reply_groups.get(group)
        if st is None:
            # group expired (a slow sibling element) — the reply is still
            # wanted: fall back to a direct single send so the primary's
            # session can complete its quorum
            if reply_to is not None:
                self.replica.comm.send(reply_to, raw_reply)
            return
        st["got"].append(raw_reply)
        if len(st["got"]) >= st["expect"]:
            del self._reply_groups[group]
            self._send_group_reply(group[1], st)

    # ------------------------------------------------------------------
    # backup side
    # ------------------------------------------------------------------
    def _element_request(self, msg: m.PreProcessRequestMsg
                         ) -> Optional[m.ClientRequestMsg]:
        """Shared element validation for single + batched requests."""
        try:
            req = m.unpack(msg.request)
        except m.MsgError:
            return None
        if not isinstance(req, m.ClientRequestMsg) \
                or req.sender_id != msg.client_id \
                or req.req_seq_num != msg.req_seq_num:
            return None
        if not self.replica.sig.verify(req.sender_id, req.signed_payload(),
                                       req.signature):
            return None
        return req

    def on_preprocess_request(self, sender: int,
                              msg: m.PreProcessRequestMsg) -> None:
        if sender != self.replica.primary:
            return
        cached = self._cache_get((msg.client_id, msg.req_seq_num,
                                  msg.retry_id))
        if cached is not None:
            self.replica.comm.send(sender, cached)
            return
        req = self._element_request(msg)
        if req is None:
            return
        self._launch(req, msg.retry_id, primary=False, reply_to=sender)

    def on_preprocess_batch_request(self, sender: int,
                                    msg: m.PreProcessBatchRequestMsg) -> None:
        """A grouped preprocess request: launch every valid element, fold
        all replies into one PreProcessBatchReplyMsg (reference
        PreProcessBatchRequestMsg handling)."""
        if sender != self.replica.primary:
            return
        elements = []
        for raw in msg.requests:
            try:
                ppr = m.unpack(raw)
            except m.MsgError:
                return
            if not isinstance(ppr, m.PreProcessRequestMsg) \
                    or ppr.client_id != msg.client_id:
                return                  # malformed group: drop whole
            elements.append(ppr)
        group = (sender, msg.batch_id)
        if group in self._reply_groups:
            return                      # duplicate batch delivery
        cached_raws, todo = [], []
        for ppr in elements:
            cached = self._cache_get((ppr.client_id, ppr.req_seq_num,
                                      ppr.retry_id))
            if cached is not None:
                cached_raws.append(cached)
                continue
            req = self._element_request(ppr)
            if req is not None:
                todo.append((req, ppr.retry_id))
            # invalid elements simply produce no reply: the primary's
            # per-element session rebroadcast covers the gap
        if not cached_raws and not todo:
            return
        st = {"expect": len(cached_raws) + len(todo),
              "got": list(cached_raws), "reply_to": sender,
              "client": msg.client_id, "started": time.monotonic()}
        if not todo:
            # everything cached: fold-and-send immediately
            self._send_group_reply(msg.batch_id, st)
            return
        self._reply_groups[group] = st
        for req, retry_id in todo:
            self._launch(req, retry_id, primary=False, reply_to=sender,
                         group=group)

    def on_preprocess_batch_reply(self, sender: int,
                                  msg: m.PreProcessBatchReplyMsg) -> None:
        """Primary unfolds a grouped reply into per-element handling."""
        for raw in msg.replies:
            try:
                rep = m.unpack(raw)
            except m.MsgError:
                return
            if not isinstance(rep, m.PreProcessReplyMsg) \
                    or rep.sender_id != sender \
                    or rep.client_id != msg.client_id:
                return
            self.on_preprocess_reply(sender, rep)

    def on_preprocess_reply(self, sender: int,
                            msg: m.PreProcessReplyMsg) -> None:
        key = (msg.client_id, msg.req_seq_num)
        sess = self._sessions.get(key)
        if sess is None or sess.retry_id != msg.retry_id or sess.done:
            return
        if msg.status != 0:
            return
        if not self.replica.sig.verify(sender, msg.result_digest,
                                       msg.signature):
            return
        sess.replies[sender] = (msg.result_digest, msg.signature)
        self._maybe_finish(key)

    # ------------------------------------------------------------------
    def _maybe_finish(self, key) -> None:
        """f+1 matching digests (incl. our own) → order the result
        (reference: RequestProcessingState::definePreProcessingConsensusResult)."""
        sess = self._sessions.get(key)
        if sess is None or sess.my_result is None or sess.done:
            return
        my_digest = sess.replies.get(self.replica.id, (None, None))[0]
        agreeing = [(r, sig) for r, (d, sig) in sess.replies.items()
                    if d == my_digest]
        quorum = self.replica.info.f + 1
        if len(agreeing) < quorum:
            return
        sess.done = True
        del self._sessions[key]
        self.m_agreed.inc()
        flight.record(flight.EV_PREEXEC_AGREE, seq=key[1],
                      arg=len(agreeing))
        envelope = m.PreProcessResult(
            original=sess.original.pack(), result=sess.my_result,
            signatures=sorted(agreeing)[:quorum])
        wrapper = m.ClientRequestMsg(
            sender_id=sess.original.sender_id,
            req_seq_num=sess.original.req_seq_num,
            flags=(sess.original.flags
                   & ~int(m.RequestFlag.PRE_PROCESS))
            | int(m.RequestFlag.HAS_PRE_PROCESSED),
            request=ser.encode_msg(envelope),
            cid=sess.original.cid, signature=b"")
        self.replica._admit_request(wrapper)

    def _expire_sessions(self) -> None:
        now = time.monotonic()
        for key in [k for k, s in self._sessions.items()
                    if now - s.started > self.SESSION_TIMEOUT_S]:
            del self._sessions[key]
        # a reply group whose elements never all complete (handler wedge)
        # must not leak — and its partial replies are still useful, so
        # flush what arrived before dropping
        for g in [g for g, st in self._reply_groups.items()
                  if now - st["started"] > self.SESSION_TIMEOUT_S]:
            st = self._reply_groups.pop(g)
            if st["got"]:
                self._send_group_reply(g[1], st)


def validate_preprocessed_request(replica, req: m.ClientRequestMsg) -> bool:
    """Validation of an ordered PreProcessResult wrapper, used by backups
    inside PrePrepare batch validation (reference
    PreProcessResultMsg::validatePreProcessResultSignatures): the embedded
    original must carry a valid client signature, and f+1 distinct
    replicas must have signed the (request, result) binding."""
    try:
        env = ser.decode_msg(req.request, m.PreProcessResult)
        orig = m.unpack(env.original)
    except Exception:
        return False
    if not isinstance(orig, m.ClientRequestMsg):
        return False
    if orig.sender_id != req.sender_id \
            or orig.req_seq_num != req.req_seq_num:
        return False
    if not orig.flags & m.RequestFlag.PRE_PROCESS:
        return False
    if not replica.sig.verify(orig.sender_id, orig.signed_payload(),
                              orig.signature):
        return False
    digest = m.preexec_digest(orig.sender_id, orig.req_seq_num,
                              env.original, env.result)
    seen = set()
    for replica_id, sig in env.signatures:
        if replica_id in seen or not replica.info.is_replica(replica_id):
            continue
        if replica.sig.verify(replica_id, digest, sig):
            seen.add(replica_id)
    return len(seen) >= replica.info.f + 1


def unpack_preprocessed(request: bytes):
    """-> (original ClientRequestMsg, result bytes)."""
    env = ser.decode_msg(request, m.PreProcessResult)
    return m.unpack(env.original), env.result
