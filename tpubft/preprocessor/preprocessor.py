"""PreProcessor protocol logic. See package docstring."""
from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from tpubft.consensus import messages as m
from tpubft.utils import serialize as ser


@dataclass
class _Session:
    """Primary-side state for one in-flight pre-execution
    (reference RequestProcessingState)."""
    original: m.ClientRequestMsg
    retry_id: int
    started: float
    last_broadcast: float = 0.0
    my_result: Optional[bytes] = None
    # replica -> (digest, sig) of agreeing replies
    replies: Dict[int, Tuple[bytes, bytes]] = field(default_factory=dict)
    done: bool = False


class PreProcessor:
    """Attached to a Replica when cfg.pre_execution_enabled. All methods
    except the pool callbacks run on the dispatcher thread."""

    SESSION_TIMEOUT_S = 10.0

    def __init__(self, replica, num_threads: int = 4) -> None:
        self.replica = replica
        self._pool = ThreadPoolExecutor(max_workers=num_threads,
                                        thread_name_prefix="preexec")
        self._sessions: Dict[Tuple[int, int], _Session] = {}
        # backup-side reply cache: (client, req_seq, retry_id) -> packed
        # PreProcessReplyMsg — rebroadcasts must not re-execute the app
        self._reply_cache: Dict[Tuple[int, int, int], bytes] = {}
        self._retry_counter = 0
        replica.dispatcher.register_internal("preexec", self._on_internal)
        replica.dispatcher.add_timer(1.0, self._expire_sessions)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)

    # ------------------------------------------------------------------
    # primary side
    # ------------------------------------------------------------------
    REBROADCAST_PERIOD_S = 1.0

    def on_client_request(self, req: m.ClientRequestMsg) -> None:
        """Primary receives a PRE_PROCESS request
        (onClientPreProcessRequestMsg)."""
        key = (req.sender_id, req.req_seq_num)
        sess = self._sessions.get(key)
        if sess is not None:
            # client retransmission: the original broadcast may have been
            # lost — re-send the PreProcessRequest (bounded rate) so a
            # stuck session can still reach its reply quorum
            now = time.monotonic()
            if now - sess.last_broadcast >= self.REBROADCAST_PERIOD_S:
                sess.last_broadcast = now
                self._broadcast_request(sess)
            return
        if not self.replica.clients.can_become_pending(*key):
            return
        self._retry_counter += 1
        sess = _Session(original=req, retry_id=self._retry_counter,
                        started=time.monotonic(),
                        last_broadcast=time.monotonic())
        self._sessions[key] = sess
        self._broadcast_request(sess)
        self._launch(req, sess.retry_id, primary=True)

    def _broadcast_request(self, sess: _Session) -> None:
        ppr = m.PreProcessRequestMsg(
            sender_id=self.replica.id, client_id=sess.original.sender_id,
            req_seq_num=sess.original.req_seq_num, retry_id=sess.retry_id,
            request=sess.original.pack())
        for r in self.replica.info.other_replicas(self.replica.id):
            self.replica.comm.send(r, ppr.pack())

    def _launch(self, req: m.ClientRequestMsg, retry_id: int,
                primary: bool, reply_to: Optional[int] = None) -> None:
        """Run handler.pre_execute on the pool; result re-enters the
        dispatcher as an internal msg (launchAsyncReqPreProcessingJob)."""
        handler = self.replica.handler

        def job():
            try:
                result = handler.pre_execute(req.sender_id, req.req_seq_num,
                                             req.request)
            except Exception:
                result = None
            self.replica.incoming.push_internal(
                "preexec", ("done", req, retry_id, primary, reply_to,
                            result))
        self._pool.submit(job)

    def _on_internal(self, item) -> None:
        kind, req, retry_id, primary, reply_to, result = item
        key = (req.sender_id, req.req_seq_num)
        if primary:
            sess = self._sessions.get(key)
            if sess is None or sess.retry_id != retry_id or sess.done:
                return
            if result is None:
                # unsupported/failed: fall back to normal ordering with
                # the request untouched (flags are client-signed)
                sess.done = True
                del self._sessions[key]
                self.replica._admit_request(req)
                return
            sess.my_result = result
            digest = m.preexec_digest(key[0], key[1], req.pack(), result)
            sig = self.replica.sig.sign(digest)
            sess.replies[self.replica.id] = (digest, sig)
            self._maybe_finish(key)
        else:
            # backup: sign our digest and reply to the primary
            if result is None:
                status, digest, sig = 1, b"", b""
            else:
                digest = m.preexec_digest(key[0], key[1], req.pack(), result)
                sig = self.replica.sig.sign(digest)
                status = 0
            reply = m.PreProcessReplyMsg(
                sender_id=self.replica.id, client_id=key[0],
                req_seq_num=key[1], retry_id=retry_id,
                result_digest=digest, status=status, signature=sig)
            raw = reply.pack()
            self._reply_cache[(key[0], key[1], retry_id)] = raw
            if len(self._reply_cache) > 512:
                self._reply_cache.pop(next(iter(self._reply_cache)))
            self.replica.comm.send(reply_to, raw)

    # ------------------------------------------------------------------
    # backup side
    # ------------------------------------------------------------------
    def on_preprocess_request(self, sender: int,
                              msg: m.PreProcessRequestMsg) -> None:
        if sender != self.replica.primary:
            return
        cached = self._reply_cache.get((msg.client_id, msg.req_seq_num,
                                        msg.retry_id))
        if cached is not None:
            self.replica.comm.send(sender, cached)
            return
        try:
            req = m.unpack(msg.request)
        except m.MsgError:
            return
        if not isinstance(req, m.ClientRequestMsg) \
                or req.sender_id != msg.client_id \
                or req.req_seq_num != msg.req_seq_num:
            return
        if not self.replica.sig.verify(req.sender_id, req.signed_payload(),
                                       req.signature):
            return
        self._launch(req, msg.retry_id, primary=False, reply_to=sender)

    def on_preprocess_reply(self, sender: int,
                            msg: m.PreProcessReplyMsg) -> None:
        key = (msg.client_id, msg.req_seq_num)
        sess = self._sessions.get(key)
        if sess is None or sess.retry_id != msg.retry_id or sess.done:
            return
        if msg.status != 0:
            return
        if not self.replica.sig.verify(sender, msg.result_digest,
                                       msg.signature):
            return
        sess.replies[sender] = (msg.result_digest, msg.signature)
        self._maybe_finish(key)

    # ------------------------------------------------------------------
    def _maybe_finish(self, key) -> None:
        """f+1 matching digests (incl. our own) → order the result
        (reference: RequestProcessingState::definePreProcessingConsensusResult)."""
        sess = self._sessions.get(key)
        if sess is None or sess.my_result is None or sess.done:
            return
        my_digest = sess.replies.get(self.replica.id, (None, None))[0]
        agreeing = [(r, sig) for r, (d, sig) in sess.replies.items()
                    if d == my_digest]
        quorum = self.replica.info.f + 1
        if len(agreeing) < quorum:
            return
        sess.done = True
        del self._sessions[key]
        envelope = m.PreProcessResult(
            original=sess.original.pack(), result=sess.my_result,
            signatures=sorted(agreeing)[:quorum])
        wrapper = m.ClientRequestMsg(
            sender_id=sess.original.sender_id,
            req_seq_num=sess.original.req_seq_num,
            flags=(sess.original.flags
                   & ~int(m.RequestFlag.PRE_PROCESS))
            | int(m.RequestFlag.HAS_PRE_PROCESSED),
            request=ser.encode_msg(envelope),
            cid=sess.original.cid, signature=b"")
        self.replica._admit_request(wrapper)

    def _expire_sessions(self) -> None:
        now = time.monotonic()
        for key in [k for k, s in self._sessions.items()
                    if now - s.started > self.SESSION_TIMEOUT_S]:
            del self._sessions[key]


def validate_preprocessed_request(replica, req: m.ClientRequestMsg) -> bool:
    """Validation of an ordered PreProcessResult wrapper, used by backups
    inside PrePrepare batch validation (reference
    PreProcessResultMsg::validatePreProcessResultSignatures): the embedded
    original must carry a valid client signature, and f+1 distinct
    replicas must have signed the (request, result) binding."""
    try:
        env = ser.decode_msg(req.request, m.PreProcessResult)
        orig = m.unpack(env.original)
    except Exception:
        return False
    if not isinstance(orig, m.ClientRequestMsg):
        return False
    if orig.sender_id != req.sender_id \
            or orig.req_seq_num != req.req_seq_num:
        return False
    if not orig.flags & m.RequestFlag.PRE_PROCESS:
        return False
    if not replica.sig.verify(orig.sender_id, orig.signed_payload(),
                              orig.signature):
        return False
    digest = m.preexec_digest(orig.sender_id, orig.req_seq_num,
                              env.original, env.result)
    seen = set()
    for replica_id, sig in env.signatures:
        if replica_id in seen or not replica.info.is_replica(replica_id):
            continue
        if replica.sig.verify(replica_id, digest, sig):
            seen.add(replica_id)
    return len(seen) >= replica.info.f + 1


def unpack_preprocessed(request: bytes):
    """-> (original ClientRequestMsg, result bytes)."""
    env = ser.decode_msg(request, m.PreProcessResult)
    return m.unpack(env.original), env.result
