"""Consensus flight recorder — always-on, low-overhead slot telemetry.

The reference ships per-stage histograms (diagnostics.h /
performance_handler.h) and span contexts riding every message; our
spans can say *that* a slot was slow but not *where*. This module is
the missing substrate: every hot seam emits a fixed-size event

    (monotonic_ns, event_code, seq, view, arg)

into a bounded ring owned by the EMITTING thread — the ring write
itself takes no lock, no formatting, no allocation beyond one tuple —
so the recorder can stay on in production and its tail is always
available when something goes wrong (an aircraft flight recorder, not
a profiler you remember to attach after the crash). The ~8
slot-lifecycle events per consensus SLOT (not per message) additionally
fold through the shared SlotTracker under its lock: contention there is
bounded by slot rate, which is orders of magnitude below message rate.

Three consumers fold the rings:

  * ``SlotTracker`` — folds slot-stage events into per-seq timings
    (adm_wait / dispatch / prepare / commit / exec / reply), feeding
    the diagnostics histograms (``slot.<stage>``) and
    ``status get slots``;
  * ``KernelProfiler`` — per-kernel call count, batch-size stats, wall
    time and the first-call compile-warmup split, recorded by
    ``ops.dispatch.device_section`` and served as
    ``status get kernels``;
  * the dump plane — ``status get flight`` on demand, plus
    ``dump(reason)`` JSON artifacts (rings + kernel profile + slot
    summary + lock hold stats) written automatically on every
    stalled/degraded health transition (consensus/health.py) and on
    chaos-campaign red verdicts (testing/campaign.py); offline,
    ``tools/tpuprof.py`` merges per-replica dumps into a slot timeline.

Knobs (environment — read once at import, like TPUBFT_THREADCHECK):

  * ``TPUBFT_FLIGHT=0``      compiles the recorder out: ``record``
    becomes a bound no-op, every seam pays one global lookup + call;
  * ``TPUBFT_FLIGHT_RING``   events kept per thread (default 4096);
  * ``TPUBFT_FLIGHT_DIR``    dump-artifact directory (default
    ``<tmp>/tpubft-flight``).

Thread identity: rings carry the emitting thread's name as its role
plus a replica id seeded by ``set_thread_rid`` (the dispatcher,
execution lane, and admission workers seed theirs at loop entry), so
multi-replica processes (the in-process test cluster) stay separable.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

from tpubft.utils.racecheck import make_lock

# ---------------------------------------------------------------------
# event catalog (docs/OPERATIONS.md "Telemetry, flight recorder &
# profiling" mirrors this table — update both)
# ---------------------------------------------------------------------
EV_ADM_INGEST = 1       # admission ingest (transport thread; arg=burst)
EV_ADM_DRAIN = 2        # admission drain cycle begins (arg=batch size)
EV_ADM_ADMIT = 3        # PrePrepare admitted to the dispatcher queue
EV_DISPATCH = 4         # dispatcher handler entry (arg=msg code)
EV_CLIENT_REQ = 5       # client request reached the dispatcher
EV_PP_DISPATCH = 6      # PrePrepare handler entry (dispatcher)
EV_PP_ACCEPT = 7        # PrePrepare accepted into the window
EV_PREPARED = 8         # prepare quorum (PrepareFull accepted)
EV_COMMITTED = 9        # commit quorum (arg: 0=slow, 1=fast)
EV_EXEC_ENQ = 10        # committed slot handed to the execution lane
EV_EXEC_APPLY = 11      # durable apply (lane thread; arg=run length)
EV_REPLY = 12           # slot integrated + replies sent (dispatcher)
EV_DEV_ENTER = 13       # device_section entry (view=kind id, arg=batch)
EV_DEV_EXIT = 14        # device_section exit (view=kind id, arg=us)
EV_HEALTH = 15          # health verdict transition (arg=verdict id)
EV_SPEC_ENQ = 16        # slot handed to the lane SPECULATIVELY
EV_SPEC_SEAL = 17       # speculative run sealed at commit (arg=run len)
EV_SPEC_ABORT = 18      # speculation aborted; slot re-executes committed
EV_COMBINE_FLUSH = 19   # fused combine flush (batcher; arg=slots drained)
# thin-replica read tier (serving-plane events; seq carries a BLOCK id,
# not a consensus seqnum — the read path has no slot)
EV_TRS_SUBSCRIBE = 20   # subscription accepted (seq=start block)
EV_TRS_PUSH = 21        # sealed run published to subscribers
#                         (seq=last block of the run; arg=blocks in run)
EV_TRS_PROOF = 22       # merkle proof served (seq=block; arg=category id)
# pre-execution plane (seq carries the client req_seq_num)
EV_PREEXEC_LAUNCH = 23  # speculative execution launched (arg=retry id)
EV_PREEXEC_AGREE = 24   # f+1 digest agreement reached (arg=votes)
EV_PREEXEC_CONFLICT = 25  # read-set conflict at commit; fell back to
#                           normal ordering (seq=consensus slot)
EV_TUNE = 26            # autotuner knob change (seq=knob id,
#                         view=old value, arg=new value; the knob-id →
#                         name table rides every dump via the tuning
#                         dump provider)
EV_DUR_GROUP = 27       # durability group committed (io thread;
#                         seq=new watermark, arg=runs in the group —
#                         one event per group fsync)
EV_AGG_FORWARD = 28     # aggregation overlay: interior node flushed a
#                         partial aggregate to its parent (dispatcher;
#                         seq/view=slot, arg=contributor count)
EV_AGG_ROOT = 29        # aggregation overlay: root absorbed a partial
#                         into the slot's ShareCollector (dispatcher;
#                         arg=contributor count)
EV_AGG_FALLBACK = 30    # aggregation overlay: parent timeout fired —
#                         share re-sent DIRECT to the collector
#                         (dispatcher; arg=share kind 0=prep/1=commit)
# optimistic reply plane (ReplicaConfig.optimistic_replies)
EV_OPT_REPLY = 31       # slot released to the reply pipeline on a
#                         structurally-bound commit cert BEFORE its
#                         pairing check (dispatcher; arg=0 slow/1 fast)
EV_CERT_ASYNC_DONE = 32  # deferred combined-cert check landed for an
#                          optimistically-released slot (dispatcher)
EV_CERT_ASYNC_LAG = 33  # lag sample for the deferred combine tail:
#                         optimistic release -> verified cert
#                         (dispatcher; arg=lag in µs — feeds the
#                         slot.cert_lag overlay stage)
# verified crypto-offload tier (tpubft/offload/ — helpers are
# non-voting and never trusted; every event rides the leasing thread)
EV_OFF_LEASE = 34       # lease issued to a helper (arg=items in the
#                         lease, view=kind id)
EV_OFF_VERIFIED = 35    # helper result passed the on-replica 2G2T
#                         soundness check (arg=soundness-check µs)
EV_OFF_REJECTED = 36    # helper result FAILED the soundness check or
#                         arrived malformed/stale — the lease re-ran
#                         locally (arg=helper ordinal)
EV_OFF_EVICT = 37       # helper evicted (arg: 0=sick/timeout,
#                         1=byzantine quarantine — no auto re-admission)

EV_NAMES = {
    EV_ADM_INGEST: "adm_ingest", EV_ADM_DRAIN: "adm_drain",
    EV_ADM_ADMIT: "adm_admit", EV_DISPATCH: "dispatch",
    EV_CLIENT_REQ: "client_req", EV_PP_DISPATCH: "pp_dispatch",
    EV_PP_ACCEPT: "pp_accept", EV_PREPARED: "prepared",
    EV_COMMITTED: "committed", EV_EXEC_ENQ: "exec_enq",
    EV_EXEC_APPLY: "exec_apply", EV_REPLY: "reply",
    EV_DEV_ENTER: "dev_enter", EV_DEV_EXIT: "dev_exit",
    EV_HEALTH: "health", EV_SPEC_ENQ: "spec_enqueue",
    EV_SPEC_SEAL: "spec_seal", EV_SPEC_ABORT: "spec_abort",
    EV_COMBINE_FLUSH: "combine_flush",
    EV_TRS_SUBSCRIBE: "trs_subscribe", EV_TRS_PUSH: "trs_push",
    EV_TRS_PROOF: "trs_proof", EV_PREEXEC_LAUNCH: "preexec_launch",
    EV_PREEXEC_AGREE: "preexec_agree",
    EV_PREEXEC_CONFLICT: "preexec_conflict", EV_TUNE: "tune",
    EV_DUR_GROUP: "dur_group", EV_AGG_FORWARD: "agg_forward",
    EV_AGG_ROOT: "agg_root", EV_AGG_FALLBACK: "agg_fallback",
    EV_OPT_REPLY: "opt_reply", EV_CERT_ASYNC_DONE: "cert_async_done",
    EV_CERT_ASYNC_LAG: "cert_async_lag",
    EV_OFF_LEASE: "lease_issued", EV_OFF_VERIFIED: "lease_verified",
    EV_OFF_REJECTED: "lease_rejected", EV_OFF_EVICT: "helper_evicted",
}

# events the slot tracker folds inline (everything else is ring-only)
_SLOT_CODES = frozenset((EV_ADM_ADMIT, EV_PP_DISPATCH, EV_PP_ACCEPT,
                         EV_PREPARED, EV_COMMITTED, EV_EXEC_ENQ,
                         EV_EXEC_APPLY, EV_REPLY, EV_SPEC_ENQ,
                         EV_SPEC_SEAL, EV_SPEC_ABORT,
                         EV_CERT_ASYNC_LAG))

# the six PIPELINE stages partition a slot's lifetime (they sum to the
# slot total); spec_overlap is an OVERLAY — the slice of the commit
# window reclaimed by speculative execution — and is excluded from the
# total (it runs concurrently with `commit`, > 0 only on slots whose
# speculative run actually sealed). cert_lag is the second overlay:
# optimistic release -> verified certificate, the deferred-combine tail
# that runs AFTER the client already has its reply (> 0 only under
# ReplicaConfig.optimistic_replies; fed by EV_CERT_ASYNC_LAG samples,
# which usually land after the slot finalized on EV_REPLY — so it is
# tracked as a sample stream, never part of a slot's total)
PIPELINE_STAGES = ("adm_wait", "dispatch", "prepare", "commit", "exec",
                   "reply")
STAGES = PIPELINE_STAGES + ("spec_overlap", "cert_lag")

RING_SIZE = max(64, int(os.environ.get("TPUBFT_FLIGHT_RING", "4096")
                        or 4096))


def _default_dump_dir() -> str:
    return os.environ.get(
        "TPUBFT_FLIGHT_DIR",
        os.path.join(tempfile.gettempdir(), "tpubft-flight"))


_dump_dir = _default_dump_dir()
_dump_counter = 0
_dump_mu = make_lock("flight.dump")


# ---------------------------------------------------------------------
# per-thread rings
# ---------------------------------------------------------------------
class _Ring:
    """Bounded event ring owned by exactly one thread: writes are
    lock-free (the registry lock is taken once, at creation). Readers
    (snapshot/dump) take a racy copy — a torn read costs at most one
    half-written slot of telemetry, never correctness."""

    __slots__ = ("buf", "idx", "role", "rid", "thread_ref")

    def __init__(self, role: str, rid: int) -> None:
        self.buf: List[Optional[Tuple]] = [None] * RING_SIZE
        self.idx = 0
        self.role = role
        self.rid = rid
        # weakref, not ident: thread idents are recycled, so an
        # ident-based liveness check would keep dead rings looking
        # alive forever under thread churn
        self.thread_ref = weakref.ref(threading.current_thread())

    def owner_alive(self) -> bool:
        t = self.thread_ref()
        return t is not None and t.is_alive()

    def events(self) -> List[Tuple]:
        """Oldest-to-newest copy (racy; see class docstring)."""
        i = self.idx
        out = [e for e in self.buf[i:] + self.buf[:i] if e is not None]
        return out


_tl = threading.local()
_rings_mu = make_lock("flight.rings")
_rings: List[_Ring] = []

# dead-thread rings are RETAINED (their tail is exactly the evidence a
# post-mortem dump wants) but bounded: beyond this many, the oldest
# dead rings are dropped at the next ring registration, so
# thread-churning processes (test clusters, chaos campaigns) don't
# accumulate one ring per thread that ever lived
DEAD_RING_KEEP = 32


def _prune_dead_locked() -> None:
    dead = [r for r in _rings if not r.owner_alive()]
    for r in dead[:max(0, len(dead) - DEAD_RING_KEEP)]:
        _rings.remove(r)


def set_thread_rid(rid: int) -> None:
    """Seed the calling thread's replica id (dispatcher / exec lane /
    admission loops call this at entry) so multi-replica processes
    attribute events correctly."""
    _tl.rid = rid
    ring = getattr(_tl, "ring", None)
    if ring is not None:
        ring.rid = rid


def _ring() -> _Ring:
    ring = getattr(_tl, "ring", None)
    if ring is None:
        ring = _Ring(threading.current_thread().name,
                     getattr(_tl, "rid", -1))
        _tl.ring = ring
        with _rings_mu:
            _rings.append(ring)
            _prune_dead_locked()      # rare path: once per new thread
    return ring


def _record(code: int, seq: int = 0, view: int = 0, arg: int = 0) -> None:
    ring = _ring()
    t = time.monotonic_ns()
    ring.buf[ring.idx] = (t, code, seq, view, arg)
    ring.idx = (ring.idx + 1) % RING_SIZE
    if code in _SLOT_CODES:
        _tracker.on_event(ring.rid, code, seq, view, arg, t)


def _record_off(code: int, seq: int = 0, view: int = 0,
                arg: int = 0) -> None:
    return None


ENABLED = os.environ.get("TPUBFT_FLIGHT", "1") not in ("", "0")
# the ONE hot-path entry point: callers use `flight.record(...)` (a
# module-attribute lookup) so enable/disable swaps take effect
record = _record if ENABLED else _record_off


def enabled() -> bool:
    return record is _record


def _set_enabled(on: bool) -> None:
    """Test hook (the production compile-out is TPUBFT_FLIGHT=0 at
    process start)."""
    global record
    record = _record if on else _record_off


def configure(dump_dir: Optional[str] = None) -> None:
    global _dump_dir
    if dump_dir is not None:
        _dump_dir = dump_dir


# ---------------------------------------------------------------------
# slot lifecycle tracker
# ---------------------------------------------------------------------
class SlotTracker:
    """Folds slot-stage events into per-(replica, seq) stage timings.

    Stage boundaries (ns timestamps, all monotonic):

        adm_wait  admission admit -> PrePrepare handler entry
                  (external-queue wait; 0 for the primary's own PP)
        dispatch  handler entry -> accept (validation, incl. the async
                  client-sig round trip; 0 for the primary self-accept)
        prepare   accept -> prepare quorum (0 on the fast path)
        commit    prepare quorum (or accept) -> commit quorum
        exec      commit -> durable apply (lane thread)
        reply     durable apply -> slot integrated + replies sent

    Plus one OVERLAY stage that runs concurrently with ``commit`` and
    is excluded from the slot total:

        spec_overlap  speculative enqueue -> commit quorum: the slice
                  of the combine window the execution lane reclaimed
                  by running the slot ahead of its commit certificate
                  (> 0 only when the speculative run sealed; aborted
                  speculations fold to 0)

    A slot finalizes on EV_REPLY (the dispatcher records it for every
    integrated slot, replies or not): its stage durations feed the
    process-wide ``slot.<stage>`` diagnostics histograms and a bounded
    deque of recent completed slots behind ``status get slots``."""

    MAX_LIVE = 4096
    KEEP = 512

    def __init__(self) -> None:
        self._mu = make_lock("flight.slots")
        self._live: Dict[Tuple[int, int], Dict] = {}
        self._done: "deque[Dict]" = deque(maxlen=self.KEEP)
        self._hists: Dict[str, object] = {}
        self._finalized = 0
        # cert_lag overlay samples, (rid, lag_ms): EV_CERT_ASYNC_LAG
        # usually arrives AFTER its slot finalized on EV_REPLY (that is
        # the whole point of the optimistic reply plane), so the
        # deferred-combine tail is tracked as its own bounded sample
        # stream instead of a per-slot field
        self._cert_lag: "deque[Tuple[int, float]]" = deque(maxlen=self.KEEP)
        # recently-finalized slot keys: with optimistic replies the
        # verified-commit event (EV_COMMITTED) lands AFTER the slot
        # already finalized on EV_REPLY — without this guard the late
        # event would resurrect the slot as a live entry that never
        # finalizes and eventually evicts genuinely-live slots
        self._folded: "deque[Tuple[int, int]]" = deque()
        self._folded_set: set = set()
        # per-replica finalized counts: an rid-filtered summary must
        # report ITS replica's progress (the autotuner's fresh-signal
        # gate), not the process total — in a multi-replica process a
        # stalled replica's controller must not mistake its siblings'
        # slots for fresh local signal
        self._finalized_by_rid: Dict[int, int] = {}

    def _hist(self, stage: str):
        h = self._hists.get(stage)
        if h is None:
            from tpubft.diagnostics import get_registrar
            h = self._hists[stage] = get_registrar().histogram(
                f"slot.{stage}")
        return h

    _FIELD = {EV_ADM_ADMIT: "admit", EV_PP_DISPATCH: "handler",
              EV_PP_ACCEPT: "accept", EV_PREPARED: "prepared",
              EV_COMMITTED: "committed", EV_EXEC_ENQ: "enqueued",
              EV_EXEC_APPLY: "applied", EV_REPLY: "replied",
              EV_SPEC_ENQ: "spec_enq", EV_SPEC_SEAL: "spec_seal"}

    def on_event(self, rid: int, code: int, seq: int, view: int,
                 arg: int, t_ns: int) -> None:
        if code == EV_CERT_ASYNC_LAG:
            # overlay sample (arg = lag in µs): folded independently of
            # the slot record, which is typically already finalized
            lag_ms = arg / 1e3
            with self._mu:
                self._cert_lag.append((rid, lag_ms))
            self._hist("cert_lag").record(arg)      # histograms in µs
            return
        key = (rid, seq)
        with self._mu:
            slot = self._live.get(key)
            if slot is None:
                if (code in (EV_REPLY, EV_SPEC_ABORT)
                        or key in self._folded_set):
                    return              # replay / late event on a
                    #                     slot that already folded
                if len(self._live) >= self.MAX_LIVE:
                    # bounded: evict the oldest live entry (a wedged or
                    # view-changed-away slot must not pin memory)
                    self._live.pop(next(iter(self._live)))
                slot = self._live[key] = {"rid": rid, "seq": seq,
                                          "view": view}
            if code == EV_SPEC_ABORT:
                # the speculation was discarded: this slot re-executes
                # from its committed body, so no combine window was
                # reclaimed — spec_overlap must fold to 0
                slot.pop("spec_enq", None)
                slot.pop("spec_seal", None)
                return
            field = self._FIELD[code]
            slot.setdefault(field, t_ns)
            if code == EV_COMMITTED:
                slot.setdefault("path", "fast" if arg else "slow")
            if code != EV_REPLY:
                return
            del self._live[key]
            self._folded_set.add(key)
            self._folded.append(key)
            if len(self._folded) > self.MAX_LIVE:
                self._folded_set.discard(self._folded.popleft())
        self._finalize(slot)

    @staticmethod
    def fold(slot: Dict) -> Dict[str, float]:
        """Stage durations in milliseconds from a slot's raw
        timestamps — pure, shared with tools/tpuprof.py."""
        def ms(a: Optional[int], b: Optional[int]) -> float:
            if a is None or b is None or b < a:
                return 0.0
            return (b - a) / 1e6
        accept = slot.get("accept")
        prepared = slot.get("prepared")
        return {
            "adm_wait": ms(slot.get("admit"), slot.get("handler")),
            "dispatch": ms(slot.get("handler"), accept),
            "prepare": ms(accept, prepared),
            "commit": ms(prepared if prepared is not None else accept,
                         slot.get("committed")),
            "exec": ms(slot.get("committed"), slot.get("applied")),
            "reply": ms(slot.get("applied"), slot.get("replied")),
            # combine-window slice reclaimed by speculation: counted
            # only when the speculative run actually SEALED (an aborted
            # or commit-first speculation reclaimed nothing)
            "spec_overlap": (ms(slot.get("spec_enq"),
                                slot.get("committed"))
                             if slot.get("spec_seal") is not None
                             else 0.0),
            # per-slot placeholder: the deferred-combine tail lands
            # AFTER the slot finalizes, so cert_lag is folded from the
            # EV_CERT_ASYNC_LAG sample stream (see summary()), never
            # from a slot's own timestamps
            "cert_lag": 0.0,
        }

    def _finalize(self, slot: Dict) -> None:
        stages = self.fold(slot)
        rec = {"rid": slot["rid"], "seq": slot["seq"],
               "view": slot.get("view", 0),
               "path": slot.get("path", "?"),
               "spec": slot.get("spec_seal") is not None,
               "total_ms": round(sum(stages[s]
                                     for s in PIPELINE_STAGES), 3),
               "stages_ms": {k: round(v, 3) for k, v in stages.items()}}
        for stage, v_ms in stages.items():
            self._hist(stage).record(v_ms * 1e3)      # histograms in us
        with self._mu:
            self._finalized += 1
            self._finalized_by_rid[rec["rid"]] = \
                self._finalized_by_rid.get(rec["rid"], 0) + 1
            self._done.append(rec)

    def summary(self, rid: Optional[int] = None) -> Dict:
        """Per-stage breakdown over the retained completed slots:
        count/avg/p50/p95/max in ms (the bench --profile artifact and
        ``status get slots`` payload)."""
        with self._mu:
            done = [d for d in self._done
                    if rid is None or d["rid"] == rid]
            live = len(self._live)
            finalized = (self._finalized if rid is None
                         else self._finalized_by_rid.get(rid, 0))
            lag_samples = [ms for r, ms in self._cert_lag
                           if rid is None or r == rid]
        stages: Dict[str, Dict] = {}
        for stage in STAGES:
            if stage == "cert_lag":
                vals = sorted(lag_samples)
            else:
                vals = sorted(d["stages_ms"][stage] for d in done)
            n = len(vals)
            stages[stage] = {
                "count": n,
                "avg_ms": round(sum(vals) / n, 3) if n else 0.0,
                "p50_ms": vals[n // 2] if n else 0.0,
                "p95_ms": vals[min(n - 1, int(n * 0.95))] if n else 0.0,
                "max_ms": vals[-1] if n else 0.0,
            }
        return {"completed": len(done), "finalized_total": finalized,
                "live": live, "stages": stages}

    def recent(self, limit: int = 50,
               rid: Optional[int] = None) -> List[Dict]:
        with self._mu:
            done = [d for d in self._done
                    if rid is None or d["rid"] == rid]
        return done[-limit:]

    def reset(self) -> None:
        with self._mu:
            self._live.clear()
            self._done.clear()
            self._finalized = 0
            self._finalized_by_rid.clear()
            self._cert_lag.clear()
            self._folded.clear()
            self._folded_set.clear()


_tracker = SlotTracker()


def slot_tracker() -> SlotTracker:
    return _tracker


def stage_summary(rid: Optional[int] = None) -> Dict:
    return _tracker.summary(rid=rid)


# ---------------------------------------------------------------------
# kernel profiler (fed by ops/dispatch.device_section)
# ---------------------------------------------------------------------
class KernelProfiler:
    """Per-kernel-kind device profile. The first call is split out —
    it pays the XLA compile, and folding it into the mean makes every
    warm-path number a lie."""

    def __init__(self) -> None:
        self._mu = make_lock("flight.kernels")
        self._stats: Dict[str, Dict] = {}
        self._kind_ids: Dict[str, int] = {}

    def kind_id(self, kind: str) -> int:
        with self._mu:
            kid = self._kind_ids.get(kind)
            if kid is None:
                kid = self._kind_ids[kind] = len(self._kind_ids) + 1
            return kid

    def record(self, kind: str, batch: int, elapsed_ns: int,
               breaker_state: str) -> None:
        us = elapsed_ns / 1e3
        with self._mu:
            st = self._stats.get(kind)
            if st is None:
                st = self._stats[kind] = {
                    "calls": 0, "first_call_us": us, "total_us": 0.0,
                    "warm_us": 0.0, "max_us": 0.0,
                    "batch_sum": 0, "batch_max": 0,
                    "batch_min": batch, "breaker": {}}
            st["calls"] += 1
            st["total_us"] += us
            if st["calls"] > 1:
                st["warm_us"] += us
            st["max_us"] = max(st["max_us"], us)
            st["batch_sum"] += batch
            st["batch_max"] = max(st["batch_max"], batch)
            st["batch_min"] = min(st["batch_min"], batch)
            st["breaker"][breaker_state] = \
                st["breaker"].get(breaker_state, 0) + 1

    def snapshot(self) -> Dict:
        with self._mu:
            out = {}
            for kind, st in self._stats.items():
                calls = st["calls"]
                warm = calls - 1
                out[kind] = {
                    "calls": calls,
                    "first_call_ms": round(st["first_call_us"] / 1e3, 3),
                    "warm_avg_ms": round(
                        st["warm_us"] / warm / 1e3, 3) if warm else 0.0,
                    "total_ms": round(st["total_us"] / 1e3, 3),
                    "max_ms": round(st["max_us"] / 1e3, 3),
                    "batch_avg": round(st["batch_sum"] / calls, 1),
                    "batch_min": st["batch_min"],
                    "batch_max": st["batch_max"],
                    "breaker_states": dict(st["breaker"]),
                }
            return out

    def kind_table(self) -> Dict[int, str]:
        with self._mu:
            return {v: k for k, v in self._kind_ids.items()}

    def reset(self) -> None:
        with self._mu:
            self._stats.clear()


_profiler = KernelProfiler()


def kernel_profiler() -> KernelProfiler:
    return _profiler


# ---------------------------------------------------------------------
# dump plane
# ---------------------------------------------------------------------
# registered subsystem-state providers: each dump/snapshot calls every
# provider and attaches its payload under "providers" — the autotuner
# rides this (knob values + decision log join EV_TUNE events to names),
# and any future subsystem can without touching the recorder
_providers_mu = make_lock("flight.providers")
_providers: Dict[str, object] = {}


def register_dump_provider(name: str, fn) -> None:
    """Attach `fn()`'s JSON-able payload to every snapshot/dump under
    ``providers[name]`` (idempotent by name: last registration wins)."""
    with _providers_mu:
        _providers[name] = fn


def unregister_dump_provider(name: str) -> None:
    with _providers_mu:
        _providers.pop(name, None)


def _provider_payloads() -> Dict:
    with _providers_mu:
        items = list(_providers.items())
    out = {}
    for name, fn in items:
        try:
            out[name] = fn()
        except Exception:  # noqa: BLE001 — a broken provider must not
            out[name] = "<provider error>"   # take down the dump plane
    return out


def snapshot(max_events_per_ring: Optional[int] = None) -> Dict:
    """Full recorder state as one JSON-able dict. ``ts_epoch`` /
    ``mono_ns`` anchor the monotonic event clock to wall time so
    tools/tpuprof.py can align dumps from different replicas."""
    with _rings_mu:
        # retention pass here too (registration is the other site):
        # a snapshot-heavy process with no NEW threads must still shed
        # dead rings beyond the cap
        _prune_dead_locked()
        rings = list(_rings)
    ring_dumps = []
    for r in rings:
        evs = r.events()
        if max_events_per_ring is not None:
            evs = evs[-max_events_per_ring:]
        ring_dumps.append({"thread": r.role, "rid": r.rid,
                           "events": [list(e) for e in evs]})
    from tpubft.utils.racecheck import hold_stats
    from tpubft.utils.tracing import get_tracer
    spans = [{"name": s.name, "trace_id": s.context.trace_id,
              "span_id": s.context.span_id, "epoch": s.epoch,
              "start": s.start, "end": s.end, "tags": dict(s.tags)}
             for s in get_tracer().finished_spans()[-256:]]
    return {
        "ts_epoch": time.time(),
        "mono_ns": time.monotonic_ns(),
        "pid": os.getpid(),
        "enabled": enabled(),
        "ring_size": RING_SIZE,
        "event_names": {str(k): v for k, v in EV_NAMES.items()},
        "kernel_kinds": {str(k): v for k, v in
                         _profiler.kind_table().items()},
        "rings": ring_dumps,
        "kernels": _profiler.snapshot(),
        "slots": {"summary": _tracker.summary(),
                  "recent": _tracker.recent(limit=SlotTracker.KEEP)},
        "lock_hold_s": hold_stats(),
        "spans": spans,
        "providers": _provider_payloads(),
    }


# dump retention: this process keeps at most this many artifacts in
# the dump dir (oldest pruned at each write) — a flapping verdict or a
# long chaos campaign must degrade to rotating evidence, never to a
# filled filesystem
MAX_DUMPS = max(2, int(os.environ.get("TPUBFT_FLIGHT_MAX_DUMPS", "64")
                       or 64))


def _prune_dumps_locked() -> None:
    prefix = f"flight-{os.getpid()}-"
    try:
        mine = sorted(f for f in os.listdir(_dump_dir)
                      if f.startswith(prefix) and f.endswith(".json"))
        for f in mine[:max(0, len(mine) - MAX_DUMPS)]:
            os.unlink(os.path.join(_dump_dir, f))
    except OSError:
        pass


def dump(reason: str, extra: Optional[Dict] = None,
         path: Optional[str] = None) -> Optional[str]:
    """Write a flight-dump JSON artifact; returns its path (None on
    I/O failure — the dump plane must never take down its host)."""
    global _dump_counter
    try:
        snap = snapshot()
        snap["reason"] = reason
        if extra is not None:
            snap["extra"] = extra
        if path is None:
            os.makedirs(_dump_dir, exist_ok=True)
            safe = "".join(ch if ch.isalnum() or ch in "-_." else "_"
                           for ch in reason)[:80]
            with _dump_mu:
                _dump_counter += 1
                n = _dump_counter
                path = os.path.join(
                    _dump_dir,
                    f"flight-{os.getpid()}-{n:06d}-{safe}.json")
                _prune_dumps_locked()
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(snap, fh)
        return path
    except Exception:  # noqa: BLE001 — diagnostics must not crash host
        return None


def reset() -> None:
    """Drop all recorded state (bench/test isolation). Rings stay
    registered (threads keep their identity); their contents clear."""
    with _rings_mu:
        for r in _rings:
            r.buf = [None] * RING_SIZE
            r.idx = 0
    _tracker.reset()
    _profiler.reset()


# ---------------------------------------------------------------------
# diagnostics wiring (`status get flight|slots|kernels`)
# ---------------------------------------------------------------------
def install_diagnostics(registrar=None) -> None:
    """Idempotent registration of the recorder's status handlers on the
    (given or global) diagnostics registrar."""
    if registrar is None:
        from tpubft.diagnostics import get_registrar
        registrar = get_registrar()
    registrar.register_status("flight", lambda: json.dumps(
        snapshot(max_events_per_ring=256)))
    registrar.register_status("slots", lambda: json.dumps(
        {"summary": _tracker.summary(),
         "recent": _tracker.recent(limit=50)}, sort_keys=True))
    registrar.register_status("kernels", lambda: json.dumps(
        _profiler.snapshot(), sort_keys=True))
