"""Replica configuration registry.

TPU-native rebuild of the reference's ReplicaConfig
(/root/reference/bftengine/include/bftengine/ReplicaConfig.hpp:28-89): a
declarative parameter registry with defaults, descriptions, serialization,
and derived quorum arithmetic (n = 3f + 2c + 1).
"""
from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


# identity/topology fields have dedicated CLI flags on every binary and
# feed key generation + endpoint tables from argv — overriding them
# through the generic escape hatch would silently desync those
_TOPOLOGY_FIELDS = frozenset({
    "replica_id", "f_val", "c_val", "num_ro_replicas",
    "num_of_client_proxies", "is_read_only"})


def parse_config_overrides(pairs) -> Dict[str, Any]:
    """--config-override key=value (repeatable): any non-topology
    ReplicaConfig field, coerced to the field's declared type. The
    generic escape hatch so new tunables never need a dedicated flag to
    reach replica processes."""
    types = {f.name: f.type for f in dataclasses.fields(ReplicaConfig)}
    out: Dict[str, Any] = {}
    for pair in pairs or []:
        key, sep, val = pair.partition("=")
        if not sep or key not in types:
            raise SystemExit(f"--config-override: unknown or malformed "
                             f"'{pair}' (want <ReplicaConfig field>=<value>)")
        if key in _TOPOLOGY_FIELDS:
            raise SystemExit(f"--config-override: '{key}' is a topology "
                             f"field — use its dedicated flag (keys and "
                             f"endpoint tables are derived from argv)")
        t = types[key]
        if t in ("int", int):
            out[key] = int(val)
        elif t in ("float", float):
            out[key] = float(val)
        elif t in ("bool", bool):
            out[key] = val.lower() in ("1", "true", "yes", "on")
        elif t in ("str", str):
            out[key] = val
        else:
            # an unrecognized declared type must fail at parse time, not
            # surface as a str/type mismatch deep inside the replica
            raise SystemExit(f"--config-override: field '{key}' has "
                             f"unsupported type {t!r}")
    return out


@dataclass
class ReplicaConfig:
    """All tunables for one replica. Field docs mirror the reference params."""

    # identity / topology
    replica_id: int = 0
    f_val: int = 1                  # max byzantine replicas tolerated
    c_val: int = 0                  # max slow/crashed replicas for fast path
    num_of_client_proxies: int = 1
    num_ro_replicas: int = 0
    is_read_only: bool = False

    # batching (RequestsBatchingLogic equivalents)
    max_num_of_requests_in_batch: int = 100
    max_batch_size_bytes: int = 33_554_432
    batch_flush_period_ms: int = 7

    # protocol windows/timers
    # max consensus slots proposed-but-not-executed (the PrePrepare
    # pipeline gate; under load this is also what forms request batches).
    # Reference: ReplicaConfig.hpp concurrencyLevel, SKVBC tester
    # replica default 3 (tests/simpleKVBC/TesterReplica/setup.cpp:72)
    concurrency_level: int = 3
    view_change_timer_ms: int = 4000
    status_report_timer_ms: int = 1000
    checkpoint_window_size: int = 150   # seqnums between protocol checkpoints
    work_window_size: int = 300         # in-flight seqnum window (2 checkpoints)
    max_reply_size_bytes: int = 1_048_576

    # state transfer
    st_stall_timeout_ms: int = 5000     # certified checkpoint ahead + no
                                        # execution progress -> fetch state

    # commit paths
    fast_path_timeout_ms: int = 300     # demote in-flight seq to slow path
    auto_primary_rotation_enabled: bool = False
    view_change_protocol_enabled: bool = True
    pre_execution_enabled: bool = False
    # backup-side pre-execution reply cache (preprocessor/preprocessor.py
    # _reply_cache): bounded LRU of packed PreProcessReplyMsg so a
    # primary's rebroadcast is answered from cache instead of
    # re-executing the handler. Sized like the SigManager verify memo:
    # big enough to cover in-flight sessions x retries, small enough
    # that real client traffic cannot grow it without bound.
    preexec_reply_cache_max: int = 512
    # pre-execution worker pool width (backup + primary speculative
    # executions run here, off the dispatcher)
    preexec_threads: int = 4

    # thin-replica read tier (thinreplica/server.py): serve state reads,
    # merkle proofs, and live update subscriptions off the consensus
    # path, fed once per sealed execution run from the ledger's
    # durable-apply seam. Requires a blockchain-backed handler —
    # silently inactive otherwise.
    thin_replica_enabled: bool = False
    # TCP port for the thin-replica listener (0 = ephemeral; in-process
    # clusters discover the bound port via replica.thin_replica.port)
    thin_replica_port: int = 0
    # per-subscriber live-update buffer (runs, not blocks): a subscriber
    # lagging more than this many sealed runs is dropped (it
    # re-subscribes and catches up from history) — see
    # trs_dropped_subscribers / trs_overflows
    thin_replica_sub_buffer: int = 1024
    time_service_enabled: bool = False
    time_max_skew_ms: int = 1000
    key_exchange_on_start: bool = False

    # crypto
    # "auto" resolves to "tpu" when a real accelerator is reachable
    # (safe subprocess probe — crypto/backend.py), else "cpu"
    crypto_backend: str = "auto"        # "cpu" | "tpu" | "auto"
    kvbc_version: str = "categorized"   # ledger engine: "categorized" | "v4"
    # fsync every DB write batch. Default matches the reference's RocksDB
    # WriteOptions (sync=false): process-crash consistency comes from the
    # OS page cache + record CRCs (torn-tail recovery); a host power loss
    # may lose the newest suffix. Profiling: True costs ~7 fsyncs (~8ms)
    # per consensus op per replica.
    db_sync_writes: bool = False
    # even with db_sync_writes=False, batches touching the CONSENSUS
    # METADATA families (view/prepared/checkpoint descriptors) still
    # fsync: losing a prepare this replica already voted on is a safety
    # hazard under correlated power loss, while block data is always
    # re-derivable from the quorum via state transfer. False = nothing
    # syncs (benchmarking escape hatch).
    db_sync_metadata: bool = True
    replica_sig_scheme: str = "ed25519"  # per-message replica signatures
    client_sig_scheme: str = "ed25519"
    # certificate (threshold) scheme: "multisig-ed25519", "threshold-bls",
    # or "adaptive" — resolved ONCE at key generation by cluster size:
    # below the crossover the Ed25519 multisig vector (no G1 ladder math
    # at all), at/above it compact BLS threshold certificates
    # (crypto/systems.resolve_threshold_scheme; the EdDSA-vs-BLS
    # committee measurements, arXiv 2302.00418, quantify the tradeoff)
    threshold_scheme: str = "adaptive"
    # n-crossover for "adaptive" (0 = the built-in default measured by
    # benchmarks/bench_combine.py --crossover). Every replica of a
    # cluster must configure the same value — the resolved scheme is
    # part of the cluster key material
    threshold_scheme_crossover_n: int = 0
    client_transaction_signing_enabled: bool = True

    # crypto batch dispatch (TPU seam)
    verify_batch_size: int = 256
    verify_batch_flush_us: int = 200
    # fused cross-slot combine plane (consensus/collectors.CombineBatcher):
    # due collectors across seqnums and kinds drain into ONE
    # combine_batch call per flush (BLS: one segmented multi-MSM launch
    # + one RLC pairing check for the whole batch) instead of one
    # combine job per slot. False = the legacy per-collector job path
    # (A/B control for bench_combine / bench_e2e pairing runs).
    fused_combine: bool = True
    # flush window / max slots per fused combine flush. The window
    # bounds added commit latency on an idle replica; under pipelined
    # load the batch fills first (see docs/OPERATIONS.md "Certificate
    # schemes & combine batching" for tuning)
    combine_flush_us: int = 300
    combine_batch_max: int = 64
    # share-aggregation overlay (ISSUE 17, arXiv 1911.04698): "off" =
    # every replica sends its Prepare/Commit shares straight to the
    # slot's collector (the O(n) fan-in path, byte-identical to the
    # pre-aggregation protocol); "tree" = shares climb a deterministic
    # view-seeded fanout tree rooted at the collector, interior nodes
    # forwarding 56-byte partial aggregates so the collector's inbound
    # share traffic drops to O(fanout); "gossip" = same overlay but
    # re-seeded every `agg_rotate_seqs` sequence numbers as well as per
    # view, so a slow interior node rotates out mid-view. Requires the
    # adaptive scheme (which resolves to "multisig-bls" when this is
    # on) or an explicit "multisig-bls" — Shamir threshold shares
    # cannot partially aggregate. Every replica of a cluster MUST
    # configure the same mode: the overlay shape is derived
    # deterministically, never negotiated on the wire.
    share_aggregation: str = "off"      # "off" | "tree" | "gossip"
    # overlay fanout (children per interior node). WIRE-VISIBLE and
    # pinned (never autotuned): every replica derives parent/children
    # from (n, fanout, view), so per-replica drift would fragment the
    # overlay — shares forwarded to a node that doesn't consider itself
    # the sender's parent would still aggregate (partials are
    # self-describing) but the O(fanout) bound and the timeout
    # accounting would be lost. See tuning/wiring.py.
    agg_fanout: int = 4
    # how long a non-root replica waits for its subtree's slot to reach
    # a full certificate before re-sending its own share DIRECT to the
    # collector (the all-to-all fallback: a dead/slow interior
    # aggregator costs one timeout, never liveness)
    agg_parent_timeout_ms: int = 250
    # how long an interior node holds a partially-filled aggregation
    # buffer before flushing what it has up the tree (bounds the
    # latency a straggler child can add at each level)
    agg_flush_ms: int = 30
    # "gossip" mode: re-seed the overlay permutation every this many
    # sequence numbers (rotation cadence within a view)
    agg_rotate_seqs: int = 16
    # below this many signatures a batch verifies on the CPU verifiers
    # instead of paying a device dispatch (latency-critical singletons)
    device_min_verify_batch: int = 32
    # hot-path verifications (client sigs at PrePrepare, combined-cert
    # checks) run as background jobs re-entering the dispatcher as
    # internal msgs (reference: RequestThreadPool +
    # CombinedSigVerificationJob); False = verify inline (debug only)
    async_verification: bool = True

    # bounded client table (million-principal client plane): max client
    # records resident in ClientsManager. Cold clients demand-page back
    # from their reply-ring reserved pages under an LRU (clients with
    # in-flight requests are pinned); the pager replays the per-client
    # restart rule, so at-most-once dedup survives an evict/reload
    # cycle exactly as it survives a restart. Autotuner-registered.
    # 0 = legacy unbounded table with eager boot restore (every client
    # O(1) resident forever — test-cluster shape only).
    client_table_max: int = 4096

    # admission pipeline (transport → dispatcher): >0 = a pool of that
    # many admission workers does all stateless per-message work off
    # the dispatcher — header peek (dead-view/stale-seq/garbage drops
    # before full unpack), parse, and signature verification coalesced
    # into ONE SigManager.verify_batch per drain cycle (one device
    # dispatch on the TPU backend); the dispatcher's external queue
    # then carries pre-parsed, pre-verified messages and its handlers
    # only mutate state. 0 = legacy inline path (raw bytes to the
    # dispatcher, parse/verify in the handlers).
    admission_workers: int = 1
    # max messages one admission drain cycle pulls from the ingest
    # queue (bounds verify-batch size and admission latency)
    admission_drain_max: int = 256
    # key-sharded admission routing: with >1 admission workers, client
    # datagrams route to a fixed worker by a stable hash of the wire
    # principal, so each worker's verify batches / signature memo /
    # per-principal comb caches see a disjoint, stable slice of the key
    # population (cache hit-rates hold as principals scale instead of
    # being diluted across every worker). Protocol-critical and
    # consensus traffic stays on the shared queues. False = legacy
    # shared-buffer draining (the A/B control; ledgers are
    # byte-identical either way).
    admission_key_sharding: bool = True
    # overload backpressure: when the admission ingest queue reaches the
    # high watermark the plane enters shed mode — fresh client requests
    # (ClientRequest/ClientBatch datagrams) are dropped at ingest (each
    # counted in adm_shed_overload) until depth falls back to the low
    # watermark. Protocol-critical traffic (view-change family,
    # checkpoints, state transfer, restart votes) rides a separate
    # priority queue that shedding never touches and workers drain
    # first, so an overloaded replica keeps participating in liveness
    # machinery while client goodput is shed. high = 0 disables
    # watermark shedding (the hard ingest bound remains).
    admission_high_watermark: int = 15000
    admission_low_watermark: int = 5000

    # device circuit breaker (tpubft/utils/breaker.py — process-wide,
    # wrapped around every device kernel seam): trip OPEN after this
    # many CONSECUTIVE device failures, fast-failing callers into the
    # scalar/host engines
    breaker_failure_threshold: int = 3
    # how long an OPEN breaker waits before letting one half-open probe
    # batch re-test the device (doubles on failed probes, up to 16x)
    breaker_cooldown_ms: int = 2000
    # latency SLO: a device dispatch slower than this classifies as a
    # failure even when it succeeds (a wedging accelerator transport
    # turns slow long before it raises). 0 disables the classifier —
    # the default, because first-dispatch XLA compiles legitimately
    # take seconds; enable post-warmup or with a compile-clearing value.
    breaker_latency_slo_ms: int = 0

    # verified crypto-offload tier (tpubft/offload/ — ISSUE 20): lease
    # BLS MSM/combine work and the ECDSA RLC fold to non-voting helper
    # processes, re-verifying every result on-replica with the 2G2T
    # constant-size soundness check before it can touch a verdict. A
    # lying helper is quarantined (operator reset required); a slow or
    # dead one cools down and is probe re-admitted. Off = the tier
    # doesn't exist; on, the autotuner's `offload_route` knob still
    # routes work helper-ward only while measured lease latency beats
    # the local per-item cost.
    offload_enabled: bool = False
    # comma-separated helper endpoints "id=host:port[,id=host:port...]"
    # (in-process tests register transports on the pool directly)
    offload_helpers: str = ""
    # lease deadline: a helper that misses it is SICK (cooldown+probe);
    # the lease retries once on another helper, then runs locally
    offload_lease_timeout_ms: int = 200
    # concurrent leases in flight across the pool; at the cap, work
    # runs locally instead of queueing behind the fleet
    offload_max_inflight: int = 4

    # health plane (tpubft/consensus/health.py): poll cadence of the
    # watchdog thread and the stall threshold for the dispatcher /
    # admission probes (the execution lane uses
    # execution_drain_timeout_ms; state transfer uses st_stall_timeout_ms
    # scaled by its retry machinery)
    health_poll_ms: int = 1000
    health_stall_ms: int = 5000

    # closed-loop autotuner (tpubft/tuning/): a per-replica controller
    # thread drives the performance knobs above (flush windows, batch
    # caps, accumulation depth, admission watermarks, the ECDSA
    # device/host crossover) from live telemetry — kernel-profiler
    # batch stats, flight-recorder stage breakdown, breaker/health
    # verdicts — within hard bounds, with per-knob hysteresis and
    # cooldown. The ReplicaConfig values stay the DEFAULTS every knob
    # backs off to whenever the health verdict leaves `healthy` or a
    # breaker opens (the controller never fights the degradation
    # plane). False = every knob stays exactly at its configured value.
    autotune_enabled: bool = True
    # controller poll cadence; each poll snapshots telemetry and casts
    # one policy vote per knob
    autotune_interval_ms: int = 1000
    # minimum interval between moves of any one knob (with the 2-vote
    # hysteresis this bounds how fast tuning can ramp — and how fast a
    # bad policy could wander)
    autotune_cooldown_ms: int = 3000
    # knob-registry seed file (JSON, written by e.g.
    # `bench_msm_crossover --ecdsa --seed-out`): measured operating
    # points loaded — and re-baselined as the degraded-reset defaults —
    # before the controller starts. "" = no seed.
    autotune_seed_file: str = ""

    # execution pipelining (reference: post-execution separation +
    # block accumulation). True = committed slots are executed by a
    # dedicated in-order executor thread that accumulates runs of
    # consecutive slots into ONE ledger commit + ONE reserved-pages
    # batch per run, keeping the dispatcher free to order the next
    # slots; False = the legacy inline path (execution on the
    # dispatcher, one commit per slot).
    execution_lane: bool = True
    # max committed slots coalesced into one execution run / ledger
    # commit. Runs always break at checkpoint-window boundaries so
    # state digests stay comparable cluster-wide. 1 degenerates to
    # per-slot commits (still off the dispatcher).
    execution_max_accumulation: int = 16
    # how long the dispatcher-side barrier (view-change send/entry,
    # state-transfer adoption, wedge/barrier batches) waits for the
    # lane to apply every submitted slot before giving up and retrying
    # on the next event. The health watchdog uses the same budget as
    # the lane's stall threshold, so a drain that would time out is
    # reported (stack dump + verdict) instead of silently eaten.
    execution_drain_timeout_ms: int = 30000
    # group-commit durability pipeline (tpubft/durability/): the
    # execution lane SEALS each run's ledger WriteBatch + reply pages
    # into a dedicated io thread that group-commits across runs — one
    # concatenated apply + ONE fsync per group — and publishes a
    # monotone durability watermark; replies, last_executed and the
    # at-most-once reply cache advance only behind it. The consensus-
    # metadata carve-out (db_sync_metadata) stays synchronous on the
    # dispatcher. Requires the execution lane; False = the legacy
    # per-run apply with immediate completion.
    durability_pipeline: bool = True
    # max runs fsynced per group (1 degenerates to the per-run durable
    # apply — the bench_e2e --durability-off A/B control's shape)
    durability_group_max: int = 8
    # how long the io thread holds a partial group open for more runs,
    # measured from the group's FIRST sealed run (bounds the extra
    # reply latency durability batching can add; autotuned live)
    durability_window_us: int = 1000
    # speculative execution ahead of the threshold combine: the
    # dispatcher hands a slot to the execution lane as SPECULATIVE at
    # prepare-quorum (slow path) or PrePrepare acceptance (fast paths,
    # which have no prepare round), so the lane executes it inside an
    # open, never-durable accumulation while the commit shares are
    # still combining; the run is sealed (one durable apply) only when
    # the commit certificate lands with the same digest, and replies +
    # last_executed stay strictly post-commit. View change, barrier
    # batches, and state-transfer adoption abort the overlay and the
    # slot re-executes from its committed body. Requires the execution
    # lane, an accumulation-capable ledger handler, and the time
    # service off (its page writes bypass the rollback substrate) —
    # silently inactive otherwise. False = legacy strictly-post-commit
    # execution.
    speculative_execution: bool = True
    # optimistic reply plane (arXiv 2407.12172): serve clients from f+1
    # matching INDIVIDUALLY-SIGNED replies instead of waiting for the
    # threshold certificate. With this on, a backup releases a slot to
    # the execution/durability pipeline as soon as a structurally-bound
    # commit certificate arrives over a VERIFIED prepare quorum (slow
    # path) or fast-path proposal — the expensive pairing check of the
    # combined signature completes asynchronously off the reply path —
    # and every ClientReplyMsg carries the replica's own signature so
    # the client's f+1 matcher can authenticate each vote. The compact
    # certificate still forms on the unchanged combine/aggregation path
    # (checkpointing, state transfer, audit), and `last_executed`
    # PERSISTENCE stays gated on verified commits (the optimistic
    # window is reply-visibility only). A certificate that fails its
    # deferred check poisons the optimistic plane for the rest of the
    # view (certificate-gated replies resume). Requires the execution
    # lane + speculation substrate to pay off; without them replies
    # simply stay certificate-gated.
    optimistic_replies: bool = False

    # retransmissions
    retransmissions_enabled: bool = True
    retransmission_timer_ms: int = 50

    # state transfer fetch pipeline (StConfig wiring — kvbc/replica.py):
    # ranges of `state_transfer_batch_blocks` blocks, up to
    # `st_window_ranges` ranges in flight striped across live sources,
    # blocks chunked at `max_block_chunk_bytes` on the wire (must clear
    # the transport datagram limit), completed windows of >=
    # `st_device_digest_threshold` blocks digest-verified as one device
    # batch
    max_block_chunk_bytes: int = 24 * 1024
    state_transfer_batch_blocks: int = 64
    st_window_ranges: int = 4
    st_device_digest_threshold: int = 16

    # key exchange
    key_exchange_on_start: bool = False

    extra: Dict[str, Any] = field(default_factory=dict)

    # ---- derived quorum arithmetic (ReplicaConfig.hpp numReplicas etc.) ----
    @property
    def n_val(self) -> int:
        return 3 * self.f_val + 2 * self.c_val + 1

    @property
    def num_replicas(self) -> int:
        return self.n_val

    @property
    def slow_path_quorum(self) -> int:
        """2f + c + 1 matching prepare/commit shares (PBFT-style)."""
        return 2 * self.f_val + self.c_val + 1

    @property
    def fast_path_threshold_quorum(self) -> int:
        """3f + c + 1 shares for FAST_WITH_THRESHOLD."""
        return 3 * self.f_val + self.c_val + 1

    @property
    def optimistic_fast_quorum(self) -> int:
        """all n shares for OPTIMISTIC_FAST."""
        return self.n_val

    def validate(self) -> None:
        if self.replica_id >= self.n_val + self.num_ro_replicas:
            raise ValueError(
                f"replica_id {self.replica_id} out of range for n={self.n_val} "
                f"(+{self.num_ro_replicas} RO)")
        if self.f_val < 1:
            raise ValueError("f_val must be >= 1")
        if self.work_window_size % self.checkpoint_window_size != 0:
            raise ValueError("work window must be a multiple of checkpoint window")
        if self.execution_max_accumulation < 1:
            raise ValueError("execution_max_accumulation must be >= 1")
        if self.admission_workers < 0:
            raise ValueError("admission_workers must be >= 0")
        if self.client_table_max < 0:
            raise ValueError("client_table_max must be >= 0")
        if self.admission_drain_max < 1:
            raise ValueError("admission_drain_max must be >= 1")
        if self.admission_high_watermark \
                and not 0 < self.admission_low_watermark \
                < self.admission_high_watermark:
            raise ValueError("need 0 < admission_low_watermark < "
                             "admission_high_watermark (or high = 0 to "
                             "disable overload shedding)")
        if self.execution_drain_timeout_ms < 1:
            raise ValueError("execution_drain_timeout_ms must be >= 1")
        if self.durability_group_max < 1:
            raise ValueError("durability_group_max must be >= 1")
        if self.durability_window_us < 0:
            raise ValueError("durability_window_us must be >= 0")
        if self.breaker_failure_threshold < 1:
            raise ValueError("breaker_failure_threshold must be >= 1")
        if self.offload_lease_timeout_ms < 1:
            raise ValueError("offload_lease_timeout_ms must be >= 1")
        if self.offload_max_inflight < 1:
            raise ValueError("offload_max_inflight must be >= 1")
        for ep in filter(None, self.offload_helpers.split(",")):
            if "=" not in ep or ":" not in ep.split("=", 1)[1]:
                raise ValueError(
                    f"offload_helpers entry {ep!r} must be id=host:port")
        if self.health_poll_ms < 1 or self.health_stall_ms < 1:
            raise ValueError("health_poll_ms/health_stall_ms must be >= 1")
        if self.autotune_interval_ms < 10:
            raise ValueError("autotune_interval_ms must be >= 10")
        if self.autotune_cooldown_ms < 0:
            raise ValueError("autotune_cooldown_ms must be >= 0")
        if self.threshold_scheme_crossover_n < 0:
            raise ValueError("threshold_scheme_crossover_n must be >= 0")
        if self.combine_batch_max < 1 or self.combine_flush_us < 0:
            raise ValueError("combine_batch_max must be >= 1 and "
                             "combine_flush_us >= 0")
        if self.share_aggregation not in ("off", "tree", "gossip"):
            raise ValueError("share_aggregation must be off|tree|gossip")
        if self.share_aggregation != "off":
            if self.threshold_scheme not in ("adaptive", "multisig-bls"):
                raise ValueError(
                    "share_aggregation requires threshold_scheme adaptive "
                    "(resolves to multisig-bls) or multisig-bls — Shamir "
                    "threshold shares cannot partially aggregate")
            if self.n_val > 64:
                raise ValueError("share_aggregation contributor bitmaps "
                                 "are u64 (n <= 64)")
        if self.agg_fanout < 2:
            raise ValueError("agg_fanout must be >= 2")
        if self.agg_parent_timeout_ms < 1 or self.agg_flush_ms < 0 \
                or self.agg_rotate_seqs < 1:
            raise ValueError("agg_parent_timeout_ms must be >= 1, "
                             "agg_flush_ms >= 0, agg_rotate_seqs >= 1")
        if self.preexec_reply_cache_max < 1:
            raise ValueError("preexec_reply_cache_max must be >= 1")
        if self.preexec_threads < 1:
            raise ValueError("preexec_threads must be >= 1")
        if self.thin_replica_sub_buffer < 1:
            raise ValueError("thin_replica_sub_buffer must be >= 1")
        if not 0 <= self.thin_replica_port <= 65535:
            raise ValueError("thin_replica_port must be a valid TCP port")

    # ---- serialization ----
    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "ReplicaConfig":
        return cls(**json.loads(s))

    def describe(self) -> Dict[str, str]:
        return {f.name: str(getattr(self, f.name)) for f in dataclasses.fields(self)}
