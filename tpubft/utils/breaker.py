"""Circuit breaker for the device-kernel seams — degradation as a
first-class runtime state.

PRs 1-4 put every hot path on batched device kernels (SigManager's
cross-principal verify ride, ops/sha256 digest batches, the BLS
combine/MSM) with *static* fallbacks: the scalar engines are selected at
import/config time and a device failure mid-run either crashes the call
or wedges the thread behind a hung dispatch. This module makes the
fallbacks reachable at RUNTIME: every device seam runs inside a
`CircuitBreaker.attempt()` section that

  * classifies failures — a device exception OR a latency-SLO breach
    both count against the failure budget (a wedged accelerator
    transport usually manifests as multi-second dispatches long before
    it raises);
  * trips OPEN after `failure_threshold` CONSECUTIVE failures: further
    attempts fast-fail with `BreakerOpen` before touching the device,
    so callers fall through to their scalar/host paths immediately
    instead of queueing behind a dead transport;
  * re-admits the device via HALF-OPEN probes: once `cooldown_s`
    elapses, a single in-flight attempt is allowed through as a probe
    batch — success closes the breaker (cooldown resets), failure
    re-opens it with exponential cooldown escalation up to
    `max_cooldown_s` (concord-bft's controller treats its slow path the
    same way: a protocol state you enter and leave on evidence, not an
    outage).

The process-wide breaker registry feeds the health plane
(tpubft/consensus/health.py): breaker states ride `status get health`
and the metrics snapshot, so a degraded run is visible, not silent.

Nesting: a guarded seam may call another guarded seam (SigManager's
verify ride dispatches through ops/ed25519's guarded kernel call). Only
the OUTERMOST attempt on a thread records an outcome — inner sections
are pass-through, so one device failure is one failure, not two.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, Optional

from tpubft.utils.racecheck import make_lock

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class BreakerOpen(RuntimeError):
    """Fast-fail: the breaker is OPEN (or the half-open probe slot is
    taken) — the caller must use its scalar/host fallback."""


class CircuitBreaker:
    def __init__(self, name: str,
                 failure_threshold: int = 3,
                 cooldown_s: float = 2.0,
                 latency_slo_s: float = 0.0,
                 max_cooldown_s: float = 30.0,
                 probe_max: int = 1,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.base_cooldown_s = cooldown_s
        # 0 disables the SLO classifier (first-dispatch XLA compiles can
        # legitimately take seconds — enable only after warmup or with a
        # budget that clears the compile)
        self.latency_slo_s = latency_slo_s
        self.max_cooldown_s = max_cooldown_s
        self.probe_max = max(1, probe_max)
        self._clock = clock
        self._mu = make_lock(f"breaker.{name}")
        self._tl = threading.local()      # nesting depth + probe flag
        self._state = CLOSED
        self._consecutive = 0
        self._cooldown_s = cooldown_s
        self._open_until = 0.0
        self._probe_inflight = 0
        # counters (plain ints under _mu; surfaced by the health plane)
        self.successes = 0
        self.failures = 0
        self.slo_breaches = 0
        self.trips = 0                    # CLOSED/HALF_OPEN -> OPEN
        self.recoveries = 0               # HALF_OPEN -> CLOSED
        self.fast_fails = 0               # attempts rejected while OPEN
        self.failures_by_kind: Dict[str, int] = {}
        _register(self)

    # ------------------------------------------------------------------
    # configuration (replica wiring pushes ReplicaConfig knobs here; the
    # breaker is process-wide, so the last-configured values win — all
    # replicas of one process share one device)
    # ------------------------------------------------------------------
    def configure(self, failure_threshold: Optional[int] = None,
                  cooldown_s: Optional[float] = None,
                  latency_slo_s: Optional[float] = None,
                  max_cooldown_s: Optional[float] = None) -> None:
        with self._mu:
            if failure_threshold is not None:
                self.failure_threshold = max(1, failure_threshold)
            if cooldown_s is not None:
                self.base_cooldown_s = cooldown_s
                self._cooldown_s = min(self._cooldown_s, max(
                    cooldown_s, 0.001)) if self._state != CLOSED else cooldown_s
            if latency_slo_s is not None:
                self.latency_slo_s = latency_slo_s
            if max_cooldown_s is not None:
                self.max_cooldown_s = max_cooldown_s

    def reset(self) -> None:
        """Back to CLOSED with a fresh failure budget (test isolation;
        counters are preserved — they are cumulative telemetry)."""
        with self._mu:
            self._state = CLOSED
            self._consecutive = 0
            self._cooldown_s = self.base_cooldown_s
            self._probe_inflight = 0

    # ------------------------------------------------------------------
    # state machine
    # ------------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._mu:
            return self._state_locked()

    def _state_locked(self) -> str:
        # OPEN with an expired cooldown reads as HALF_OPEN: the next
        # attempt becomes the probe
        if self._state == OPEN and self._clock() >= self._open_until:
            return HALF_OPEN
        return self._state

    def allow(self) -> bool:
        """Non-mutating admission preview (hot paths that want to skip
        building the device batch entirely when degraded)."""
        with self._mu:
            s = self._state_locked()
            return s == CLOSED or (s == HALF_OPEN
                                   and self._probe_inflight < self.probe_max)

    def _admit(self) -> bool:
        """Admission decision; returns probe-ness. Raises BreakerOpen."""
        with self._mu:
            now = self._clock()
            if self._state == OPEN and now >= self._open_until:
                self._state = HALF_OPEN
                self._probe_inflight = 0
            if self._state == CLOSED:
                return False
            if self._state == HALF_OPEN \
                    and self._probe_inflight < self.probe_max:
                self._probe_inflight += 1
                return True
            self.fast_fails += 1
        raise BreakerOpen(
            f"breaker {self.name!r} open ({self._cooldown_s:.1f}s cooldown)")

    def record_success(self, probe: bool = False) -> None:
        with self._mu:
            self.successes += 1
            self._consecutive = 0
            if probe:
                self._probe_inflight = max(0, self._probe_inflight - 1)
            # only a PROBE verdict may close the breaker: a non-probe
            # success seeing HALF_OPEN is a stale call admitted back
            # when the breaker was CLOSED (e.g. a dispatch that wedged
            # for the whole failure burst and finally returned) — its
            # evidence predates the trip and must not re-admit the
            # device while the real probe is still in flight
            if probe and self._state == HALF_OPEN:
                self._state = CLOSED
                self._cooldown_s = self.base_cooldown_s
                self.recoveries += 1

    def record_failure(self, kind: str = "", cause: str = "error",
                       probe: bool = False) -> None:
        with self._mu:
            self.failures += 1
            if cause == "slow":
                self.slo_breaches += 1
            if kind:
                self.failures_by_kind[kind] = \
                    self.failures_by_kind.get(kind, 0) + 1
            self._consecutive += 1
            if probe:
                self._probe_inflight = max(0, self._probe_inflight - 1)
            if self._state == HALF_OPEN:
                # the probe failed: re-open with escalated cooldown
                self._trip_locked(escalate=True)
            elif self._state == CLOSED \
                    and self._consecutive >= self.failure_threshold:
                self._trip_locked(escalate=False)

    def trip(self, cooldown_s: Optional[float] = None,
             cause: str = "forced") -> None:
        """Force OPEN from outside the attempt/verdict flow — the
        quarantine primitive (ISSUE 20: a Byzantine crypto-offload
        helper is evicted with an effectively-infinite cooldown; only
        an operator `reset()` re-admits it). Unlike failures, a forced
        trip carries no probe semantics: with a large enough cooldown
        the HALF_OPEN window simply never arrives."""
        with self._mu:
            if cooldown_s is not None:
                self._cooldown_s = cooldown_s
            if cause:
                self.failures_by_kind[cause] = \
                    self.failures_by_kind.get(cause, 0) + 1
            self._state = OPEN
            self._open_until = self._clock() + self._cooldown_s
            self.trips += 1

    def _trip_locked(self, escalate: bool) -> None:
        if escalate:
            self._cooldown_s = min(self._cooldown_s * 2, self.max_cooldown_s)
        self._state = OPEN
        self._open_until = self._clock() + self._cooldown_s
        self.trips += 1

    def exclude_wait(self, dt: float) -> None:
        """Credit host-side queueing against the latency-SLO clock of
        this thread's in-flight attempt. The device gate serializes
        producers (admission workers, exec-lane hashing, ST digests):
        time spent waiting behind another HEALTHY thread's batch is
        contention, not device slowness, and must not count toward the
        failure budget — or peak load alone trips the breaker."""
        if dt > 0 and getattr(self._tl, "depth", 0):
            self._tl.exclude = getattr(self._tl, "exclude", 0.0) + dt

    def _abandon(self, probe: bool) -> None:
        """Neither success nor failure (BaseException unwinding through
        the section): release the probe slot without a verdict."""
        if not probe:
            return
        with self._mu:
            self._probe_inflight = max(0, self._probe_inflight - 1)

    # ------------------------------------------------------------------
    # the guarded section
    # ------------------------------------------------------------------
    @contextmanager
    def attempt(self, kind: str = ""):
        """Run one device interaction under the breaker. Raises
        BreakerOpen (without running the body) when the device is
        disallowed; classifies body exceptions as failures and re-raises
        them; classifies an over-SLO success as a failure but still
        returns normally (the result is valid — the DEVICE is slow)."""
        depth = getattr(self._tl, "depth", 0)
        if depth:
            # nested seam: the outermost attempt owns the verdict
            self._tl.depth = depth + 1
            try:
                yield
            finally:
                self._tl.depth = depth
            return
        probe = self._admit()
        self._tl.depth = 1
        self._tl.exclude = 0.0
        t0 = self._clock()
        try:
            yield
        except Exception:
            self.record_failure(kind, "error", probe)
            raise
        except BaseException:
            self._abandon(probe)
            raise
        else:
            elapsed = self._clock() - t0 - getattr(self._tl, "exclude", 0.0)
            if self.latency_slo_s and elapsed > self.latency_slo_s:
                self.record_failure(kind, "slow", probe)
            else:
                self.record_success(probe)
        finally:
            self._tl.depth = 0

    def snapshot(self) -> Dict:
        with self._mu:
            now = self._clock()
            return {
                "state": self._state_locked(),
                "consecutive_failures": self._consecutive,
                "failure_threshold": self.failure_threshold,
                "cooldown_s": round(self._cooldown_s, 3),
                "open_for_s": round(max(0.0, self._open_until - now), 3)
                if self._state == OPEN else 0.0,
                "successes": self.successes,
                "failures": self.failures,
                "slo_breaches": self.slo_breaches,
                "trips": self.trips,
                "recoveries": self.recoveries,
                "fast_fails": self.fast_fails,
                "failures_by_kind": dict(self.failures_by_kind),
            }


# ---------------------------------------------------------------------
# process-wide registry (the health plane enumerates it)
# ---------------------------------------------------------------------
_registry: Dict[str, CircuitBreaker] = {}
# RLock: get_breaker constructs under the lock and CircuitBreaker's
# constructor re-enters it via _register
_registry_mu = threading.RLock()


def _register(b: CircuitBreaker) -> None:
    with _registry_mu:
        _registry[b.name] = b


def get_breaker(name: str, **kwargs) -> CircuitBreaker:
    """Get-or-create a named breaker (kwargs only apply on creation).
    The whole get-or-create runs under the registry lock: two racing
    first callers must share ONE instance, or one of them records
    failures on a breaker the health plane (and configure()) never
    sees."""
    with _registry_mu:
        b = _registry.get(name)
        if b is None:
            b = CircuitBreaker(name, **kwargs)
        return b


def all_breakers() -> Dict[str, CircuitBreaker]:
    with _registry_mu:
        return dict(_registry)


def prefixed(prefix: str) -> Dict[str, CircuitBreaker]:
    """Registry slice by name prefix — how the health plane (and tests)
    enumerate a breaker FAMILY, e.g. the per-chip mesh children
    `device.chip<N>` (ISSUE 16) without knowing the chip inventory."""
    with _registry_mu:
        return {n: b for n, b in _registry.items()
                if n.startswith(prefix)}


def snapshot_all() -> Dict[str, Dict]:
    return {name: b.snapshot() for name, b in all_breakers().items()}


def any_degraded() -> bool:
    """True when any breaker is not fully CLOSED — the health plane's
    'degraded' input."""
    return any(b.state != CLOSED for b in all_breakers().values())
