"""FlushBatcher — the one batching dispatcher both verification seams use.

Accumulates submitted items and hands the worker thread a whole batch:
flush happens when the batch fills OR the flush window after the first
item elapses (latency-bounded). The wake discipline matters: the worker
is notified on the empty→non-empty transition and on a full batch ONLY —
waking it on every submit would cut the flush window short and collapse
batches to ~2 items under steady arrival (the device/pairing batch then
never amortizes).

Consumers: SigManager.BatchVerifier (cross-message device signature
batches) and collectors.CertBatchVerifier (aggregated combined-cert
pairing checks).
"""
from __future__ import annotations

import threading
from typing import Callable, Generic, List, TypeVar

T = TypeVar("T")


class FlushBatcher(Generic[T]):
    def __init__(self, drain: Callable[[List[T]], None],
                 batch_size: int = 64, flush_us: int = 500,
                 on_drop: Callable[[T], None] = None,
                 name: str = "flush-batcher"):
        self._drain = drain
        self._batch_size = batch_size
        self._flush_s = flush_us / 1e6
        self._on_drop = on_drop
        self._pending: List[T] = []
        self._wake = threading.Condition(threading.Lock())
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._thread.start()

    def submit(self, item: T) -> None:
        with self._wake:
            if self._running:
                self._pending.append(item)
                if len(self._pending) == 1 \
                        or len(self._pending) >= self._batch_size:
                    self._wake.notify()
                return
        # stopped batcher never drains: resolve the item now (outside
        # the lock — on_drop may re-enter) so no waiter hangs on a
        # PendingVerdict that never settles
        self._drop_one(item)

    def _drop_one(self, item: T) -> None:
        if self._on_drop is None:
            return
        try:
            self._on_drop(item)
        except Exception:  # noqa: BLE001 — one bad callback must not
            pass           # strand the remaining waiters

    def _run(self) -> None:
        while self._running:
            with self._wake:
                if not self._pending:
                    self._wake.wait(timeout=0.05)
                    continue
                # flush window: wait once for the batch to fill; submits
                # during this wait do not re-notify (len > 1)
                if len(self._pending) < self._batch_size:
                    self._wake.wait(timeout=self._flush_s)
                batch, self._pending = self._pending, []
            try:
                self._drain(batch)
            except Exception:  # noqa: BLE001 — a bad batch must not kill
                from tpubft.utils.logging import get_logger
                get_logger("batcher").exception("drain raised (%s)",
                                                self._thread.name)
                # waiters on the failed batch must still resolve
                for item in batch:
                    self._drop_one(item)

    def stop(self) -> None:
        with self._wake:
            self._running = False
            self._wake.notify()
        self._thread.join(timeout=2)
        # swap the residue under the lock: a wedged worker (join timed
        # out) or a racing submit must not observe a half-drained list
        # or double-resolve items the worker is still posting verdicts on
        with self._wake:
            residue, self._pending = self._pending, []
        for item in residue:
            self._drop_one(item)
