"""FlushBatcher — the one batching dispatcher both verification seams use.

Accumulates submitted items and hands the worker thread a whole batch:
flush happens when the batch fills OR the flush window after the first
item elapses (latency-bounded). The wake discipline matters: the worker
is notified on the empty→non-empty transition and on a full batch ONLY —
waking it on every submit would cut the flush window short and collapse
batches to ~2 items under steady arrival (the device/pairing batch then
never amortizes).

Consumers: SigManager.BatchVerifier (cross-message device signature
batches) and collectors.CertBatchVerifier (aggregated combined-cert
pairing checks).
"""
from __future__ import annotations

import threading
from typing import Callable, Generic, List, TypeVar

T = TypeVar("T")


class FlushBatcher(Generic[T]):
    def __init__(self, drain: Callable[[List[T]], None],
                 batch_size: int = 64, flush_us: int = 500,
                 on_drop: Callable[[T], None] = None,
                 name: str = "flush-batcher"):
        self._drain = drain
        self._batch_size = batch_size
        self._flush_s = flush_us / 1e6
        self._on_drop = on_drop
        self._pending: List[T] = []
        self._wake = threading.Condition(threading.Lock())
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._thread.start()

    def reconfigure(self, batch_size: int = None,
                    flush_us: int = None) -> None:
        """Live retuning seam (the autotuner's actuator): batch size
        and flush window take effect from the next drain cycle. The
        worker is woken so a SHORTER window applies to the batch
        already accumulating, not after one stale full wait."""
        with self._wake:
            if batch_size is not None:
                self._batch_size = max(1, int(batch_size))
            if flush_us is not None:
                self._flush_s = max(0, int(flush_us)) / 1e6
            self._wake.notify()

    @property
    def batch_size(self) -> int:
        return self._batch_size

    @property
    def flush_us(self) -> int:
        return int(self._flush_s * 1e6)

    def submit(self, item: T) -> None:
        with self._wake:
            if self._running:
                self._pending.append(item)
                if len(self._pending) == 1 \
                        or len(self._pending) >= self._batch_size:
                    self._wake.notify()
                return
        # stopped batcher never drains: resolve the item now (outside
        # the lock — on_drop may re-enter) so no waiter hangs on a
        # PendingVerdict that never settles
        self._drop_one(item)

    def _drop_one(self, item: T) -> None:
        if self._on_drop is None:
            return
        try:
            self._on_drop(item)
        except Exception:  # noqa: BLE001 — one bad callback must not
            pass           # strand the remaining waiters

    def _run(self) -> None:
        import time as _time
        while self._running:
            with self._wake:
                if not self._pending:
                    self._wake.wait(timeout=0.05)
                    continue
                # flush window: wait for the batch to fill; submits
                # during this wait do not re-notify (len > 1). The wait
                # re-checks its deadline on every wakeup, reading the
                # (possibly reconfigured) window and cap fresh — a
                # reconfigure() notify retunes the in-progress wait
                # instead of being mistaken for window expiry, and a
                # SHRUNK window cuts the remaining wait short
                start = _time.monotonic()
                while (self._running and self._pending
                       and len(self._pending) < self._batch_size):
                    remaining = self._flush_s - (_time.monotonic()
                                                 - start)
                    if remaining <= 0:
                        break
                    self._wake.wait(timeout=remaining)
                batch, self._pending = self._pending, []
            try:
                self._drain(batch)
            except Exception:  # noqa: BLE001 — a bad batch must not kill
                from tpubft.utils.logging import get_logger
                get_logger("batcher").exception("drain raised (%s)",
                                                self._thread.name)
                # waiters on the failed batch must still resolve
                for item in batch:
                    self._drop_one(item)

    def stop(self) -> None:
        with self._wake:
            self._running = False
            self._wake.notify()
        self._thread.join(timeout=2)
        # swap the residue under the lock: a wedged worker (join timed
        # out) or a racing submit must not observe a half-drained list
        # or double-resolve items the worker is still posting verdicts on
        with self._wake:
            residue, self._pending = self._pending, []
        for item in residue:
            self._drop_one(item)
