"""Foundation utilities (reference: util/ — SURVEY.md §2.8)."""
