"""Canonical binary serialization for wire messages and persisted state.

TPU-native rebuild of the reference's CMF (Concord Message Format,
/root/reference/messages/compiler/cmfc.py + grammar.ebnf) and the
hand-rolled packed message headers (bftengine/src/bftengine/messages/).
Instead of an external codegen step, messages are declared as Python
dataclasses with a field-spec table; the codec supports CMF's type system:
fixed-width little-endian ints, bool, bytes/string (uvarint-length-prefixed),
lists, fixed lists, maps, optionals, oneof (by message id), and nested
messages. Deterministic (canonical) encoding: maps are sorted by key.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, fields, is_dataclass
from typing import Any, Dict, List, Optional, Tuple, Type, get_args, get_origin


class SerializeError(Exception):
    pass


# ---------------- low-level primitives ----------------

def write_uint(buf: bytearray, v: int, width: int) -> None:
    if v < 0 or v >= 1 << (8 * width):
        raise SerializeError(f"uint{8*width} out of range: {v}")
    buf += v.to_bytes(width, "little")


def read_uint(data: memoryview, off: int, width: int) -> Tuple[int, int]:
    if off + width > len(data):
        raise SerializeError("truncated uint")
    return int.from_bytes(data[off:off + width], "little"), off + width


def write_bytes(buf: bytearray, b: bytes) -> None:
    write_uvarint(buf, len(b))
    buf += b


def read_bytes(data: memoryview, off: int) -> Tuple[bytes, int]:
    n, off = read_uvarint(data, off)
    if off + n > len(data):
        raise SerializeError("truncated bytes")
    return bytes(data[off:off + n]), off + n


def write_uvarint(buf: bytearray, v: int) -> None:
    if v < 0:
        raise SerializeError("uvarint must be >= 0")
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


def read_uvarint(data: memoryview, off: int) -> Tuple[int, int]:
    """Decode a uvarint, rejecting non-minimal (overlong) encodings and
    values >= 2^64 so every value has exactly one byte representation."""
    shift = 0
    result = 0
    while True:
        if off >= len(data) or shift > 63:
            raise SerializeError("truncated/overlong uvarint")
        b = data[off]
        off += 1
        if shift == 63 and b > 1:
            raise SerializeError("uvarint exceeds 64 bits")
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            if b == 0 and shift != 0:
                raise SerializeError("non-minimal uvarint encoding")
            return result, off
        shift += 7


# ---------------- typed field codec ----------------
# Field specs: ("u8"|"u16"|"u32"|"u64"|"bool"|"bytes"|"str"|
#               ("list", spec) | ("fixed", spec, n) | ("map", kspec, vspec) |
#               ("opt", spec) | ("msg", cls))

def encode_value(buf: bytearray, spec: Any, v: Any) -> None:
    if spec == "u8":
        write_uint(buf, v, 1)
    elif spec == "u16":
        write_uint(buf, v, 2)
    elif spec == "u32":
        write_uint(buf, v, 4)
    elif spec == "u64":
        write_uint(buf, v, 8)
    elif spec == "i64":
        if not -(1 << 63) <= v < 1 << 63:
            raise SerializeError(f"i64 out of range: {v}")
        write_uint(buf, v & 0xFFFFFFFFFFFFFFFF, 8)
    elif spec == "bool":
        buf.append(1 if v else 0)
    elif spec == "bytes":
        write_bytes(buf, v)
    elif spec == "str":
        write_bytes(buf, v.encode("utf-8"))
    elif isinstance(spec, tuple):
        tag = spec[0]
        if tag == "list":
            write_uvarint(buf, len(v))
            for item in v:
                encode_value(buf, spec[1], item)
        elif tag == "fixed":
            if len(v) != spec[2]:
                raise SerializeError(f"fixed list length {len(v)} != {spec[2]}")
            for item in v:
                encode_value(buf, spec[1], item)
        elif tag == "map":
            write_uvarint(buf, len(v))
            for k in sorted(v):
                encode_value(buf, spec[1], k)
                encode_value(buf, spec[2], v[k])
        elif tag == "pair":
            # CMF `kvpair` — ordered 2-tuple (order-preserving, unlike map)
            encode_value(buf, spec[1], v[0])
            encode_value(buf, spec[2], v[1])
        elif tag == "opt":
            if v is None:
                buf.append(0)
            else:
                buf.append(1)
                encode_value(buf, spec[1], v)
        elif tag == "msg":
            encode_msg_into(buf, v)
        else:
            raise SerializeError(f"bad spec {spec}")
    else:
        raise SerializeError(f"bad spec {spec}")


def decode_value(data: memoryview, off: int, spec: Any) -> Tuple[Any, int]:
    if spec == "u8":
        return read_uint(data, off, 1)
    if spec == "u16":
        return read_uint(data, off, 2)
    if spec == "u32":
        return read_uint(data, off, 4)
    if spec == "u64":
        return read_uint(data, off, 8)
    if spec == "i64":
        v, off = read_uint(data, off, 8)
        return v - (1 << 64) if v >= 1 << 63 else v, off
    if spec == "bool":
        v, off = read_uint(data, off, 1)
        return bool(v), off
    if spec == "bytes":
        return read_bytes(data, off)
    if spec == "str":
        b, off = read_bytes(data, off)
        return b.decode("utf-8"), off
    if isinstance(spec, tuple):
        tag = spec[0]
        if tag == "list":
            n, off = read_uvarint(data, off)
            out = []
            for _ in range(n):
                v, off = decode_value(data, off, spec[1])
                out.append(v)
            return out, off
        if tag == "fixed":
            out = []
            for _ in range(spec[2]):
                v, off = decode_value(data, off, spec[1])
                out.append(v)
            return out, off
        if tag == "map":
            n, off = read_uvarint(data, off)
            out = {}
            for _ in range(n):
                k, off = decode_value(data, off, spec[1])
                v, off = decode_value(data, off, spec[2])
                out[k] = v
            return out, off
        if tag == "pair":
            a, off = decode_value(data, off, spec[1])
            b, off = decode_value(data, off, spec[2])
            return (a, b), off
        if tag == "opt":
            flag, off = read_uint(data, off, 1)
            if not flag:
                return None, off
            return decode_value(data, off, spec[1])
        if tag == "msg":
            return decode_msg_from(data, off, spec[1])
    raise SerializeError(f"bad spec {spec}")


# ---------------- dataclass message codec ----------------
# A serializable message is a dataclass with a class attr SPEC:
#   SPEC = [("field_name", spec), ...]  in canonical field order.
#
# Hot path: the generic SPEC walk (a dict-dispatch + function call per
# field) was a top profiler entry on the consensus dispatcher, so each
# message class gets a GENERATED encoder/decoder compiled once and
# cached — fixed-width ints, bool, bytes, str and list<bytes> are
# inlined; every other spec shape falls back to the interpretive
# encode_value/decode_value (identical wire format either way, covered
# by the same round-trip tests).

_INT_WIDTH = {"u8": 1, "u16": 2, "u32": 4, "u64": 8}
_ENC_CACHE: Dict[type, Any] = {}
_DEC_CACHE: Dict[type, Any] = {}


def _compile_encoder(cls: Type):
    specs = [s for _, s in cls.SPEC]
    lines = ["def _enc(buf, msg):"]
    for i, (name, spec) in enumerate(cls.SPEC):
        v = f"_v{i}"
        lines.append(f"    {v} = msg.{name}")
        if spec in _INT_WIDTH:
            w = _INT_WIDTH[spec]
            lines += [
                f"    if {v} < 0 or {v} >= {1 << (8 * w)}:",
                f"        raise SerializeError('uint{8*w} out of range: "
                f"%r' % ({v},))",
                f"    buf += {v}.to_bytes({w}, 'little')",
            ]
        elif spec == "i64":
            lines += [
                f"    if not {-(1 << 63)} <= {v} < {1 << 63}:",
                f"        raise SerializeError('i64 out of range: "
                f"%r' % ({v},))",
                f"    buf += ({v} & {(1 << 64) - 1}).to_bytes(8, 'little')",
            ]
        elif spec == "bool":
            lines.append(f"    buf.append(1 if {v} else 0)")
        elif spec == "bytes":
            lines += [f"    write_uvarint(buf, len({v}))",
                      f"    buf += {v}"]
        elif spec == "str":
            lines += [f"    {v} = {v}.encode('utf-8')",
                      f"    write_uvarint(buf, len({v}))",
                      f"    buf += {v}"]
        elif spec == ("list", "bytes"):
            lines += [f"    write_uvarint(buf, len({v}))",
                      f"    for _it in {v}:",
                      "        write_uvarint(buf, len(_it))",
                      "        buf += _it"]
        else:
            lines.append(f"    encode_value(buf, _specs[{i}], {v})")
    lines.append("    return None")
    ns = {"_specs": specs, "encode_value": encode_value,
          "write_uvarint": write_uvarint, "SerializeError": SerializeError}
    exec("\n".join(lines), ns)  # noqa: S102 — codegen from static SPECs
    return ns["_enc"]


def _compile_decoder(cls: Type):
    specs = [s for _, s in cls.SPEC]
    names = [n for n, _ in cls.SPEC]
    lines = ["def _dec(data, off):",
             "    _n = len(data)"]
    for i, (name, spec) in enumerate(cls.SPEC):
        v = f"_v{i}"
        if spec in _INT_WIDTH:
            w = _INT_WIDTH[spec]
            lines += [
                f"    if off + {w} > _n:",
                "        raise SerializeError('truncated uint')",
                f"    {v} = int.from_bytes(data[off:off + {w}], 'little')",
                f"    off += {w}",
            ]
        elif spec == "i64":
            lines += [
                "    if off + 8 > _n:",
                "        raise SerializeError('truncated uint')",
                f"    {v} = int.from_bytes(data[off:off + 8], 'little')",
                "    off += 8",
                f"    if {v} >= {1 << 63}:",
                f"        {v} -= {1 << 64}",
            ]
        elif spec == "bool":
            lines += [
                "    if off >= _n:",
                "        raise SerializeError('truncated uint')",
                f"    {v} = bool(data[off]); off += 1",
            ]
        elif spec in ("bytes", "str"):
            lines += [
                "    _ln, off = read_uvarint(data, off)",
                "    if off + _ln > _n:",
                "        raise SerializeError('truncated bytes')",
                f"    {v} = bytes(data[off:off + _ln]); off += _ln",
            ]
            if spec == "str":
                lines.append(f"    {v} = {v}.decode('utf-8')")
        elif spec == ("list", "bytes"):
            lines += [
                "    _cnt, off = read_uvarint(data, off)",
                f"    {v} = []",
                "    for _ in range(_cnt):",
                "        _ln, off = read_uvarint(data, off)",
                "        if off + _ln > _n:",
                "            raise SerializeError('truncated bytes')",
                f"        {v}.append(bytes(data[off:off + _ln]))",
                "        off += _ln",
            ]
        else:
            lines.append(
                f"    {v}, off = decode_value(data, off, _specs[{i}])")
    kwargs = ", ".join(f"{n}={f'_v{i}'}" for i, n in enumerate(names))
    lines.append(f"    return _cls({kwargs}), off")
    ns = {"_specs": specs, "_cls": cls, "decode_value": decode_value,
          "read_uvarint": read_uvarint, "SerializeError": SerializeError}
    exec("\n".join(lines), ns)  # noqa: S102 — codegen from static SPECs
    return ns["_dec"]


def encode_msg_into(buf: bytearray, msg: Any) -> None:
    enc = _ENC_CACHE.get(type(msg))
    if enc is None:
        if not is_dataclass(msg):
            raise SerializeError(f"not a message: {msg!r}")
        enc = _ENC_CACHE[type(msg)] = _compile_encoder(type(msg))
    enc(buf, msg)


def encode_msg(msg: Any) -> bytes:
    buf = bytearray()
    encode_msg_into(buf, msg)
    return bytes(buf)


def decode_msg_from(data: memoryview, off: int, cls: Type) -> Tuple[Any, int]:
    dec = _DEC_CACHE.get(cls)
    if dec is None:
        dec = _DEC_CACHE[cls] = _compile_decoder(cls)
    return dec(data, off)


def decode_msg(data: bytes, cls: Type) -> Any:
    msg, off = decode_msg_from(memoryview(data), 0, cls)
    if off != len(data):
        raise SerializeError(f"{cls.__name__}: {len(data)-off} trailing bytes")
    return msg
