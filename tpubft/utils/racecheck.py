"""Race / deadlock detection — the runtime counterpart of the
reference's sanitizer build modes.

The reference gates TSan/ASan/UBSan at build time
(/root/reference/CMakeLists.txt:30-32 `THREADCHECK`/`LEAKCHECK`/
`UNDEFINED_BEHAVIOR_CHECK`). Python has no compile modes, so the
equivalent here is runtime instrumentation, enabled the same way the
reference enables TSan — as a test-infrastructure switch
(`TPUBFT_THREADCHECK=1`):

* ``CheckedLock`` / ``LockOrderChecker`` — a lock wrapper that records the
  global lock-acquisition ORDER graph across threads; a cycle in that
  graph is a potential deadlock (the classic TSan lock-order-inversion
  report), raised immediately at the acquisition that closes the cycle.
* ``StallWatchdog`` — heartbeat monitor for the framework's critical
  threads (dispatcher, collector pool): a thread that stops beating past
  the threshold gets every Python thread's stack dumped to the log — the
  liveness side of race debugging (deadlocks manifest as stalls).
"""
from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Dict, Optional, Set, Tuple

from tpubft.utils.logging import get_logger

log = get_logger("racecheck")


def enabled() -> bool:
    return os.environ.get("TPUBFT_THREADCHECK", "") not in ("", "0")


class LockOrderViolation(RuntimeError):
    """A lock acquisition closed a cycle in the global lock-order graph."""


class LockOrderChecker:
    """Global acquisition-order graph over named locks. Edge A→B is
    recorded when B is acquired while A is held; a path B⇝A existing at
    that moment means two threads can deadlock — report at the exact
    acquisition site that introduces the inversion."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._edges: Dict[str, Set[str]] = {}
        self._edge_sites: Dict[Tuple[str, str], str] = {}
        self._held = threading.local()

    def _held_set(self):
        if not hasattr(self._held, "names"):
            self._held.names = []
        return self._held.names

    def _reaches(self, src: str, dst: str) -> bool:
        seen = set()
        stack = [src]
        while stack:
            cur = stack.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self._edges.get(cur, ()))
        return False

    def on_acquire(self, name: str) -> str:
        """Record the acquisition; returns the formatted site so the
        caller (CheckedLock) can reuse it for hold-time reports without
        a second stack capture."""
        held = self._held_set()
        site = "".join(traceback.format_stack(limit=4)[:-1])
        with self._mu:
            for h in held:
                if h == name:
                    continue
                if name not in self._edges.get(h, set()):
                    # adding h→name; inversion iff name⇝h already exists
                    if self._reaches(name, h):
                        first = self._edge_sites.get(
                            (name, h)) or "(recorded earlier)"
                        raise LockOrderViolation(
                            f"lock-order inversion: acquiring {name!r} "
                            f"while holding {h!r}, but the opposite order "
                            f"exists elsewhere.\nThis acquisition:\n{site}"
                            f"\nOpposite-order site:\n{first}")
                    self._edges.setdefault(h, set()).add(name)
                    self._edge_sites[(h, name)] = site
        held.append(name)
        return site

    def on_release(self, name: str) -> None:
        held = self._held_set()
        if name in held:
            held.remove(name)


_checker = LockOrderChecker()


def get_checker() -> LockOrderChecker:
    return _checker


# ---- held-too-long accounting -----------------------------------------
# Per-lock max-hold-time under TPUBFT_THREADCHECK: a "dispatcher briefly
# stalled" report becomes named-lock evidence — which lock, held from
# which acquisition site, for how long. Holders exceeding the threshold
# (TPUBFT_LOCK_HOLD_MS, default 100ms) are logged with the site.
_HOLD_ENV = "TPUBFT_LOCK_HOLD_MS"
_hold_mu = threading.Lock()
_hold_max: Dict[str, float] = {}          # lock name -> max hold (s)
_hold_reports = 0


def hold_threshold_s() -> float:
    try:
        return float(os.environ.get(_HOLD_ENV, "100")) / 1000.0
    except ValueError:
        return 0.1


def hold_stats() -> Dict[str, float]:
    """Snapshot of per-lock max hold time (seconds) recorded so far."""
    with _hold_mu:
        return dict(_hold_max)


def hold_report_count() -> int:
    with _hold_mu:
        return _hold_reports


def reset_hold_stats() -> None:
    global _hold_reports
    with _hold_mu:
        _hold_max.clear()
        _hold_reports = 0


class CheckedLock:
    """Drop-in threading.Lock/RLock wrapper feeding the order checker
    and the per-lock hold-time accounting. Zero-cost import path:
    construct via `make_lock(name)` which returns a plain lock when the
    check is disabled."""

    def __init__(self, name: str, reentrant: bool = False) -> None:
        self._name = name
        self._lock = threading.RLock() if reentrant else threading.Lock()
        # holder-only state: written while the underlying lock is held
        self._depth = 0
        self._acquired_at = 0.0
        self._site = ""

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            try:
                site = _checker.on_acquire(self._name)
            except LockOrderViolation:
                # report the POTENTIAL deadlock without creating a real
                # one: the underlying lock must not stay held by a thread
                # that unwound past its release
                self._lock.release()
                raise
            self._depth += 1
            if self._depth == 1:              # outermost acquisition
                self._acquired_at = time.monotonic()
                self._site = site
        return ok

    def release(self) -> None:
        global _hold_reports
        self._depth -= 1
        if self._depth == 0:
            held_s = time.monotonic() - self._acquired_at
            site = self._site
            over = held_s > hold_threshold_s()
            with _hold_mu:
                if held_s > _hold_max.get(self._name, 0.0):
                    _hold_max[self._name] = held_s
                if over:
                    _hold_reports += 1
            if over:
                log.warning(
                    "lock %r held %.1fms (> %.0fms threshold); "
                    "acquired at:\n%s", self._name, held_s * 1e3,
                    hold_threshold_s() * 1e3, site)
        _checker.on_release(self._name)
        self._lock.release()

    def __enter__(self) -> "CheckedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def make_lock(name: str, reentrant: bool = False):
    """Project-wide lock constructor: instrumented under
    TPUBFT_THREADCHECK, plain otherwise."""
    if enabled():
        return CheckedLock(name, reentrant)
    return threading.RLock() if reentrant else threading.Lock()


def make_condition(name: str) -> threading.Condition:
    """Project-wide Condition constructor: a `threading.Condition` over
    a `CheckedLock` under TPUBFT_THREADCHECK (every acquire/release —
    including wait()'s release/re-acquire cycle — feeds the lock-order
    graph and the hold-time accounting, like any make_lock site), a
    plain Condition otherwise. Condition's ownership probe
    (`acquire(False)` try/release) composes with CheckedLock: a failed
    probe records nothing."""
    if enabled():
        return threading.Condition(CheckedLock(name))
    return threading.Condition()


class StallWatchdog:
    """Heartbeat-monitored liveness: critical loops call `beat(name)`;
    a beat older than `threshold_s` triggers one full-process stack dump
    (throttled) so deadlocks/stalls are diagnosable post-hoc."""

    def __init__(self, threshold_s: float = 30.0,
                 poll_s: float = 5.0) -> None:
        self.threshold_s = threshold_s
        self.poll_s = poll_s
        self._beats: Dict[str, float] = {}
        self._mu = make_lock("racecheck.watchdog")
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._reported: Set[str] = set()
        self.stall_reports = 0

    def beat(self, name: str) -> None:
        if not self._running:
            self.start()              # first heartbeat arms the monitor
        with self._mu:
            self._beats[name] = time.monotonic()
            self._reported.discard(name)

    def unregister(self, name: str) -> None:
        with self._mu:
            self._beats.pop(name, None)
            self._reported.discard(name)

    def start(self) -> None:
        with self._mu:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="stall-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _run(self) -> None:
        while self._running:
            time.sleep(self.poll_s)
            now = time.monotonic()
            with self._mu:
                stalled = [n for n, t in self._beats.items()
                           if now - t > self.threshold_s
                           and n not in self._reported]
                for n in stalled:
                    self._reported.add(n)
            if stalled:
                self.stall_reports += len(stalled)
                self._dump(stalled)

    def _dump(self, stalled) -> None:
        lines = [f"STALL: no heartbeat from {stalled} for "
                 f">{self.threshold_s}s; all thread stacks follow"]
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in frames.items():
            lines.append(f"--- thread {names.get(ident, ident)} ---")
            lines.append("".join(traceback.format_stack(frame)))
        log.error("%s", "\n".join(lines))


_watchdog = StallWatchdog()


def get_watchdog() -> StallWatchdog:
    return _watchdog
