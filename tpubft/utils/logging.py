"""Structured logging with consensus MDC context.

Rebuild of the reference's logging layer (/root/reference/logging/ —
log4cplus with MDC keys; the SCOPED_MDC_* macros in ReplicaImp.cpp:405,
1067 tag every log line with the replica/seqnum/commit-path it concerns,
so a line is join-able per consensus instance).

Design: stdlib `logging` under the `tpubft.*` namespace plus a
thread-local mapped diagnostic context (MDC). Replica dispatcher threads
pin `replica=<id>` once (sticky); the message-dispatch entry point wraps
each handler call in an `mdc_scope(view=…, seq=…)` so everything logged
inside carries the consensus coordinates without the handlers having to
thread them through — one hook point, exactly the reference's scoped-MDC
pattern.

Quiet by default (WARNING, like any library); processes opt in with
`configure()` or the TPUBFT_LOG env var (e.g. TPUBFT_LOG=debug).
"""
from __future__ import annotations

import logging
import os
import sys
import threading
from typing import Optional

_tls = threading.local()
_MISSING = object()


def mdc() -> dict:
    """This thread's current diagnostic context."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        ctx = _tls.ctx = {}
    return ctx


def set_mdc(**kv) -> None:
    """Sticky context for this thread (e.g. replica=3 at thread start)."""
    mdc().update(kv)


class mdc_scope:
    """Scoped MDC keys (reference SCOPED_MDC_SEQ_NUM etc.): values are
    restored on exit, so nesting works."""

    def __init__(self, **kv):
        self._kv = kv
        self._saved = {}

    def __enter__(self):
        ctx = mdc()
        for k, v in self._kv.items():
            self._saved[k] = ctx.get(k, _MISSING)
            ctx[k] = v
        return self

    def __exit__(self, *exc):
        ctx = mdc()
        for k, old in self._saved.items():
            if old is _MISSING:
                ctx.pop(k, None)
            else:
                ctx[k] = old
        return False


class _MdcFilter(logging.Filter):
    """Injects the rendered MDC into every record as %(mdc)s."""

    def filter(self, record: logging.LogRecord) -> bool:
        ctx = mdc()
        record.mdc = (" ".join(f"{k}={v}" for k, v in ctx.items())
                      if ctx else "-")
        return True


_FORMAT = "%(asctime)s %(levelname).1s [%(mdc)s] %(name)s: %(message)s"
# NOTE: the MDC filter rides on the HANDLER (configure() attaches it) —
# a logger-level filter would not apply to records created on child
# loggers, so handler-level is the only placement that works
_root = logging.getLogger("tpubft")
_configured = False


def get_logger(name: str) -> logging.Logger:
    """Namespaced logger; `name` is the subsystem (e.g. "replica")."""
    return logging.getLogger(f"tpubft.{name}")


def configure(level: Optional[str] = None, stream=None,
              filename: Optional[str] = None) -> None:
    """Attach a handler with the MDC format to the tpubft namespace.
    Level resolution: explicit arg > TPUBFT_LOG env > WARNING."""
    global _configured
    level = level or os.environ.get("TPUBFT_LOG", "warning")
    lvl = getattr(logging, str(level).upper(), logging.WARNING)
    handler: logging.Handler
    if filename:
        handler = logging.FileHandler(filename)
    else:
        handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT))
    handler.addFilter(_MdcFilter())
    # replace, don't stack: configure() may run twice (env-var autoconfig
    # at import + an app's explicit call) and must not duplicate lines
    for old in list(_root.handlers):
        _root.removeHandler(old)
    _root.addHandler(handler)
    _root.setLevel(lvl)
    _root.propagate = False
    _configured = True


# processes that never call configure() still get MDC-tagged lines out of
# TPUBFT_LOG=… without code changes (tests stay silent: default WARNING
# with no env var set emits nothing below warnings)
if os.environ.get("TPUBFT_LOG"):
    configure()
