"""Tracing — span propagation across the protocol pipeline.

Rebuild of the reference's OpenTracing integration
(/root/reference/util/include/OpenTracing.hpp; span context embedded in
messages via MessageBase::spanContext<T>(), child spans per protocol
stage — ReplicaImp.cpp:409-413,1070): spans carry (trace_id, span_id,
parent) plus timing; contexts serialize to a compact string that rides
the ClientRequestMsg `cid` field, so one client request is joinable
across every replica's logs and span exports. The exporter is pluggable
(in-memory ring for tests, log line by default — Jaeger's role).
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from tpubft.utils.racecheck import make_lock


@dataclass
class SpanContext:
    trace_id: str
    span_id: str

    def serialize(self) -> str:
        return f"{self.trace_id}:{self.span_id}"

    @classmethod
    def parse(cls, s: str) -> Optional["SpanContext"]:
        parts = s.split(":")
        if len(parts) != 2 or not all(parts):
            return None
        return cls(trace_id=parts[0], span_id=parts[1])


@dataclass
class Span:
    name: str
    context: SpanContext
    parent_span_id: Optional[str]
    # durations are timed on the MONOTONIC clock: a wall-clock step
    # (NTP slew, operator date set) must never yield negative/garbage
    # span durations. `epoch` is the one wall-clock tag per span, taken
    # at start, for cross-replica alignment of exported traces.
    start: float = field(default_factory=time.monotonic)
    end: Optional[float] = None
    epoch: float = field(default_factory=time.time)
    tags: Dict[str, str] = field(default_factory=dict)
    _tracer: Optional["Tracer"] = field(default=None, repr=False,
                                        compare=False)

    def set_tag(self, k: str, v) -> "Span":
        self.tags[k] = str(v)
        return self

    @property
    def duration_s(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def finish(self) -> None:
        self.end = time.monotonic()
        if self._tracer is not None:
            self._tracer._export(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()


class Tracer:
    """Process tracer with a bounded in-memory export ring (exporters can
    be attached; the ring is what tests and the diagnostics server read)."""

    RING = 2048

    def __init__(self) -> None:
        self._lock = make_lock("tracer")
        self._ring: List[Span] = []
        self._exporters: List[Callable[[Span], None]] = []
        self._counter = 0

    def _next_id(self) -> str:
        with self._lock:
            self._counter += 1
            return f"{os.getpid():x}-{self._counter:x}"

    def start_span(self, name: str,
                   parent: Optional[SpanContext] = None,
                   trace_id: Optional[str] = None,
                   tags: Optional[Dict[str, object]] = None) -> Span:
        tid = (parent.trace_id if parent
               else trace_id if trace_id else self._next_id())
        ctx = SpanContext(trace_id=tid, span_id=self._next_id())
        span = Span(name=name, context=ctx,
                    parent_span_id=parent.span_id if parent else None,
                    _tracer=self)
        for k, v in (tags or {}).items():
            span.set_tag(k, v)
        return span

    def add_exporter(self, fn: Callable[[Span], None]) -> None:
        with self._lock:
            self._exporters.append(fn)

    def _export(self, span: Span) -> None:
        # exporters snapshotted under the same lock that add_exporter
        # appends under: a concurrent registration must never race the
        # list while a finishing span iterates it
        with self._lock:
            self._ring.append(span)
            if len(self._ring) > self.RING:
                del self._ring[:len(self._ring) - self.RING]
            exporters = list(self._exporters)
        for fn in exporters:
            try:
                fn(span)
            except Exception:  # noqa: BLE001 — exporters must not crash
                pass

    def finished_spans(self, trace_id: Optional[str] = None) -> List[Span]:
        with self._lock:
            spans = list(self._ring)
        if trace_id is not None:
            spans = [s for s in spans if s.context.trace_id == trace_id]
        return spans


_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer
