"""Metrics components: counters / gauges / statuses with aggregation.

TPU-native rebuild of the reference's concordMetrics
(/root/reference/util/include/Metrics.hpp): named Components own counters,
gauges, and statuses; an Aggregator snapshots all components to JSON. A
lightweight UDP metrics server (reference util/include/MetricsServer.hpp:46)
serves snapshots to test harnesses (Apollo-equivalent polls it).
"""
from __future__ import annotations

import json
import socket
import threading
import time
from collections import deque
from typing import Dict, Optional

from tpubft.utils.racecheck import make_lock


class Meter:
    """Trailing-window rate estimator behind throughput gauges
    (st_blocks_per_sec / st_bytes_per_sec): mark(n) on the hot path,
    rate() -> events per second over the last `window_s` seconds. Marked
    from the dispatcher thread, read by metric scrapers — locked like
    Counter."""

    __slots__ = ("_window", "_events", "_lock")

    def __init__(self, window_s: float = 5.0) -> None:
        self._window = window_s
        self._events: deque = deque()        # (monotonic ts, n)
        # make_lock (not a raw threading.Lock) so the tpulint
        # static-race pass and the runtime lock-order graph both see
        # it; a leaf lock — nothing is acquired while it is held
        self._lock = make_lock("metrics.meter")

    def _trim(self, now: float) -> None:
        horizon = now - self._window
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()

    def mark(self, n: int = 1) -> None:
        now = time.monotonic()
        with self._lock:
            self._events.append((now, n))
            self._trim(now)

    def rate(self) -> float:
        now = time.monotonic()
        with self._lock:
            self._trim(now)
            if not self._events:
                return 0.0
            total = sum(n for _, n in self._events)
            span = max(now - self._events[0][0], 0.05)
            return total / span


class Counter:
    """Incremented from the dispatcher AND crypto worker threads (async
    verification), so the read-modify-write takes a lock."""
    __slots__ = ("value", "_lock")

    def __init__(self) -> None:
        self.value = 0
        self._lock = make_lock("metrics.counter")   # leaf lock (see Meter)

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    __slots__ = ("value",)

    def __init__(self, v: int = 0) -> None:
        self.value = v

    def set(self, v: int) -> None:
        self.value = v


class Status:
    __slots__ = ("value",)

    def __init__(self, v: str = "") -> None:
        self.value = v

    def set(self, v: str) -> None:
        self.value = v


class Component:
    """A named bundle of metrics, registered with an Aggregator."""

    def __init__(self, name: str, aggregator: Optional["Aggregator"] = None):
        self.name = name
        self.counters: Dict[str, Counter] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.statuses: Dict[str, Status] = {}
        if aggregator is not None:
            aggregator.register(self)

    def register_counter(self, name: str) -> Counter:
        return self.counters.setdefault(name, Counter())

    def register_gauge(self, name: str, v: int = 0) -> Gauge:
        return self.gauges.setdefault(name, Gauge(v))

    def register_status(self, name: str, v: str = "") -> Status:
        return self.statuses.setdefault(name, Status(v))

    def snapshot(self) -> Dict:
        return {
            "counters": {k: c.value for k, c in self.counters.items()},
            "gauges": {k: g.value for k, g in self.gauges.items()},
            "statuses": {k: s.value for k, s in self.statuses.items()},
        }


class Aggregator:
    def __init__(self) -> None:
        self._components: Dict[str, Component] = {}
        self._lock = threading.Lock()

    def register(self, c: Component) -> None:
        with self._lock:
            self._components[c.name] = c

    def get(self, component: str, kind: str, name: str):
        with self._lock:
            c = self._components[component]
        return c.snapshot()[kind][name]

    def snapshot(self) -> Dict:
        with self._lock:
            return {name: c.snapshot() for name, c in self._components.items()}

    def to_json(self) -> str:
        return json.dumps({"ts": time.time(), "components": self.snapshot()})


class UdpMetricsServer:
    """Serves aggregator JSON snapshots over UDP — any datagram gets a reply.

    Mirrors the reference's UDP metrics server that the Apollo harness polls
    (/root/reference/util/include/MetricsServer.hpp:46, tests/apollo/util/bft_metrics.py).
    """

    def __init__(self, aggregator: Aggregator, port: int = 0, host: str = "127.0.0.1"):
        self._agg = aggregator
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, port))
        self._sock.settimeout(0.2)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                _, addr = self._sock.recvfrom(65536)
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                self._sock.sendto(self._agg.to_json().encode(), addr)
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        self._sock.close()


def _prom_name(*parts: str) -> str:
    out = "_".join(parts)
    return "".join(ch if ch.isalnum() or ch == "_" else "_" for ch in out)


def prometheus_exposition(agg: Aggregator, prefix: str = "tpubft") -> str:
    """Render an aggregator snapshot in the Prometheus text exposition
    format (the role of the reference's Prometheus bridge,
    util/include/concord_prometheus_metrics.hpp): counters and gauges
    become `<prefix>_<component>_<name>`; statuses become an info-style
    gauge with the value as a label."""
    lines = []
    for comp, snap in sorted(agg.snapshot().items()):
        for name, v in sorted(snap.get("counters", {}).items()):
            m = _prom_name(prefix, comp, name)
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {v}")
        for name, v in sorted(snap.get("gauges", {}).items()):
            m = _prom_name(prefix, comp, name)
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {v}")
        for name, v in sorted(snap.get("statuses", {}).items()):
            m = _prom_name(prefix, comp, name, "info")
            val = (str(v).replace("\\", "\\\\").replace('"', '\\"')
                   .replace("\n", "\\n"))
            lines.append(f"# TYPE {m} gauge")
            lines.append(f'{m}{{value="{val}"}} 1')
    return "\n".join(lines) + "\n"


class PrometheusEndpoint:
    """Minimal HTTP /metrics endpoint serving the exposition format —
    scrapeable by a real Prometheus. One thread, stdlib only."""

    def __init__(self, aggregator: Aggregator, port: int = 0,
                 host: str = "127.0.0.1", prefix: str = "tpubft"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        agg = aggregator

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path.split("?")[0] != "/metrics":
                    body = b"see /metrics"
                    self.send_response(404)
                else:
                    body = prometheus_exposition(agg, prefix).encode()
                    self.send_response(200)
                    self.send_header("content-type",
                                     "text/plain; version=0.0.4")
                self.send_header("content-length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="prometheus")
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
