"""Closed-loop autotuner — the actuator half of the telemetry plane.

PRs 5-8 built the sensors (breaker verdicts, HealthMonitor verdicts,
flight-recorder stage histograms, kernel-profiler batch stats) but every
performance actuator stayed a static knob hand-benched per machine:
flush windows, batch caps, accumulation depth, admission watermarks, the
ECDSA device/host crossover. This package closes the loop (ROADMAP item
8): a per-replica `TuningController` thread periodically snapshots the
telemetry plane and drives registered `Knob`s through per-knob policies,
within operator-configured bounds, with hysteresis and cooldown so one
noisy sample never flips a knob, and with one hard rule — when the
HealthMonitor leaves `healthy` or any breaker opens, every unpinned knob
backs off to its configured default (the controller never fights the
degradation plane).

Layout:

  * ``knobs.py``     — `Knob` + `KnobRegistry` (bounds, step policy,
                       hysteresis/cooldown bookkeeping, frozen pins,
                       seed-file I/O);
  * ``policies.py``  — the per-knob direction policies (grow/shrink/
                       hold) over a `Telemetry` snapshot;
  * ``controller.py``— the `TuningController` loop, decision log,
                       `tuning` metrics component, `EV_TUNE` flight
                       events, `status get tuning` payload;
  * ``wiring.py``    — `build_replica_tuning(replica, cfg)`: the knob
                       catalog for one replica, bound to its live
                       actuator seams.

See docs/OPERATIONS.md "Autotuning" for the knob catalog and the
operator workflow (pinning, seed files, reading decisions).
"""
from tpubft.tuning.knobs import (Knob, KnobRegistry, load_seed,
                                 write_seed)
from tpubft.tuning.controller import TuningController
from tpubft.tuning.wiring import build_replica_tuning

__all__ = ["Knob", "KnobRegistry", "TuningController",
           "build_replica_tuning", "load_seed", "write_seed"]
