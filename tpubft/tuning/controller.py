"""The tuning control loop.

One `TuningController` per replica: a daemon thread that, every
`interval_s`, snapshots the telemetry plane (flight-recorder stage
summary, kernel profiler, breaker registry, health verdict, queue
depths, SigManager counters) into a `Telemetry`, and drives the knob
registry:

  * **degraded rule first** — when the health verdict leaves `healthy`
    or any breaker is not CLOSED, every unpinned knob resets to its
    configured default in one pass and tuning stops until the plane has
    been healthy again for `warmup_polls` consecutive intervals. The
    controller never fights the degradation plane: an OPEN breaker
    means the sensors are measuring the fallback path, and tuning on
    fallback costs would chase a phantom optimum.
  * **policy votes** — healthy and warmed up, each knob's policy votes
    a direction; the registry's hysteresis + cooldown turn sustained
    votes into bounded steps (`Knob.stepped`, clamped to [lo, hi]).

Every applied change is one decision: an `EV_TUNE` flight event
(seq = knob id, view = old value, arg = new value), a decision-log
entry (bounded deque, served by `status get tuning` and attached to
flight dumps via the recorder's dump-provider hook so tpuprof can join
knob changes to stage timelines), and the per-knob `knob_<name>` gauge
on the `tuning` metrics component.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from tpubft.tuning.knobs import KnobRegistry
from tpubft.tuning.policies import Policy, Telemetry
from tpubft.utils import breaker as breaker_mod
from tpubft.utils import flight
from tpubft.utils.logging import get_logger
from tpubft.utils.metrics import Aggregator, Component

log = get_logger("tuning")

DECISION_KEEP = 256


class TuningController:
    def __init__(self, registry: KnobRegistry, name: str = "tuning",
                 interval_s: float = 1.0,
                 aggregator: Optional[Aggregator] = None,
                 rid: int = -1,
                 warmup_polls: int = 2,
                 stages_fn: Optional[Callable[[], Dict]] = None,
                 kernels_fn: Optional[Callable[[], Dict]] = None,
                 health_fn: Optional[Callable[[], str]] = None,
                 depths_fn: Optional[Callable[[], Dict]] = None,
                 counters_fn: Optional[Callable[[], Dict]] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.registry = registry
        self._name = name
        self.interval_s = interval_s
        self._rid = rid
        self.warmup_polls = max(1, warmup_polls)
        self._stages_fn = stages_fn
        self._kernels_fn = kernels_fn
        self._health_fn = health_fn
        self._depths_fn = depths_fn
        self._counters_fn = counters_fn
        self._clock = clock
        self._policies: Dict[str, Policy] = {}
        self._prev: Optional[Telemetry] = None
        self._prev_counters: Dict[str, float] = {}
        self._healthy_streak = 0
        self._backed_off = False
        self._decisions: "deque[Dict]" = deque(maxlen=DECISION_KEEP)
        self._mu = threading.Lock()        # decisions + prev snapshot
        self._running = False
        # Event-paced loop (NOT time.sleep): stop() must return
        # immediately — with four replicas per in-process cluster and
        # hundreds of cluster teardowns per test run, a sleeping loop's
        # up-to-interval join cost compounds into minutes
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

        self.metrics = Component("tuning", aggregator)
        self.m_steps = self.metrics.register_counter("tune_steps")
        self.m_resets = self.metrics.register_counter("tune_resets")
        self.m_polls = self.metrics.register_counter("tune_polls")
        self.m_active = self.metrics.register_gauge("tuning_active")
        self.m_verdict = self.metrics.register_status("last_verdict",
                                                      "healthy")
        self._gauges: Dict[str, object] = {}

    # ------------------------------------------------------------------
    # wiring
    # ------------------------------------------------------------------
    def add_policy(self, knob_name: str, policy: Policy) -> None:
        self._policies[knob_name] = policy
        g = self.metrics.register_gauge(f"knob_{knob_name}")
        g.set(self.registry.get(knob_name))
        self._gauges[knob_name] = g

    def track(self, knob_name: str) -> None:
        """Register a knob for metrics/catalog visibility without a
        policy (manual/pinned knobs still show in `status get tuning`
        and still reset on degradation)."""
        g = self.metrics.register_gauge(f"knob_{knob_name}")
        g.set(self.registry.get(knob_name))
        self._gauges[knob_name] = g

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._stop_evt.clear()
        self.m_active.set(1)
        flight.register_dump_provider(f"{self._name}", self.dump_state)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"tuner-{self._name}")
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        self._stop_evt.set()
        self.m_active.set(0)
        flight.unregister_dump_provider(f"{self._name}")
        t = self._thread
        if t is not None:
            t.join(timeout=2)
            self._thread = None

    def _run(self) -> None:
        flight.set_thread_rid(self._rid)
        while self._running:
            if self._stop_evt.wait(self.interval_s):
                return
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the tuner must outlive
                log.exception("tuning poll failed")  # anything it tunes

    # ------------------------------------------------------------------
    # sensor gather
    # ------------------------------------------------------------------
    def gather(self) -> Telemetry:
        # each sensor is isolated: a broken PERF sensor reads as "no
        # signal" (policies hold), but it must never mask the breaker
        # and health reads below — those decide the degraded rule, and
        # a shared try would fail OPEN as "healthy" exactly when the
        # telemetry plane is misbehaving
        tel = Telemetry()
        try:
            if self._stages_fn is not None:
                summary = self._stages_fn() or {}
                tel.stages = summary.get("stages", {})
                tel.completed_slots = int(
                    summary.get("finalized_total", 0))
        except Exception:  # noqa: BLE001
            log.exception("stage sensor failed")
        try:
            if self._kernels_fn is not None:
                tel.kernels = self._kernels_fn() or {}
        except Exception:  # noqa: BLE001
            log.exception("kernel sensor failed")
        try:
            if self._depths_fn is not None:
                tel.depths = self._depths_fn() or {}
        except Exception:  # noqa: BLE001
            log.exception("depth sensor failed")
        try:
            if self._counters_fn is not None:
                cur = {k: float(v)
                       for k, v in (self._counters_fn() or {}).items()}
                tel.counters = dict(cur)
                for k, v in cur.items():
                    tel.counters[f"{k}_delta"] = \
                        v - self._prev_counters.get(k, 0.0)
                self._prev_counters = cur
        except Exception:  # noqa: BLE001
            log.exception("counter sensor failed")
        # the degraded-rule inputs: a failure here fails SAFE (treated
        # as degraded), never open
        try:
            tel.breakers = breaker_mod.snapshot_all()
            if self._health_fn is not None:
                tel.health = self._health_fn() or "healthy"
        except Exception:  # noqa: BLE001
            log.exception("health sensor failed; treating as degraded")
            tel.health = "degraded"
        return tel

    def _degraded(self, tel: Telemetry) -> bool:
        if tel.health != "healthy":
            return True
        return any(b.get("state") != breaker_mod.CLOSED
                   for b in tel.breakers.values())

    # ------------------------------------------------------------------
    # the control step
    # ------------------------------------------------------------------
    def poll_once(self) -> List[Dict]:
        """One control interval; returns the decisions made (tests call
        this directly with stubbed sensors)."""
        self.m_polls.inc()
        tel = self.gather()
        self.m_verdict.set(tel.health)
        made: List[Dict] = []
        if self._degraded(tel):
            self._healthy_streak = 0
            if not self._backed_off:
                self._backed_off = True
                for name, old, new in self.registry.reset_to_defaults():
                    made.append(self._decide(name, old, new,
                                             "degraded-reset",
                                             tel.health))
                if made:
                    self.m_resets.inc()
        else:
            self._healthy_streak += 1
            self._backed_off = False
            if self._healthy_streak > self.warmup_polls:
                made.extend(self._evaluate(tel))
        with self._mu:
            self._prev = tel
        return made

    def _evaluate(self, tel: Telemetry) -> List[Dict]:
        with self._mu:
            prev = self._prev
        made = []
        for name, policy in self._policies.items():
            try:
                knob = self.registry.knob(name)
            except KeyError:
                continue
            try:
                direction = policy(tel, prev, knob)
            except Exception:  # noqa: BLE001 — a broken policy holds
                log.exception("policy for %s raised", name)
                continue
            if not self.registry.vote(name, direction):
                continue
            old = knob.value
            applied = self.registry.step(name, direction)
            if applied is not None:
                made.append(self._decide(name, old, applied, "policy",
                                         f"dir={direction:+d}"))
        return made

    def _decide(self, name: str, old: int, new: int, source: str,
                detail: str) -> Dict:
        flight.record(flight.EV_TUNE, seq=self.registry.knob_id(name),
                      view=int(old), arg=int(new))
        self.m_steps.inc()
        g = self._gauges.get(name)
        if g is not None:
            g.set(int(new))
        d = {"ts": time.time(), "knob": name, "old": int(old),
             "new": int(new), "source": source, "detail": detail}
        with self._mu:
            self._decisions.append(d)
        log.info("tune %s: %s %d -> %d (%s)", source, name, old, new,
                 detail)
        return d

    # ------------------------------------------------------------------
    # surfaces
    # ------------------------------------------------------------------
    def write_seed(self, path: str) -> Optional[str]:
        """Persist the converged operating point as a knob-registry
        seed file (ROADMAP 8d): every knob's CURRENT value, frozen pins
        preserved, in exactly the format `load_seed` re-baselines from
        — so the next boot starts warm at this host's measured optimum
        instead of re-walking from cold defaults. Called on clean
        replica shutdown when `autotune_seed_file` is configured; a
        write failure is logged, never raised (shutdown must finish)."""
        from tpubft.tuning.knobs import write_seed as _write
        snap = self.registry.snapshot()
        knobs = {name: ({"value": s["value"], "frozen": True}
                        if s["frozen"] else s["value"])
                 for name, s in snap.items()}
        try:
            return _write(path, knobs,
                          note=f"converged operating point written by "
                               f"{self._name} on clean shutdown")
        except Exception:  # noqa: BLE001 — see docstring
            log.exception("seed write-back to %s failed", path)
            return None

    def decisions(self, limit: int = 50) -> List[Dict]:
        with self._mu:
            return list(self._decisions)[-limit:]

    def state(self) -> Dict:
        with self._mu:
            prev = self._prev
        return {
            "rid": self._rid,
            "active": bool(self._running),
            "interval_s": self.interval_s,
            "healthy_streak": self._healthy_streak,
            "backed_off": self._backed_off,
            "last_verdict": (prev.health if prev is not None
                             else "healthy"),
            "knobs": self.registry.snapshot(),
            "knob_ids": {str(i): n
                         for i, n in self.registry.id_table().items()},
            "decisions": self.decisions(),
        }

    def dump_state(self) -> Dict:
        """Flight-dump provider payload: the decision log + knob values
        ride every dump artifact, so tpuprof can join EV_TUNE events
        (knob ids) to names and stage timelines."""
        return self.state()

    def render(self) -> str:
        """`status get tuning` payload."""
        return json.dumps(self.state(), sort_keys=True)
