"""Typed knob registry — the actuator surface the controller drives.

A `Knob` is one live tunable: a bounded integer value with a
multiplicative step policy, per-knob hysteresis (consecutive
same-direction policy votes required before a move) and cooldown
(minimum interval between moves), and a `frozen` pin that makes the
operator the only writer. The registry is the ONE mutation path: every
store goes through `KnobRegistry.set`, which clamps to bounds under the
registry lock and then pushes the applied value into the live actuator
via the knob's `apply_fn` (outside the lock — actuators take their own
locks, and the registry must never hold its lock across them).

Seed files let benchmarks hand a measured operating point to the next
process (`bench_msm_crossover --ecdsa` writes one instead of an
env-export line): JSON ``{"knobs": {name: value | {"value": v,
"frozen": true}}}``, loaded at replica wiring via
``ReplicaConfig.autotune_seed_file``. Unknown names are ignored with a
log line — a seed measured on one build must not wedge a newer one.
"""
from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from tpubft.utils.logging import get_logger
from tpubft.utils.racecheck import make_lock

log = get_logger("tuning")

GROW = 1
HOLD = 0
SHRINK = -1


@dataclass
class Knob:
    """One live tunable. `value` is read lock-free by hot paths that
    hold a reference (an int attribute read is atomic); every WRITE
    goes through `KnobRegistry.set`."""

    name: str
    value: int
    default: int
    lo: int
    hi: int
    # multiplicative step policy: grow multiplies by step_up, shrink by
    # step_down (always moving at least 1 so small values still step)
    step_up: float = 1.5
    step_down: float = 0.5
    # consecutive same-direction policy votes required before a move
    # (>= 2 means one noisy sample can never flip a knob)
    hysteresis: int = 2
    # minimum seconds between controller moves of this knob
    cooldown_s: float = 3.0
    # operator pin: policies and degraded resets never touch it
    frozen: bool = False
    # pushes an applied value into the live actuator (None = pull-style
    # consumers read knob.value / registry.get themselves)
    apply_fn: Optional[Callable[[int], None]] = None
    unit: str = ""
    # doc string for the catalog: which telemetry drives this knob
    sensor: str = ""
    # controller bookkeeping (registry-lock guarded). A never-moved
    # knob must never read as in-cooldown, whatever the monotonic
    # clock's origin — hence -inf, not 0.
    last_change_mono: float = float("-inf")
    changes: int = 0
    direction_flips: int = 0
    _last_move_dir: int = field(default=0, repr=False)
    _streak_dir: int = field(default=0, repr=False)
    _streak_n: int = field(default=0, repr=False)

    def clamp(self, v: int) -> int:
        return max(self.lo, min(self.hi, int(v)))

    def stepped(self, direction: int) -> int:
        """Next value in `direction` under the step policy (unclamped)."""
        if direction == GROW:
            return max(self.value + 1, int(self.value * self.step_up))
        if direction == SHRINK:
            return min(self.value - 1, int(self.value * self.step_down))
        return self.value

    def snapshot(self) -> Dict:
        return {"value": self.value, "default": self.default,
                "lo": self.lo, "hi": self.hi, "unit": self.unit,
                "frozen": self.frozen, "sensor": self.sensor,
                "changes": self.changes,
                "direction_flips": self.direction_flips,
                "hysteresis": self.hysteresis,
                "cooldown_s": self.cooldown_s}


class KnobRegistry:
    """All knobs of one replica. Thread discipline: values mutate ONLY
    inside `set` under the registry lock (tpulint's static-race pass
    sees the lexical make_lock region; a knob store anywhere else is a
    caught finding), apply callbacks run after release."""

    def __init__(self, name: str = "tuning",
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._name = name
        self._clock = clock
        self._mu = make_lock(f"{name}.knobs")
        self._knobs: Dict[str, Knob] = {}
        self._ids: Dict[str, int] = {}     # flight-event knob ids

    # ------------------------------------------------------------------
    # registration / lookup
    # ------------------------------------------------------------------
    def register(self, knob: Knob) -> Knob:
        with self._mu:
            if knob.name in self._knobs:
                raise ValueError(f"knob {knob.name!r} already registered")
            knob.value = knob.clamp(knob.value)
            self._knobs[knob.name] = knob
            self._ids[knob.name] = len(self._ids) + 1
        return knob

    def knob(self, name: str) -> Knob:
        with self._mu:
            return self._knobs[name]

    def get(self, name: str, default: Optional[int] = None) -> int:
        with self._mu:
            k = self._knobs.get(name)
            if k is None:
                if default is None:
                    raise KeyError(name)
                return default
            return k.value

    def names(self) -> List[str]:
        with self._mu:
            return list(self._knobs)

    def knob_id(self, name: str) -> int:
        with self._mu:
            return self._ids.get(name, 0)

    def id_table(self) -> Dict[int, str]:
        with self._mu:
            return {v: k for k, v in self._ids.items()}

    # ------------------------------------------------------------------
    # mutation — the one store path
    # ------------------------------------------------------------------
    def set(self, name: str, value: int, source: str = "manual",
            force: bool = False) -> Optional[int]:
        """Clamp-and-store; returns the applied value, or None when the
        store was a no-op (same value, unknown knob, or a frozen knob
        and the caller is not the operator `force`)."""
        with self._mu:
            k = self._knobs.get(name)
            if k is None:
                return None
            if k.frozen and not force:
                return None
            v = k.clamp(value)
            old = k.value
            if v == old:
                return None
            k.value = v
            k.changes += 1
            direction = GROW if v > old else SHRINK
            if k._last_move_dir and direction != k._last_move_dir:
                k.direction_flips += 1
            k._last_move_dir = direction
            k.last_change_mono = self._clock()
            apply_fn = k.apply_fn
        # outside the lock: actuators take their own locks, and the
        # registry lock must never nest over them (lock-order pass)
        if apply_fn is not None:
            try:
                apply_fn(v)
            except Exception:  # noqa: BLE001 — a failing actuator push
                log.exception("knob %s apply failed (value=%s)", name, v)
        return v

    def rebase_default(self, name: str, value: int) -> None:
        """Re-baseline a knob's default (the degraded-reset target) —
        a seeded measured operating point IS this host's default."""
        with self._mu:
            k = self._knobs[name]
            k.default = k.clamp(int(value))

    def freeze(self, name: str, value: Optional[int] = None) -> None:
        """Operator pin: optionally set, then stop every policy (and
        degraded reset) from moving this knob."""
        if value is not None:
            self.set(name, value, source="pin", force=True)
        with self._mu:
            self._knobs[name].frozen = True

    def unfreeze(self, name: str) -> None:
        with self._mu:
            self._knobs[name].frozen = False

    def reset_to_defaults(self, source: str = "degraded"
                          ) -> List[tuple]:
        """Back every unpinned knob off to its configured default (the
        degradation rule: never fight the health plane). Returns the
        (name, old, new) changes actually made."""
        with self._mu:
            todo = [(k.name, k.value, k.default)
                    for k in self._knobs.values()
                    if not k.frozen and k.value != k.default]
        changes = []
        for name, old, default in todo:
            applied = self.set(name, default, source=source)
            if applied is not None:
                changes.append((name, old, applied))
        return changes

    # ------------------------------------------------------------------
    # hysteresis / cooldown bookkeeping (controller-side helpers; under
    # the registry lock so vote state is consistent with values)
    # ------------------------------------------------------------------
    def vote(self, name: str, direction: int) -> bool:
        """Record one policy vote for `name`; True when the knob is due
        a move: `hysteresis` consecutive same-direction votes AND past
        its cooldown AND not frozen. HOLD votes reset the streak."""
        with self._mu:
            k = self._knobs.get(name)
            if k is None or k.frozen:
                return False
            if direction == HOLD:
                k._streak_dir = 0
                k._streak_n = 0
                return False
            if direction == k._streak_dir:
                k._streak_n += 1
            else:
                k._streak_dir = direction
                k._streak_n = 1
            if k._streak_n < k.hysteresis:
                return False
            if self._clock() - k.last_change_mono < k.cooldown_s:
                return False
            return True

    def step(self, name: str, direction: int,
             source: str = "policy") -> Optional[int]:
        """Apply one policy step in `direction` (already voted through
        `vote`). Returns the applied value or None (clamped no-op)."""
        with self._mu:
            k = self._knobs.get(name)
            if k is None:
                return None
            target = k.stepped(direction)
        return self.set(name, target, source=source)

    def snapshot(self) -> Dict[str, Dict]:
        with self._mu:
            return {name: k.snapshot() for name, k in self._knobs.items()}


# ----------------------------------------------------------------------
# seed-file I/O (bench → replica handoff)
# ----------------------------------------------------------------------
def write_seed(path: str, knobs: Dict[str, object],
               note: str = "") -> str:
    """Write a knob-registry seed file: {"knobs": {name: value |
    {"value": v, "frozen": bool}}}. Returns the path."""
    payload = {"knobs": knobs}
    if note:
        payload["note"] = note
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
    return path


def load_seed(registry: KnobRegistry, path: str) -> int:
    """Apply a seed file to `registry`; returns how many knobs were
    seeded. Unknown knob names are logged and skipped (forward/backward
    compatible), malformed files raise (a requested seed that cannot
    parse is an operator error, not a default)."""
    with open(path, "r", encoding="utf-8") as fh:
        payload = json.load(fh)
    entries = payload.get("knobs", {})
    if not isinstance(entries, dict):
        raise ValueError(f"seed file {path}: 'knobs' must be an object")
    seeded = 0
    known = set(registry.names())
    for name, spec in entries.items():
        if name not in known:
            log.warning("seed %s: unknown knob %r ignored", path, name)
            continue
        frozen = False
        if isinstance(spec, dict):
            value = spec.get("value")
            frozen = bool(spec.get("frozen", False))
        else:
            value = spec
        if value is not None:
            registry.set(name, int(value), source="seed", force=True)
            # seeding re-baselines the degraded-reset target too: a
            # measured operating point IS this host's default
            registry.rebase_default(name, int(value))
            seeded += 1
        if frozen:
            registry.freeze(name)
    return seeded
