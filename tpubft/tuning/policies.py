"""Per-knob tuning policies — pure functions from telemetry to a
direction vote.

A policy never moves a knob itself: it votes GROW / SHRINK / HOLD each
controller interval, and the registry's hysteresis (consecutive
same-direction votes) + cooldown decide whether the vote becomes a
step. Policies therefore stay simple threshold rules over the measured
signals; the stability machinery lives in one place.

The shared doctrine (ISSUE 14 / ROADMAP item 8):

  * batch/flush knobs grow while the kernel profile shows falling
    per-item cost (amortization still improving) and shrink as soon as
    the latency-sensitive stage (`adm_wait` for the verify plane,
    `commit` for the combine plane) dominates the slot breakdown —
    batching is only worth the latency it buys back;
  * `execution_max_accumulation` shrinks when `exec` dominates the
    slot breakdown and grows back while the lane is deep and exec is
    cheap;
  * the ECDSA device/host crossover follows the measured per-item cost
    of the `ecdsa` kernel vs the batched host engine;
  * every policy HOLDs without fresh signal — an idle replica's knobs
    must not wander.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from tpubft.tuning.knobs import GROW, HOLD, SHRINK, Knob
from tpubft.utils.flight import PIPELINE_STAGES

# a stage "dominates" the slot breakdown past this fraction of the
# summed per-stage p50s
DOMINANT_FRAC = 0.5
# and is "cheap" below this fraction
MINOR_FRAC = 0.2
# per-item kernel cost is "falling" when the fresh interval's cost is
# at most this ratio of the previous interval's
FALLING_RATIO = 0.98
# device/host crossover moves only on a >=10% measured cost gap
CROSSOVER_MARGIN = 0.9


@dataclass
class Telemetry:
    """One controller interval's sensor snapshot (built by the
    controller; policies treat it read-only)."""

    stages: Dict[str, Dict] = field(default_factory=dict)
    kernels: Dict[str, Dict] = field(default_factory=dict)
    breakers: Dict[str, Dict] = field(default_factory=dict)
    health: str = "healthy"
    depths: Dict[str, int] = field(default_factory=dict)
    counters: Dict[str, float] = field(default_factory=dict)
    completed_slots: int = 0


Policy = Callable[[Telemetry, Optional[Telemetry], Knob], int]


# ----------------------------------------------------------------------
# signal helpers
# ----------------------------------------------------------------------
def fresh_slots(cur: Telemetry, prev: Optional[Telemetry]) -> int:
    if prev is None:
        return 0
    return max(0, cur.completed_slots - prev.completed_slots)


def stage_fraction(tel: Telemetry, stage: str) -> float:
    """`stage`'s share of the summed pipeline-stage p50s (0 when the
    breakdown is empty)."""
    total = 0.0
    for s in PIPELINE_STAGES:
        total += float(tel.stages.get(s, {}).get("p50_ms", 0.0))
    if total <= 0.0:
        return 0.0
    return float(tel.stages.get(stage, {}).get("p50_ms", 0.0)) / total


def kernel_per_item_us(tel: Telemetry, kind: str) -> Optional[float]:
    """Warm per-item cost of one kernel kind in µs (None until the
    profile has warm calls and a batch shape)."""
    st = tel.kernels.get(kind)
    if not st or st.get("calls", 0) < 2:
        return None
    batch_avg = float(st.get("batch_avg", 0.0))
    if batch_avg <= 0.0:
        return None
    return float(st.get("warm_avg_ms", 0.0)) * 1e3 / batch_avg


def kernel_calls(tel: Telemetry, kind: str) -> int:
    return int(tel.kernels.get(kind, {}).get("calls", 0))


def per_item_falling(cur: Telemetry, prev: Optional[Telemetry],
                     kind: str) -> bool:
    """True when the kernel's per-item cost this interval is at or
    below FALLING_RATIO of the previous interval's (amortization still
    paying off) — and there were fresh calls to measure it on."""
    if prev is None or kernel_calls(cur, kind) <= kernel_calls(prev, kind):
        return False
    a, b = kernel_per_item_us(cur, kind), kernel_per_item_us(prev, kind)
    if a is None or b is None or b <= 0.0:
        return False
    return a <= b * FALLING_RATIO


# ----------------------------------------------------------------------
# policy factories
# ----------------------------------------------------------------------
def batch_amortize_policy(kernel_kind: str,
                          latency_stage: str) -> Policy:
    """Flush windows and batch caps: shrink when `latency_stage`
    dominates the slot breakdown (batching is costing more latency than
    it amortizes), grow while the kernel's per-item cost is still
    falling, hold otherwise."""

    def policy(cur: Telemetry, prev: Optional[Telemetry],
               knob: Knob) -> int:
        if not fresh_slots(cur, prev):
            return HOLD
        if stage_fraction(cur, latency_stage) > DOMINANT_FRAC:
            return SHRINK
        if per_item_falling(cur, prev, kernel_kind):
            return GROW
        return HOLD

    return policy


def optimistic_combine_policy(inner: Policy) -> Policy:
    """Wrap the combine-plane amortization policy for the optimistic
    reply plane (ISSUE 18): once replies stop waiting on the combine,
    shrinking the flush window buys the client NOTHING — the cert_lag
    overlay (optimistic release → verified certificate) shows fresh
    samples exactly when certificates form off the critical path, so a
    SHRINK vote from the inner policy is downgraded to HOLD while that
    signal is fresh. GROW stays allowed: wider flush windows amortize
    the deferred combine even harder, which is the whole point."""

    def policy(cur: Telemetry, prev: Optional[Telemetry],
               knob: Knob) -> int:
        vote = inner(cur, prev, knob)
        if vote != SHRINK or prev is None:
            return vote
        fresh_lag = (int(cur.stages.get("cert_lag", {}).get("count", 0))
                     > int(prev.stages.get("cert_lag", {})
                           .get("count", 0)))
        return HOLD if fresh_lag else vote

    return policy


def breaker_readmission_policy() -> Policy:
    """`breaker_cooldown_ms` from re-admission OUTCOMES: a trip that
    lands after a recovery means the breaker re-admitted traffic too
    early and the device re-failed under it — GROW the cooldown. An
    interval whose recoveries advance with NO new trips means the plane
    held after re-admission — SHRINK back toward faster re-admission.
    Intervals without fresh breaker history hold. (The controller's
    degraded rule guarantees policies only run with every breaker
    CLOSED, so this reads the trip/recovery COUNTER deltas — the
    history of re-admissions — never live breaker state.)"""

    def policy(cur: Telemetry, prev: Optional[Telemetry],
               knob: Knob) -> int:
        if prev is None:
            return HOLD
        d_trips = d_recov = 0
        for name, b in cur.breakers.items():
            pb = prev.breakers.get(name, {})
            d_trips += max(0, int(b.get("trips", 0))
                           - int(pb.get("trips", 0)))
            d_recov += max(0, int(b.get("recoveries", 0))
                           - int(pb.get("recoveries", 0)))
        if d_trips > 0:
            return GROW
        if d_recov > 0:
            return SHRINK
        return HOLD

    return policy


def device_min_batch_policy() -> Policy:
    """`device_min_verify_batch` (the smallest batch worth a device
    launch) from the kernel profiler's WARM per-item cost of the
    ed25519 verify kernel: a falling per-item cost means the device is
    amortizing well at current sizes — SHRINK the floor so smaller
    batches ride it too; a rising per-item cost means launches stopped
    amortizing (the floor admits batches too small to pay the dispatch
    overhead) — GROW it back toward host territory. No fresh kernel
    calls => HOLD."""

    def policy(cur: Telemetry, prev: Optional[Telemetry],
               knob: Knob) -> int:
        if prev is None or kernel_calls(cur, "ed25519") \
                <= kernel_calls(prev, "ed25519"):
            return HOLD
        a = kernel_per_item_us(cur, "ed25519")
        b = kernel_per_item_us(prev, "ed25519")
        if a is None or b is None or b <= 0.0:
            return HOLD
        if a <= b * FALLING_RATIO:
            return SHRINK
        if a * FALLING_RATIO >= b:
            return GROW
        return HOLD

    return policy


def exec_accumulation_policy() -> Policy:
    """Shrink accumulation when `exec` dominates the slot breakdown
    (long coalesced runs are serializing replies behind one apply);
    grow while the lane is deeper than the current cap and exec stays
    minor (coalescing would cut per-slot commit overhead)."""

    def policy(cur: Telemetry, prev: Optional[Telemetry],
               knob: Knob) -> int:
        if not fresh_slots(cur, prev):
            return HOLD
        frac = stage_fraction(cur, "exec")
        if frac > DOMINANT_FRAC:
            return SHRINK
        if frac < MINOR_FRAC \
                and cur.depths.get("exec_lane", 0) > knob.value:
            return GROW
        return HOLD

    return policy


def ecdsa_crossover_policy() -> Policy:
    """Move the device/host crossover from measured per-item costs:
    the `ecdsa` kernel profile (device tier) vs the batched host
    engine's drained timing counters (`ecdsa_host_us` / items, fed by
    SigManager). A >=10% gap in either direction moves the boundary
    toward the cheaper tier; anything closer holds."""

    def policy(cur: Telemetry, prev: Optional[Telemetry],
               knob: Knob) -> int:
        if prev is None:
            return HOLD
        dev = kernel_per_item_us(cur, "ecdsa")
        items = cur.counters.get("ecdsa_host_items_delta", 0.0)
        us = cur.counters.get("ecdsa_host_us_delta", 0.0)
        host = (us / items) if items > 0 else None
        if dev is None or host is None or host <= 0.0:
            return HOLD
        if dev < host * CROSSOVER_MARGIN:
            return SHRINK        # device cheaper: admit smaller batches
        if host < dev * CROSSOVER_MARGIN:
            return GROW          # host cheaper: raise the bar
        return HOLD

    return policy


def crypto_shard_policy() -> Policy:
    """Mesh fan-out cap (ISSUE 16): follow the measured per-item cost
    of the SHARDED verify launches. The `ed25519.shard` profile row
    (written by device_section alongside the plain `ed25519` row on
    every mesh launch) proves fresh sharded traffic; the full-batch
    per-item cost then says whether the current width still amortizes —
    falling => GROW toward more chips, rising past the same ratio =>
    SHRINK (mesh dispatch overhead is beating the split at the current
    batch sizes). No fresh SHARDED launches => HOLD: an idle or
    single-chip-routed interval says nothing about the mesh. An evicted
    chip never reaches this policy at all — any non-CLOSED breaker
    trips the controller's degraded rule, which resets the knob to its
    default (full width) until the plane heals."""

    def policy(cur: Telemetry, prev: Optional[Telemetry],
               knob: Knob) -> int:
        if not fresh_slots(cur, prev):
            return HOLD
        if prev is None or kernel_calls(cur, "ed25519.shard") \
                <= kernel_calls(prev, "ed25519.shard"):
            return HOLD
        a = kernel_per_item_us(cur, "ed25519")
        b = kernel_per_item_us(prev, "ed25519")
        if a is None or b is None or b <= 0.0:
            return HOLD
        if a <= b * FALLING_RATIO:
            return GROW
        if a * FALLING_RATIO >= b:
            return SHRINK
        return HOLD

    return policy


def durability_amortize_policy() -> Policy:
    """Group-commit window/size (ISSUE 15): widen while the measured
    fsync cost PER RUN keeps falling (grouping is still amortizing the
    disk — the exact analog of the kernel-batch amortization rule,
    with the probed fsync as the 'kernel'); shrink as soon as `reply`
    dominates the slot breakdown — with the pipeline, the group-fsync
    wait is accounted to the reply stage, so a dominant reply share
    means durability batching is costing more latency than the
    amortization buys back."""

    def policy(cur: Telemetry, prev: Optional[Telemetry],
               knob: Knob) -> int:
        if not fresh_slots(cur, prev):
            return HOLD
        if stage_fraction(cur, "reply") > DOMINANT_FRAC:
            return SHRINK
        runs = cur.counters.get("dur_runs_delta", 0.0)
        us = cur.counters.get("dur_fsync_us_delta", 0.0)
        if prev is None or runs <= 0:
            return HOLD
        prev_runs = prev.counters.get("dur_runs_delta", 0.0)
        prev_us = prev.counters.get("dur_fsync_us_delta", 0.0)
        if prev_runs <= 0 or prev_us <= 0:
            return HOLD
        cost, prev_cost = us / runs, prev_us / prev_runs
        if cost <= prev_cost * FALLING_RATIO:
            return GROW
        return HOLD

    return policy


def st_window_policy() -> Policy:
    """`st_window_ranges` (state-transfer fetch pipelining) from the
    transfer's own throughput history: SHRINK on any fresh
    `source_failovers` — a failover means an outstanding range timed
    out on its source, and a wide window multiplies the data parked
    behind the slow/dead source when that happens; GROW while the
    fetched-byte rate keeps rising interval over interval (the pipeline
    is still source-bound, so more outstanding ranges buy throughput).
    An interval with no fresh transfer traffic holds — an idle
    replica's window must not wander, and the controller's degraded
    rule (any non-CLOSED breaker resets knobs to defaults) already
    covers a sick digest plane. Byte DELTAS stand in for
    st_bytes_per_sec: controller intervals are fixed-length, so the
    per-interval delta is the rate."""

    def policy(cur: Telemetry, prev: Optional[Telemetry],
               knob: Knob) -> int:
        if prev is None:
            return HOLD
        if cur.counters.get("st_failovers_delta", 0.0) > 0:
            return SHRINK
        b = cur.counters.get("st_bytes_delta", 0.0)
        pb = prev.counters.get("st_bytes_delta", 0.0)
        if b <= 0.0 or pb <= 0.0:
            return HOLD          # idle, or no prior interval to compare
        if b * FALLING_RATIO >= pb:
            return GROW          # rate still rising: widen the pipeline
        return HOLD

    return policy


def client_table_policy() -> Policy:
    """`client_table_max` (paged client-table residency bound) from
    paging traffic: GROW while the table is THRASHING — evictions and
    misses both advancing in the same interval means the LRU is
    re-paging records it just evicted, so the live principal working
    set doesn't fit; SHRINK when fresh table traffic runs with zero
    evictions and the resident set sits under half the bound — the
    bound is slack, and handing the memory back cannot touch a hot set
    that small. Intervals without table traffic hold."""

    def policy(cur: Telemetry, prev: Optional[Telemetry],
               knob: Knob) -> int:
        if prev is None:
            return HOLD
        hits = cur.counters.get("client_table_hits_delta", 0.0)
        misses = cur.counters.get("client_table_misses_delta", 0.0)
        if hits + misses <= 0.0:
            return HOLD
        evictions = cur.counters.get("client_table_evictions_delta", 0.0)
        if evictions > 0.0 and misses / (hits + misses) > MINOR_FRAC:
            return GROW
        if evictions <= 0.0 \
                and cur.depths.get("client_table", 0) < knob.value // 2:
            return SHRINK
        return HOLD

    return policy


def admission_watermark_policy() -> Policy:
    """Grow the shed watermark while the plane is shedding but
    admission wait is NOT the bottleneck (the queue would drain if
    allowed to buffer); shrink it when `adm_wait` dominates the slot
    breakdown (buffered traffic is just aging)."""

    def policy(cur: Telemetry, prev: Optional[Telemetry],
               knob: Knob) -> int:
        if not fresh_slots(cur, prev):
            return HOLD
        frac = stage_fraction(cur, "adm_wait")
        if frac > DOMINANT_FRAC:
            return SHRINK
        if cur.counters.get("adm_shedding", 0) and frac < MINOR_FRAC:
            return GROW
        return HOLD

    return policy


def offload_routing_policy() -> Policy:
    """Route combine work helper-ward only while a leased item is
    cheaper than a locally-computed one (ISSUE 20). The knob is binary
    (1=route, 0=local): GROW votes toward routing, SHRINK away from it.

    Leased per-item cost = Δ(lease µs + on-replica soundness µs) over
    Δ(leased items), diffed across telemetry snapshots so it tracks the
    CURRENT helper fleet, not boot-time history. Local per-item cost is
    the warm bls_msm kernel profile — the same sensor the combine-plane
    knobs trust. No fresh leases (or no local kernel profile yet) =>
    HOLD: an idle tier gives no signal, and flapping the route on stale
    numbers costs a lease round-trip per flip."""

    def policy(cur: Telemetry, prev: Optional[Telemetry],
               knob: Knob) -> int:
        if prev is None:
            return HOLD
        d_us = (cur.counters.get("off_lease_us", 0.0)
                - prev.counters.get("off_lease_us", 0.0)) \
            + (cur.counters.get("off_soundness_us", 0.0)
               - prev.counters.get("off_soundness_us", 0.0))
        d_items = (cur.counters.get("off_lease_items", 0.0)
                   - prev.counters.get("off_lease_items", 0.0))
        if d_items <= 0.0:
            # a closed route starves its own sensor (no leases => no
            # deltas, ever) — probe it back open, breaker-half-open
            # style: the knob cooldown bounds the flap rate and a
            # still-slow tier SHRINKs right back next interval. Only
            # while the combine plane is actually busy (fresh slots);
            # an idle replica's knobs must not walk.
            if knob.value == 0 and fresh_slots(cur, prev):
                return GROW
            return HOLD
        local = kernel_per_item_us(cur, "bls_msm")
        if local is None:
            return HOLD
        leased = d_us / d_items
        # the same >=10% margin the device/host crossover uses, so the
        # route doesn't flap on measurement noise
        if leased < local * CROSSOVER_MARGIN:
            return GROW
        if local < leased * CROSSOVER_MARGIN:
            return SHRINK
        return HOLD

    return policy
