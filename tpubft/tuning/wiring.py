"""The per-replica knob catalog — ReplicaConfig seeds the defaults,
the registry owns the values from then on.

`build_replica_tuning(replica, cfg)` registers every live actuator the
replica exposes and binds each to its seam:

  ====================================  ==================================
  knob                                  actuator seam
  ====================================  ==================================
  verify_batch_flush_us                 BatchVerifier + CertBatchVerifier
                                        flush windows (FlushBatcher)
  verify_batch_size                     BatchVerifier batch cap
  combine_flush_us / combine_batch_max  CollectorPool → CombineBatcher
  execution_max_accumulation            ExecutionLane run-coalescing cap
  admission_high_watermark              AdmissionPipeline shed watermarks
                                        (low follows at high/3)
  ecdsa_crossover_b                     crypto/tpu.set_ecdsa_crossover
                                        (process-wide, like the device)
  device_min_verify_batch               SigManager.device_min_batch
  st_window_ranges                      StConfig.window_ranges (late-
                                        bound; kvbc attaches ST after
                                        construction)
  breaker_cooldown_ms                   device breaker configure()
  agg_fanout                            replica._agg_fanout (overlay
                                        edges; PIN-ONLY, wire-visible)
  ====================================  ==================================

Knobs with a policy move from live telemetry; the rest are
catalog/pin/seed surfaces (and still reset on degradation).
`combine_batch_max` and `agg_fanout` are additionally WIRE-VISIBLE:
they shape bytes other replicas must reproduce (certificate contributor
sets, overlay edges), so they are catalog/pin-only by design — no
policy is ever attached, and operators change them cluster-wide. The seed
file (`ReplicaConfig.autotune_seed_file`, written by
`bench_msm_crossover --ecdsa --seed-out`) re-baselines measured knobs
before the controller starts.
"""
from __future__ import annotations

from tpubft.tuning.controller import TuningController
from tpubft.tuning.knobs import Knob, KnobRegistry, load_seed
from tpubft.tuning.policies import (admission_watermark_policy,
                                    batch_amortize_policy,
                                    breaker_readmission_policy,
                                    client_table_policy,
                                    crypto_shard_policy,
                                    device_min_batch_policy,
                                    durability_amortize_policy,
                                    ecdsa_crossover_policy,
                                    exec_accumulation_policy,
                                    offload_routing_policy,
                                    optimistic_combine_policy,
                                    st_window_policy)
from tpubft.utils import flight
from tpubft.utils.logging import get_logger

log = get_logger("tuning")

# registry bound caps (operator bounds live per knob; these are the
# hard rails a policy can never leave)
MAX_FLUSH_US = 20_000
MAX_BATCH = 8192
MAX_ACCUMULATION = 128
MAX_WATERMARK = 1_000_000
MAX_CROSSOVER = 1 << 20


def build_replica_tuning(replica, cfg) -> TuningController:
    rid = replica.id
    registry = KnobRegistry(name=f"tuning-r{rid}")
    cool = cfg.autotune_cooldown_ms / 1e3

    def K(name: str, value: int, lo: int, hi: int, apply_fn,
          sensor: str, unit: str = "") -> Knob:
        return registry.register(Knob(
            name=name, value=int(value), default=int(value), lo=lo,
            hi=hi, apply_fn=apply_fn, sensor=sensor, unit=unit,
            cooldown_s=cool))

    controller = TuningController(
        registry, name=f"tuning-r{rid}",
        interval_s=cfg.autotune_interval_ms / 1e3,
        aggregator=getattr(replica, "aggregator", None), rid=rid,
        stages_fn=lambda: flight.stage_summary(rid=rid),
        kernels_fn=lambda: flight.kernel_profiler().snapshot(),
        health_fn=lambda: replica.health.verdict()["verdict"],
        depths_fn=lambda: _depths(replica),
        counters_fn=lambda: _counters(replica))

    # --- verify plane: flush window + batch cap, grown while the
    # ed25519 kernel's per-item cost keeps falling, shrunk when
    # admission wait dominates the slot breakdown ---
    def apply_verify_flush(v: int) -> None:
        if replica.req_batcher is not None:
            replica.req_batcher.reconfigure(flush_us=v)
        replica.cert_batcher.reconfigure(flush_us=v)

    K("verify_batch_flush_us", cfg.verify_batch_flush_us, 50,
      MAX_FLUSH_US, apply_verify_flush,
      "ed25519 kernel per-item cost vs adm_wait p50 share", "us")
    controller.add_policy("verify_batch_flush_us",
                          batch_amortize_policy("ed25519", "adm_wait"))
    if replica.req_batcher is not None:
        K("verify_batch_size", cfg.verify_batch_size, 16, MAX_BATCH,
          lambda v: replica.req_batcher.reconfigure(batch_size=v),
          "ed25519 kernel batch fill vs adm_wait p50 share", "sigs")
        controller.add_policy("verify_batch_size",
                              batch_amortize_policy("ed25519",
                                                    "adm_wait"))

    # --- combine plane (ROADMAP 3d): flush window + slot cap from the
    # bls_msm amortization profile vs the commit stage share ---
    K("combine_flush_us", cfg.combine_flush_us, 0, MAX_FLUSH_US,
      lambda v: replica.collector_pool.reconfigure(flush_us=v),
      "bls_msm per-item cost vs commit p50 share", "us")
    # under optimistic replies the combine runs OFF the client-visible
    # path (ISSUE 18): fresh cert_lag samples veto the SHRINK votes —
    # narrowing the flush window would trade amortization for a latency
    # nobody is waiting on anymore
    _combine = batch_amortize_policy("bls_msm", "commit")
    if cfg.optimistic_replies:
        _combine = optimistic_combine_policy(_combine)
    controller.add_policy("combine_flush_us", _combine)
    # combine_batch_max is WIRE-VISIBLE and therefore pin/catalog-only
    # (ISSUE 17): the combine-flush drain order determines which share
    # subset a certificate aggregates over, and under share aggregation
    # the cert's contributor bitmap IS wire bytes — replicas autotuning
    # this independently would emit certificates other replicas never
    # mint themselves, breaking the cross-replica retransmission cache
    # and the byte-equivalence gates the benches assert. Operators pin
    # it cluster-wide (flush timing stays per-replica tunable above:
    # WHEN a batch drains is local, WHAT a cert may span is not).
    K("combine_batch_max", cfg.combine_batch_max, 1, 512,
      lambda v: replica.collector_pool.reconfigure(max_batch=v),
      "bls_msm per-item cost vs commit p50 share", "slots")
    controller.track("combine_batch_max")

    # --- execution lane: coalescing depth from the exec stage share ---
    if replica.exec_lane is not None:
        K("execution_max_accumulation", cfg.execution_max_accumulation,
          1, MAX_ACCUMULATION, replica.exec_lane.set_max_accumulation,
          "exec p50 share of the slot breakdown + lane depth", "slots")
        controller.add_policy("execution_max_accumulation",
                              exec_accumulation_policy())

    # --- durability pipeline (ISSUE 15): group-commit window + size
    # from the measured per-run fsync cost vs the reply-stage share
    # (the group-fsync wait is accounted to `reply` in the slot
    # breakdown) ---
    if getattr(replica, "durability", None) is not None:
        K("durability_group_max", cfg.durability_group_max, 1, 64,
          replica.durability.set_group_max,
          "fsync us/run falling vs reply p50 share", "runs")
        controller.add_policy("durability_group_max",
                              durability_amortize_policy())
        K("durability_window_us", cfg.durability_window_us, 0,
          MAX_FLUSH_US, replica.durability.set_window_us,
          "fsync us/run falling vs reply p50 share", "us")
        controller.add_policy("durability_window_us",
                              durability_amortize_policy())

    # --- admission backpressure: shed watermark (low follows at
    # high/3, preserving the construction-time hysteresis shape) ---
    if replica.admission is not None and cfg.admission_high_watermark:
        K("admission_high_watermark", cfg.admission_high_watermark,
          100, MAX_WATERMARK,
          lambda v: replica.admission.set_watermarks(v, max(1, v // 3)),
          "shed mode + adm_wait p50 share", "msgs")
        controller.add_policy("admission_high_watermark",
                              admission_watermark_policy())

    # --- ECDSA device/host crossover (ROADMAP 4d): process-wide, like
    # the device itself — measured `ecdsa` kernel tier vs the batched
    # host engine's drained per-item cost ---
    from tpubft.crypto import tpu as tpu_mod
    K("ecdsa_crossover_b", min(tpu_mod.ecdsa_crossover(), MAX_CROSSOVER),
      1, MAX_CROSSOVER, tpu_mod.set_ecdsa_crossover,
      "ecdsa kernel per-item cost vs ecdsa_host_us/items", "sigs")
    controller.add_policy("ecdsa_crossover_b", ecdsa_crossover_policy())

    # --- multi-chip mesh fan-out (ISSUE 16): cap the crypto plane's
    # shard count from the measured sharded-launch amortization.
    # Process-wide like the device and the crossover; default = every
    # chip, so the degraded-rule reset (any breaker non-CLOSED,
    # including an evicted chip's `device.chip<N>` child) restores full
    # width for the post-recovery remeasure ---
    from tpubft.ops import dispatch as dispatch_mod
    n_chips = dispatch_mod.crypto_mesh().device_count()
    if n_chips > 1:
        K("crypto_shard_count", n_chips, 1, n_chips,
          dispatch_mod.crypto_mesh().set_shard_count,
          "ed25519.shard per-item cost vs full-batch trend", "chips")
        controller.add_policy("crypto_shard_count", crypto_shard_policy())

    # --- device-launch floor (ISSUE 18 satellite): the smallest batch
    # worth a device ride follows the ed25519 kernel's warm per-item
    # trend — falling cost lowers the floor, rising cost raises it ---
    K("device_min_verify_batch", cfg.device_min_verify_batch, 1,
      MAX_BATCH, lambda v: setattr(replica.sig, "device_min_batch", v),
      "ed25519 warm per-item cost trend", "sigs")
    controller.add_policy("device_min_verify_batch",
                          device_min_batch_policy())

    def apply_st_window(v: int) -> None:
        # late-bound: the kvbc layer attaches state transfer after the
        # consensus replica constructs
        st = getattr(replica, "state_transfer", None)
        st_cfg = getattr(st, "cfg", None)
        if st_cfg is not None:
            st_cfg.window_ranges = int(v)

    # fetch pipelining follows the transfer's own throughput history
    # (ISSUE 19 satellite): grow while the fetched-byte rate rises,
    # shrink on source failovers — a wide window multiplies the data
    # parked behind a source that just timed out
    K("st_window_ranges", cfg.st_window_ranges, 1, 64, apply_st_window,
      "st_bytes_per_sec trend vs source_failovers", "ranges")
    controller.add_policy("st_window_ranges", st_window_policy())

    # --- paged client table (ISSUE 19): residency bound follows the
    # paging traffic — grow under evict/re-page thrash, hand memory
    # back when the resident set runs far under the bound ---
    if replica.clients.max_resident:
        K("client_table_max", cfg.client_table_max, 256, 1 << 20,
          replica.clients.set_max_resident,
          "client-table miss/eviction thrash vs resident slack",
          "clients")
        controller.add_policy("client_table_max", client_table_policy())

    def apply_breaker_cooldown(v: int) -> None:
        from tpubft.ops.dispatch import device_breaker
        device_breaker().configure(cooldown_s=v / 1e3)

    # re-admission outcomes drive the cooldown (ISSUE 18 satellite): a
    # trip after a recovery = re-admitted too early, grow; recoveries
    # holding with no new trips = shrink back toward fast re-admission
    K("breaker_cooldown_ms", cfg.breaker_cooldown_ms, 100, 120_000,
      apply_breaker_cooldown, "breaker trip/recovery history", "ms")
    controller.add_policy("breaker_cooldown_ms",
                          breaker_readmission_policy())

    # --- verified crypto-offload tier (ISSUE 20): routing is a 0/1
    # actuator on the process-wide pool — work goes helper-ward only
    # while the measured leased per-item cost (lease round-trip + the
    # on-replica soundness check) beats the local bls_msm kernel's.
    # Safety is NOT this knob's job: a lying helper is quarantined by
    # the soundness check regardless of the route state.
    if cfg.offload_enabled:
        from tpubft.ops.dispatch import offload_pool
        _pool = offload_pool()
        K("offload_route", 1, 0, 1,
          lambda v: _pool.set_routing(bool(v)),
          "leased per-item cost (lease+soundness) vs local bls_msm",
          "on/off")
        controller.add_policy("offload_route", offload_routing_policy())

    # agg_fanout is WIRE-VISIBLE and pin/catalog-only (ISSUE 17): every
    # replica derives the aggregation overlay deterministically from
    # (n, fanout, root, view) with no negotiation — a replica moving its
    # own fanout would compute different parent/child edges than its
    # peers, orphaning its shares (they land on nodes that don't expect
    # to be its parent and time out into the direct-send fallback: safe,
    # but the aggregation win silently evaporates). No policy may ever
    # drive it; operators pin it cluster-wide in one move.
    if getattr(replica, "_agg_mode", "off") != "off":
        K("agg_fanout", cfg.agg_fanout, 2, 16,
          lambda v: setattr(replica, "_agg_fanout", max(2, int(v))),
          "overlay depth vs per-hop flush latency (pin-only)", "children")
        controller.track("agg_fanout")

    # --- measured-operating-point seed (bench handoff) ---
    if cfg.autotune_seed_file:
        try:
            n = load_seed(registry, cfg.autotune_seed_file)
            log.info("r%d: seeded %d knobs from %s", rid, n,
                     cfg.autotune_seed_file)
        except Exception:  # noqa: BLE001 — a bad seed must not stop
            log.exception("r%d: knob seed %s failed; using defaults",
                          rid, cfg.autotune_seed_file)
    return controller


def _depths(replica) -> dict:
    d = {}
    if replica.exec_lane is not None:
        d["exec_lane"] = replica.exec_lane.depth
    if replica.admission is not None:
        d["admission"] = replica.admission.depth
    if getattr(replica, "durability", None) is not None:
        d["dur_lag"] = replica.durability.lag
    if getattr(replica, "clients", None) is not None:
        d["client_table"] = replica.clients.resident_count
    return d


def _counters(replica) -> dict:
    c = {"ecdsa_host_items": replica.sig.ecdsa_batched_host.value,
         "ecdsa_host_us": replica.sig.ecdsa_host_us.value}
    if replica.admission is not None:
        c["adm_shedding"] = 1 if replica.admission.shedding else 0
    if getattr(replica, "durability", None) is not None:
        c.update(replica.durability.stats())
    st = getattr(replica, "state_transfer", None)
    if st is not None:
        # late-bound like the knob itself (kvbc attaches ST after
        # construction); counter DELTAS are the policy's rate signal
        c["st_bytes"] = st.m_bytes.value
        c["st_failovers"] = st.m_failovers.value
    clients = getattr(replica, "clients", None)
    if clients is not None:
        c["client_table_hits"] = clients.table_hits
        c["client_table_misses"] = clients.table_misses
        c["client_table_evictions"] = clients.table_evictions
    from tpubft.offload import pool as _op
    if _op._POOL is not None and _op._POOL.enabled:
        # cumulative lease cost; the routing policy diffs these deltas.
        # Read even while routing is OFF — pool_if_active() would hide
        # the counters then, starving the policy of the signal it needs
        # to probe the route back open.
        c["off_lease_us"] = _op._POOL.lease_us_total
        c["off_lease_items"] = _op._POOL.lease_items_total
        c["off_soundness_us"] = _op._POOL.soundness_us_total
    return c
