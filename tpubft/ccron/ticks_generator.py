"""TicksGenerator — the pacemaker submitting TickOps through consensus
(reference ccron/ticks_generator.cpp). Only the current primary submits,
to avoid n duplicate ticks per period; duplicates are harmless anyway
(CronTable deduplicates by tick_seq)."""
from __future__ import annotations

import time
from typing import Dict

from tpubft.consensus.internal import TickOp, pack_op
from tpubft.consensus.messages import RequestFlag


class TicksGenerator:
    def __init__(self, replica, cron_table) -> None:
        self._replica = replica
        self._table = cron_table
        self._periods: Dict[str, float] = {}
        self._last_sent: Dict[str, float] = {}

    def schedule(self, component: str, period_s: float) -> None:
        self._periods[component] = period_s

    def poll(self) -> None:
        """Dispatcher timer callback."""
        if not self._replica.is_primary:
            return
        now = time.monotonic()
        for component, period in self._periods.items():
            if now - self._last_sent.get(component, 0.0) < period:
                continue
            self._last_sent[component] = now
            op = TickOp(component=component,
                        tick_seq=self._table.last_tick(component) + 1)
            self._replica.internal_client.submit(
                pack_op(op), flags=int(RequestFlag.TICK))
