"""ccron — deterministic, consensus-driven cron.

Rebuild of /root/reference/ccron/ (ticks_generator.cpp, cron_table.cpp,
periodic_action.cpp): tick requests go through consensus (TickOp via the
internal BFT client), so every replica runs the same actions at the same
sequence point — unlike a wall-clock timer, which would diverge. The
primary's TicksGenerator is merely the pacemaker; determinism comes from
ordering. Last-fired tick per component persists in a reserved page so
ticks are exactly-once across crashes and state transfer.
"""
from tpubft.ccron.cron_table import CronTable
from tpubft.ccron.ticks_generator import TicksGenerator

__all__ = ["CronTable", "TicksGenerator"]
