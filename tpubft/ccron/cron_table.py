"""CronTable — per-component ordered actions fired on consensus ticks
(reference ccron/cron_table.cpp + periodic_action.cpp)."""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from tpubft.consensus.internal import TickOp
from tpubft.consensus.reserved_pages import ReservedPagesClient

Action = Callable[[int], None]  # receives the tick sequence number


class CronTable:
    CATEGORY = "cron"

    def __init__(self, pages: Optional[ReservedPagesClient] = None) -> None:
        self._actions: Dict[str, List[Action]] = {}
        self._pages = pages
        self._last_tick: Dict[str, int] = {}

    def register(self, component: str, action: Action) -> None:
        self._actions.setdefault(component, []).append(action)

    def components(self) -> List[str]:
        return sorted(self._actions)

    def last_tick(self, component: str) -> int:
        if component in self._last_tick:
            return self._last_tick[component]
        if self._pages is not None:
            raw = self._pages.load(index=self._page_index(component))
            if raw:
                self._last_tick[component] = int.from_bytes(raw, "big")
                return self._last_tick[component]
        return 0

    def reload(self) -> None:
        """Drop the in-memory tick cache so reads fall through to the
        (possibly state-transfer-installed) reserved page."""
        self._last_tick.clear()

    def _page_index(self, component: str) -> int:
        # stable index per component, registration-order agnostic; 32-bit
        # hash space makes accidental collisions negligible
        import hashlib
        return int.from_bytes(
            hashlib.sha256(component.encode()).digest()[:4], "big")

    def on_tick(self, op: TickOp) -> None:
        """Executed on EVERY replica at the same consensus position."""
        if op.tick_seq <= self.last_tick(op.component):
            return  # duplicate/old tick (retransmission): exactly-once
        self._last_tick[op.component] = op.tick_seq
        if self._pages is not None:
            self._pages.save(op.tick_seq.to_bytes(8, "big"),
                             index=self._page_index(op.component))
        for action in self._actions.get(op.component, []):
            action(op.tick_seq)
