"""Demo applications / test state machines (reference tests/simpleTest,
tests/simpleKVBC, examples/)."""
