"""SimpleKVBC — the versioned KV test application.

Rebuild of the reference's SKVBC state machine and wire protocol
(/root/reference/tests/simpleKVBC/cmf/skvbc_messages.cmf,
TesterReplica/internalCommandsHandler.cpp): a conditional-write KV store
over the categorized blockchain. Writes carry a read_version + readset;
at execution the replica rejects the write (success=False) if any readset
key changed after read_version — the conflict-detection discipline the
reference uses to exercise pre-execution. This is the app Apollo-style
system tests and the linearizability tracker drive.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tpubft.consensus.replica import IRequestsHandler
from tpubft.kvbc import (BLOCK_MERKLE, VERSIONED_KV, BlockUpdates,
                         KeyValueBlockchain)
from tpubft.utils import serialize as ser
from tpubft.utils.racecheck import make_lock

READ_LATEST = 0  # read_version 0 = latest (reference uses 0 the same way)

_CATEGORY = "kv"


# ---------------- wire messages (skvbc_messages.cmf) ----------------

@dataclass
class ReadRequest:
    ID = 3
    read_version: int = READ_LATEST
    keys: List[bytes] = field(default_factory=list)
    SPEC = [("read_version", "u64"), ("keys", ("list", "bytes"))]


@dataclass
class WriteRequest:
    ID = 4
    read_version: int = 0
    long_exec: bool = False
    readset: List[bytes] = field(default_factory=list)
    writeset: List[Tuple[bytes, bytes]] = field(default_factory=list)
    SPEC = [("read_version", "u64"), ("long_exec", "bool"),
            ("readset", ("list", "bytes")),
            ("writeset", ("list", ("pair", "bytes", "bytes")))]


@dataclass
class GetLastBlockRequest:
    ID = 5
    SPEC = []  # no fields


@dataclass
class GetBlockDataRequest:
    ID = 6
    block_id: int = 0
    SPEC = [("block_id", "u64")]


@dataclass
class ReadReply:
    ID = 7
    reads: List[Tuple[bytes, bytes]] = field(default_factory=list)
    SPEC = [("reads", ("list", ("pair", "bytes", "bytes")))]


@dataclass
class WriteReply:
    ID = 8
    success: bool = False
    latest_block: int = 0
    SPEC = [("success", "bool"), ("latest_block", "u64")]


@dataclass
class GetLastBlockReply:
    ID = 9
    latest_block: int = 0
    SPEC = [("latest_block", "u64")]


_TYPES = {cls.ID: cls for cls in
          (ReadRequest, WriteRequest, GetLastBlockRequest,
           GetBlockDataRequest, ReadReply, WriteReply, GetLastBlockReply)}


def pack(msg) -> bytes:
    return bytes([msg.ID]) + ser.encode_msg(msg)


def unpack(data: bytes):
    if not data or data[0] not in _TYPES:
        raise ser.SerializeError(f"unknown skvbc msg id {data[:1]!r}")
    return ser.decode_msg(data[1:], _TYPES[data[0]])


# ---------------- the state machine ----------------

class SkvbcHandler(IRequestsHandler):
    """InternalCommandsHandler equivalent
    (tests/simpleKVBC/TesterReplica/internalCommandsHandler.hpp:34)."""

    def __init__(self, blockchain: KeyValueBlockchain,
                 merkle: bool = False) -> None:
        """`merkle=True` keeps the kv state in a BLOCK_MERKLE category
        (the reference SKVBC layout): every key is provable with a
        sparse-merkle audit path against the block-anchored root, which
        is what the thin-replica read tier serves. Historical
        (read_version != latest) reads are unsupported in merkle mode —
        the proof plane serves those."""
        self._bc = blockchain
        self._cat_type = BLOCK_MERKLE if merkle else VERSIONED_KV
        self._lock = make_lock("skvbc_app")

    @property
    def blockchain(self) -> KeyValueBlockchain:
        return self._bc

    # -- helpers --
    def _read_at(self, key: bytes, version: int) -> Optional[bytes]:
        if version == READ_LATEST:
            hit = self._bc.get_latest(_CATEGORY, key,
                                      cat_type=self._cat_type)
            return hit[1] if hit else None
        if self._cat_type == BLOCK_MERKLE:
            return None
        return self._bc.get_versioned(_CATEGORY, key, version)

    # -- IRequestsHandler --
    def execute(self, client_id: int, req_seq: int, flags: int,
                request: bytes) -> bytes:
        try:
            msg = unpack(request)
        except ser.SerializeError:
            return b""
        with self._lock:
            if isinstance(msg, WriteRequest):
                return self._execute_write(msg)
            # reads routed through consensus still serve consistent data
            return self._execute_read(msg)

    def _readset_stale(self, msg: WriteRequest) -> bool:
        """Any readset key written after read_version ⇒ stale (the
        conflict-detection discipline of
        internalCommandsHandler.cpp verifyWriteCommand)."""
        for key in msg.readset:
            hit = self._bc.get_latest(_CATEGORY, key,
                                      cat_type=self._cat_type)
            if hit is not None and hit[0] > msg.read_version:
                return True
        return False

    def _execute_write(self, msg: WriteRequest) -> bytes:
        if msg.readset and self._readset_stale(msg):
            return pack(WriteReply(success=False,
                                   latest_block=self._bc.last_block_id))
        bu = BlockUpdates()
        for k, v in msg.writeset:
            bu.put(_CATEGORY, k, v, cat_type=self._cat_type)
        if msg.writeset:
            self._bc.add_block(bu)
        return pack(WriteReply(success=True,
                               latest_block=self._bc.last_block_id))

    def _execute_read(self, msg) -> bytes:
        if isinstance(msg, ReadRequest):
            reads = []
            for k in msg.keys:
                v = self._read_at(k, msg.read_version)
                if v is not None:
                    reads.append((k, v))
            return pack(ReadReply(reads=reads))
        if isinstance(msg, GetLastBlockRequest):
            return pack(GetLastBlockReply(latest_block=self._bc.last_block_id))
        if isinstance(msg, GetBlockDataRequest):
            blk = self._bc.get_block(msg.block_id)
            if blk is None:
                return pack(ReadReply(reads=[]))
            from tpubft.kvbc.categories import decode_block_updates
            bu = decode_block_updates(blk.updates_blob)
            reads = []
            for _name, (_t, cu) in sorted(bu.categories.items()):
                for k in sorted(cu.kv):
                    v = cu.kv[k]
                    if v is not None:
                        reads.append((k, v))
            return pack(ReadReply(reads=reads))
        return b""

    def read(self, client_id: int, request: bytes) -> bytes:
        try:
            msg = unpack(request)
        except ser.SerializeError:
            return b""
        with self._lock:
            return self._execute_read(msg)

    # ---- pre-execution (reference InternalCommandsHandler PRE_PROCESS) --
    def pre_execute(self, client_id: int, req_seq: int,
                    request: bytes) -> Optional[bytes]:
        """Speculative phase: validate + canonicalize the write intent.
        The result must not depend on this replica's block height (f+1
        replicas at different heights must produce identical bytes), so
        the conflict check stays in apply_pre_executed — matching the
        reference, where verifyWriteCommand runs at commit."""
        try:
            msg = unpack(request)
        except ser.SerializeError:
            return None
        if not isinstance(msg, WriteRequest):
            return None
        if msg.long_exec:
            time.sleep(0.05)  # simulated heavy pre-processing
        canonical = WriteRequest(read_version=msg.read_version,
                                 long_exec=False,
                                 readset=sorted(msg.readset),
                                 writeset=sorted(msg.writeset))
        return pack(canonical)

    def pre_exec_conflicted(self, client_id: int, req_seq: int,
                            original_request: bytes,
                            result: bytes) -> bool:
        """Commit-time read-set watermark re-validation (the execution
        lane calls this before applying a pre-executed result): the
        speculation ran over an older snapshot — any readset key
        versioned past the request's read watermark invalidates it.
        Advisory for the replica's fallback decision; _execute_write
        repeats the scan under the lock because it is load-bearing for
        the PLAIN ordering path too (readset point reads — cheap)."""
        try:
            msg = unpack(result)
        except ser.SerializeError:
            return False
        if not isinstance(msg, WriteRequest) or not msg.readset:
            return False
        with self._lock:
            return self._readset_stale(msg)

    def apply_pre_executed(self, client_id: int, req_seq: int, flags: int,
                           original_request: bytes,
                           result: bytes) -> bytes:
        try:
            msg = unpack(result)
        except ser.SerializeError:
            return b""
        if not isinstance(msg, WriteRequest):
            return b""
        with self._lock:
            return self._execute_write(msg)

    def state_digest(self) -> bytes:
        with self._lock:
            return self._bc.state_digest()


class SkvbcClient:
    """Client-side protocol wrapper (reference: apollo util/skvbc.py
    SimpleKVBCProtocol) over a BftClient."""

    def __init__(self, bft_client) -> None:
        self._client = bft_client

    def write(self, writeset: List[Tuple[bytes, bytes]],
              readset: Optional[List[bytes]] = None,
              read_version: int = 0,
              timeout_ms: Optional[int] = None,
              pre_process: bool = False) -> WriteReply:
        req = WriteRequest(read_version=read_version,
                           readset=readset or [], writeset=writeset)
        reply = self._client.send_write(pack(req), timeout_ms=timeout_ms,
                                        pre_process=pre_process)
        return unpack(reply)

    def write_batch(self, writes: List[List[Tuple[bytes, bytes]]],
                    timeout_ms: Optional[int] = None,
                    pre_process: bool = False) -> List[WriteReply]:
        """Several independent write transactions in ONE wire message
        (BftClient.send_write_batch / ClientBatchRequestMsg); each
        element orders and replies separately. pre_process routes every
        element through the pre-execution plane."""
        reqs = [pack(WriteRequest(read_version=0, readset=[], writeset=ws))
                for ws in writes]
        replies = self._client.send_write_batch(reqs, timeout_ms=timeout_ms,
                                                pre_process=pre_process)
        return [unpack(r) for r in replies]

    def read(self, keys: List[bytes], read_version: int = READ_LATEST,
             timeout_ms: Optional[int] = None) -> Dict[bytes, bytes]:
        req = ReadRequest(read_version=read_version, keys=keys)
        reply = self._client.send_read(pack(req), timeout_ms=timeout_ms)
        return dict(unpack(reply).reads)

    def get_last_block(self, timeout_ms: Optional[int] = None) -> int:
        reply = self._client.send_read(pack(GetLastBlockRequest()),
                                       timeout_ms=timeout_ms)
        return unpack(reply).latest_block
