"""TesterCRE — standalone client-reconfiguration-engine process.

Rebuild of the reference's TesterCRE
(/root/reference/tests/simpleKVBC/TesterClient sibling): a client process
running the CRE poll loop against a live cluster, printing every
cluster-control state change (wedge points, key rotations) as JSON lines
until interrupted or --polls runs out.

Run:  python -m tpubft.apps.cre_client --f 1 --base-port 3710 \
          [--polls 10] [--period 1.0] [--client-idx 0]
"""
from __future__ import annotations

import argparse
import json
import sys
import time

from tpubft.apps.tester_client import add_scheme_args, make_client
from tpubft.client.cre import ClientReconfigurationEngine


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--f", type=int, default=1)
    ap.add_argument("--c", type=int, default=0)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--client-idx", type=int, default=0)
    ap.add_argument("--base-port", type=int, default=3710)
    ap.add_argument("--seed", default="tpubft-skvbc")
    ap.add_argument("--polls", type=int, default=0,
                    help="exit after N polls (0 = run forever)")
    ap.add_argument("--period", type=float, default=1.0)
    add_scheme_args(ap)
    args = ap.parse_args()

    kv = make_client(args, 0)     # client id = n + args.client_idx
    cl = kv._client
    cre = ClientReconfigurationEngine(cl, poll_period_s=args.period)
    cre.register_handler(
        lambda st: print(json.dumps({
            "event": "cluster_state", "wedge_point": st.wedge_point,
            "restart_ready": st.restart_ready, "raw": st.raw}),
            flush=True))
    try:
        n = 0
        while args.polls == 0 or n < args.polls:
            cre.poll_once()           # handlers fire on observed CHANGES
            n += 1                    # --polls counts polls, as documented
            time.sleep(args.period)
    except KeyboardInterrupt:
        pass
    finally:
        cl.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
