"""simpleTest: 4 replicas + 1 client over UDP localhost.

Rebuild of /root/reference/tests/simpleTest/ (scripts/testReplicasAndClient.sh
+ simpleTest.py CLI): the smallest real-deployment exercise — each replica
is its own OS process bound to a UDP port, a client drives counter
increments and validates replies, then everything shuts down.

Usage:
  python -m tpubft.apps.simple_test                 # orchestrate everything
  python -m tpubft.apps.simple_test --replica N ... # run one replica (internal)
"""
from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from typing import Dict, List, Tuple

from tpubft.apps import counter as counter_app
from tpubft.bftclient import BftClient, ClientConfig
from tpubft.comm import CommConfig, PlainUdpCommunication
from tpubft.consensus.keys import ClusterKeys
from tpubft.consensus.replica import Replica
from tpubft.utils.config import ReplicaConfig
from tpubft.utils.metrics import Aggregator, UdpMetricsServer


def endpoint_table(base_port: int, n: int, num_clients: int,
                   operator_id: int = None) -> Dict[int, Tuple[str, int]]:
    eps = {r: ("127.0.0.1", base_port + r) for r in range(n)}
    for i in range(num_clients):
        eps[n + i] = ("127.0.0.1", base_port + n + i)
    if operator_id is not None:
        # the operator principal is addressable too (reconfiguration
        # commands over the real transport)
        eps[operator_id] = ("127.0.0.1", base_port + operator_id)
    return eps


def add_scheme_args(ap) -> None:
    """Crypto-scheme flags shared by every cluster binary (replica,
    TesterClient, TesterCRE): client and replica processes must generate
    matching keys, so the flag names and defaults live in ONE place —
    against a cluster running non-default schemes (config 3/5: ecdsa
    clients, threshold BLS) a mismatched client is rejected on every
    request."""
    ap.add_argument("--threshold-scheme", default="multisig-ed25519")
    ap.add_argument("--client-sig-scheme", default="ed25519")


def run_replica(args) -> None:
    cfg = ReplicaConfig(replica_id=args.replica, f_val=args.f,
                        num_of_client_proxies=args.clients)
    keys = ClusterKeys.generate(cfg, args.clients,
                                seed=args.seed.encode()).for_node(args.replica)
    eps = endpoint_table(args.base_port, cfg.n_val, args.clients)
    comm = PlainUdpCommunication(CommConfig(self_id=args.replica, endpoints=eps))
    agg = Aggregator()
    rep = Replica(cfg, keys, comm, counter_app.CounterHandler(),
                  aggregator=agg)
    metrics = UdpMetricsServer(agg, port=args.metrics_port)
    metrics.start()
    rep.start()
    print(f"replica {args.replica} up (udp {eps[args.replica][1]}, "
          f"metrics {metrics.port})", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        rep.stop()
        metrics.stop()


def _wait_for_metrics(ports: List[int], timeout_s: float) -> bool:
    """Poll each replica's UDP metrics server until it answers (readiness
    gate — on small machines concurrent process startup is slow)."""
    import socket
    deadline = time.monotonic() + timeout_s
    pending = set(ports)
    while pending and time.monotonic() < deadline:
        for port in list(pending):
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.settimeout(0.3)
            try:
                s.sendto(b"ping", ("127.0.0.1", port))
                s.recvfrom(65536)
                pending.discard(port)
            except OSError:
                pass
            finally:
                s.close()
        if pending:
            time.sleep(0.2)
    return not pending


def run_orchestrator(args) -> int:
    cfg = ReplicaConfig(f_val=args.f, num_of_client_proxies=args.clients)
    n = cfg.n_val
    metrics_base = args.metrics_base_port or args.base_port + 100
    procs: List[subprocess.Popen] = []
    try:
        for r in range(n):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "tpubft.apps.simple_test",
                 "--replica", str(r), "--f", str(args.f),
                 "--base-port", str(args.base_port),
                 "--clients", str(args.clients), "--seed", args.seed,
                 "--metrics-port", str(metrics_base + r)]))
        # 120s: n concurrent cold jax imports contend on this 1-core host
        # (same flake class as the process-cluster boot timeout)
        if not _wait_for_metrics([metrics_base + r for r in range(n)],
                                 timeout_s=120):
            print("replicas failed to become ready")
            return 1
        keys = ClusterKeys.generate(cfg, args.clients, seed=args.seed.encode())
        client_id = n
        eps = endpoint_table(args.base_port, n, args.clients)
        comm = PlainUdpCommunication(CommConfig(self_id=client_id,
                                                endpoints=eps))
        client = BftClient(ClientConfig(client_id=client_id, f_val=args.f,
                                        request_timeout_ms=30000),
                           keys.for_node(client_id), comm)
        total = 0
        t0 = time.perf_counter()
        for i in range(args.ops):
            total += i + 1
            got = counter_app.decode_reply(
                client.send_write(counter_app.encode_add(i + 1)))
            if got != total:
                print(f"MISMATCH at op {i}: got {got}, want {total}")
                return 1
        dt = time.perf_counter() - t0
        read = counter_app.decode_reply(
            client.send_read(counter_app.encode_read()))
        client.stop()
        ok = read == total
        print(json.dumps({
            "ok": ok, "ops": args.ops, "final": read,
            "throughput_ops_sec": round(args.ops / dt, 1),
            "mean_latency_ms": round(1000 * dt / args.ops, 2),
        }))
        return 0 if ok else 1
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


def main() -> int:
    from tpubft.utils.logging import configure
    configure()                       # level from TPUBFT_LOG (default warn)
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--replica", type=int, default=None,
                    help="run a single replica with this id (internal)")
    ap.add_argument("--f", type=int, default=1)
    ap.add_argument("--clients", type=int, default=2)
    ap.add_argument("--base-port", type=int, default=3710)
    ap.add_argument("--metrics-port", type=int, default=0)
    ap.add_argument("--metrics-base-port", type=int, default=0)
    ap.add_argument("--ops", type=int, default=50)
    ap.add_argument("--seed", default="tpubft-simple-test")
    args = ap.parse_args()
    if args.replica is not None:
        run_replica(args)
        return 0
    return run_orchestrator(args)


if __name__ == "__main__":
    sys.exit(main())
