"""SKVBC TesterReplica — one OS-process KVBC replica.

Rebuild of /root/reference/tests/simpleKVBC/TesterReplica/main.cpp:192:
a standalone replica process running the SimpleKVBC state machine over
the categorized blockchain with persistent storage, a UDP metrics server
for the system-test harness to poll, and (optionally) a byzantine
communication-wrapping strategy for fault-injection tests
(TesterReplica/strategy/ + WrapCommunication.cpp).

Run:  python -m tpubft.apps.skvbc_replica --replica 0 --f 1 \
          --base-port 3710 --metrics-port 4710 [--db-dir DIR] [--seed S]
"""
from __future__ import annotations

import argparse
import os
import time

from tpubft.apps.simple_test import add_scheme_args, endpoint_table
from tpubft.comm import CommConfig, create_communication
from tpubft.consensus.keys import ClusterKeys
from tpubft.kvbc.replica import KvbcReplica
from tpubft.utils.config import ReplicaConfig
from tpubft.utils.metrics import Aggregator, UdpMetricsServer


def build_replica(args, comm_wrapper=None) -> KvbcReplica:
    kw = dict(replica_id=args.replica, f_val=args.f, c_val=args.c,
              num_ro_replicas=args.ro,
              num_of_client_proxies=args.clients,
              view_change_timer_ms=args.view_change_timeout_ms,
              crypto_backend=args.crypto_backend,
              pre_execution_enabled=args.pre_execution,
              checkpoint_window_size=args.checkpoint_window,
              work_window_size=args.work_window,
              kvbc_version=args.kvbc_version,
              threshold_scheme=args.threshold_scheme,
              client_sig_scheme=args.client_sig_scheme)
    if args.device_min_verify_batch is not None:
        kw["device_min_verify_batch"] = args.device_min_verify_batch
    # generic overrides win over flag-mapped fields (applied last)
    from tpubft.utils.config import parse_config_overrides
    kw.update(parse_config_overrides(getattr(args, "config_override",
                                             None)))
    cfg = ReplicaConfig(**kw)
    keys = ClusterKeys.generate(cfg, args.clients,
                                seed=args.seed.encode()).for_node(args.replica)
    from tpubft.consensus.replicas_info import ReplicasInfo
    eps = endpoint_table(args.base_port, cfg.n_val + args.ro, args.clients,
                         operator_id=ReplicasInfo.from_config(cfg).operator_id)
    if args.transport in ("tls", "tls-mux"):
        from tpubft.comm.multiplex import client_floor
        from tpubft.comm.tls import TlsConfig
        comm_cfg = TlsConfig(self_id=args.replica, endpoints=eps,
                             certs_dir=args.certs_dir,
                             key_password=os.environ.get(
                                 "TPUBFT_TLS_KEY_PASSWORD"),
                             mux_client_floor=(
                                 client_floor(cfg.n_val, args.ro)
                                 if args.transport == "tls-mux" else None))
    else:
        comm_cfg = CommConfig(self_id=args.replica, endpoints=eps)
    comm = create_communication(comm_cfg, args.transport)
    if comm_wrapper is not None:
        # byzantine strategies that re-sign mutated messages (equivocate)
        # get the replica's own signing key — the reference's strategies
        # likewise live inside the tester replica, key in hand
        comm = comm_wrapper(comm, signer=keys.my_signer()
                            if keys.my_sign_seed else None)
    db_path = (os.path.join(args.db_dir, f"replica-{args.replica}.kvlog")
               if args.db_dir else None)
    agg = Aggregator()
    handler_factory = None
    if getattr(args, "merkle", False):
        # provable state for the thin-replica serving tier: kv lives in
        # a BLOCK_MERKLE category so every read has an audit path
        from tpubft.apps.skvbc import SkvbcHandler
        handler_factory = lambda bc: SkvbcHandler(bc, merkle=True)  # noqa: E731
    return KvbcReplica(cfg, keys, comm, db_path=db_path, aggregator=agg,
                       handler_factory=handler_factory,
                       thin_replica_port=args.trs_port)


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="SKVBC tester replica")
    p.add_argument("--replica", type=int, required=True)
    p.add_argument("--f", type=int, default=1)
    p.add_argument("--c", type=int, default=0)
    p.add_argument("--ro", type=int, default=0,
                   help="read-only replicas in the topology")
    p.add_argument("--clients", type=int, default=4)
    p.add_argument("--base-port", type=int, default=3710)
    p.add_argument("--metrics-port", type=int, default=0)
    p.add_argument("--trs-port", type=int, default=None,
                   help="thin-replica streaming port (0 = ephemeral)")
    p.add_argument("--diag-port", type=int, default=None,
                   help="diagnostics admin server port (0 = ephemeral)")
    p.add_argument("--prom-port", type=int, default=None,
                   help="Prometheus /metrics HTTP port (0 = ephemeral)")
    p.add_argument("--prom-host", default="127.0.0.1",
                   help="bind address for /metrics (0.0.0.0 to let an "
                        "external Prometheus scrape)")
    p.add_argument("--db-dir", default=None)
    p.add_argument("--seed", default="tpubft-skvbc")
    p.add_argument("--transport", default="udp",
                   choices=("udp", "tcp", "tls", "tls-mux"))
    p.add_argument("--certs-dir", default=None,
                   help="TLS material dir (node-<id>.key/.crt)")
    p.add_argument("--view-change-timeout-ms", type=int, default=4000)
    p.add_argument("--strategy", default=None,
                   help="byzantine strategy name (testing)")
    p.add_argument("--device-min-verify-batch", type=int, default=None,
                   help="batches below this verify per-principal instead "
                        "of via the cross-principal device dispatch "
                        "(default: ReplicaConfig's crossover)")
    p.add_argument("--config-override", action="append", default=[],
                   metavar="FIELD=VALUE",
                   help="set any ReplicaConfig field (repeatable); the "
                        "generic escape hatch so new tunables reach "
                        "process clusters without a dedicated flag")
    p.add_argument("--crypto-backend", default="cpu",
                   choices=("cpu", "tpu", "auto"))
    p.add_argument("--pre-execution", action="store_true")
    p.add_argument("--merkle", action="store_true",
                   help="keep SKVBC state in a BLOCK_MERKLE category so "
                        "the thin-replica tier serves provable reads")
    p.add_argument("--fault-port", type=int, default=None,
                   help="per-link fault-injection control port "
                        "(Apollo iptables-partitioning analog)")
    p.add_argument("--checkpoint-window", type=int, default=150)
    p.add_argument("--work-window", type=int, default=300)
    # v1 (direct-KV) is deliberately NOT offered here: it is a legacy
    # migration-source engine (tools/migrate_v4 --from v1). As a consensus
    # engine its raising history/proof reads would let one read request
    # halt execution on every correct replica.
    p.add_argument("--kvbc-version", default="categorized",
                   choices=("categorized", "v4"))
    add_scheme_args(p)
    return p


def main() -> None:
    from tpubft.utils.logging import configure
    configure()                       # level from TPUBFT_LOG (default warn)
    if os.environ.get("TPUBFT_PROFILE_DIR"):
        # profiling runs need a GRACEFUL stop on SIGTERM so the
        # dispatcher's pstats dump (incoming.Dispatcher._loop) happens;
        # normal runs keep the default hard exit (harness timing)
        import signal

        def _term(_sig, _frm):
            raise SystemExit(0)
        signal.signal(signal.SIGTERM, _term)
    args = make_parser().parse_args()
    comm_wrapper = None
    if args.strategy:
        from tpubft.testing.byzantine import strategy_wrapper
        comm_wrapper = strategy_wrapper(args.strategy)
    fault_ctl = None
    if args.fault_port is not None:
        from tpubft.testing.faults import FaultyComm

        def wrap_faulty(inner, signer=None, _prev=comm_wrapper):
            return FaultyComm(_prev(inner, signer=signer)
                              if _prev is not None else inner)

        comm_wrapper = wrap_faulty
    kr = build_replica(args, comm_wrapper)
    if args.fault_port is not None:
        # the FaultyComm is the outermost transport handed to the replica
        from tpubft.testing.faults import FaultControlServer
        fault_ctl = FaultControlServer(kr.replica.comm,
                                       port=args.fault_port)
        fault_ctl.start()
    metrics = UdpMetricsServer(kr.replica.aggregator,
                               port=args.metrics_port)
    metrics.start()
    prom = None
    if args.prom_port is not None:
        from tpubft.utils.metrics import PrometheusEndpoint
        prom = PrometheusEndpoint(kr.replica.aggregator,
                                  port=args.prom_port,
                                  host=args.prom_host)
        prom.start()
    diag = None
    if args.diag_port is not None:
        from tpubft.diagnostics import DiagnosticsServer
        diag = DiagnosticsServer(port=args.diag_port)
        diag.start()
    kr.start()
    diag_note = f", diag {diag.port}" if diag is not None else ""
    prom_note = f", prom {prom.port}" if prom is not None else ""
    print(f"skvbc replica {args.replica} up (metrics {metrics.port}"
          f"{diag_note}{prom_note})", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        kr.stop()
        metrics.stop()
        if prom is not None:
            prom.stop()
        if diag is not None:
            diag.stop()
        if fault_ctl is not None:
            fault_ctl.stop()


if __name__ == "__main__":
    main()
