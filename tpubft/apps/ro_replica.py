"""Read-only replica process — ledger follower/archiver.

Process wrapper for tpubft.kvbc.readonly.ReadOnlyReplica (reference: the
RO replica TesterReplica variant used by the Apollo RO/S3 suites): joins
the cluster's network as id n..n+num_ro-1, follows checkpoints, fetches
state, archives blocks to a filesystem object store, and serves
read-only queries.

Run:  python -m tpubft.apps.ro_replica --replica 4 --f 1 \
          --base-port 3710 --archive-dir /tmp/archive [--seed S]
"""
from __future__ import annotations

import argparse
import time

from tpubft.apps.simple_test import add_scheme_args, endpoint_table
from tpubft.comm import CommConfig, create_communication
from tpubft.consensus.keys import ClusterKeys
from tpubft.kvbc.readonly import ReadOnlyReplica
from tpubft.statetransfer.manager import StConfig
from tpubft.storage.objectstore import FsObjectStore
from tpubft.utils.config import ReplicaConfig
from tpubft.utils.metrics import Aggregator, UdpMetricsServer


def main() -> None:
    from tpubft.utils.logging import configure
    configure()
    p = argparse.ArgumentParser(description="read-only replica")
    p.add_argument("--replica", type=int, required=True)
    p.add_argument("--f", type=int, default=1)
    p.add_argument("--c", type=int, default=0)
    p.add_argument("--ro", type=int, default=1,
                   help="number of RO replicas in the topology")
    p.add_argument("--clients", type=int, default=4)
    p.add_argument("--base-port", type=int, default=3710)
    p.add_argument("--metrics-port", type=int, default=0)
    p.add_argument("--prom-port", type=int, default=None,
                   help="Prometheus /metrics HTTP port (0 = ephemeral)")
    p.add_argument("--prom-host", default="127.0.0.1")
    p.add_argument("--archive-dir", default=None)
    p.add_argument("--s3-endpoint", default=None,
                   help="archive to an S3-compatible store (host:port) "
                        "instead of --archive-dir")
    p.add_argument("--s3-bucket", default="tpubft-archive")
    p.add_argument("--s3-access-key", default="")
    p.add_argument("--s3-secret-key-env", default="TPUBFT_S3_SECRET",
                   help="env var holding the secret key (never a flag: "
                        "argv is world-readable)")
    p.add_argument("--seed", default="tpubft-skvbc")
    p.add_argument("--checkpoint-window", type=int, default=150)
    p.add_argument("--transport", default="udp",
                   choices=("udp", "tcp", "tls", "tls-mux"))
    p.add_argument("--certs-dir", default=None,
                   help="TLS material dir (node-<id>.key/.crt)")
    p.add_argument("--config-override", action="append", default=[],
                   metavar="FIELD=VALUE",
                   help="set any ReplicaConfig field (repeatable) — same "
                        "escape hatch as the skvbc replica binary")
    add_scheme_args(p)
    args = p.parse_args()

    kw = dict(replica_id=args.replica, f_val=args.f, c_val=args.c,
              num_ro_replicas=args.ro,
              num_of_client_proxies=args.clients,
              checkpoint_window_size=args.checkpoint_window,
              threshold_scheme=args.threshold_scheme,
              client_sig_scheme=args.client_sig_scheme)
    from tpubft.utils.config import parse_config_overrides
    kw.update(parse_config_overrides(args.config_override))
    cfg = ReplicaConfig(**kw)
    keys = ClusterKeys.generate(cfg, args.clients,
                                seed=args.seed.encode()
                                ).for_node(args.replica)
    # the endpoint table covers replicas + RO + clients contiguously
    eps = endpoint_table(args.base_port, cfg.n_val + args.ro, args.clients)
    if args.transport in ("tls", "tls-mux"):
        import os as _os

        from tpubft.comm.multiplex import client_floor
        from tpubft.comm.tls import TlsConfig
        comm_cfg = TlsConfig(self_id=args.replica, endpoints=eps,
                             certs_dir=args.certs_dir,
                             key_password=_os.environ.get(
                                 "TPUBFT_TLS_KEY_PASSWORD"),
                             mux_client_floor=(
                                 client_floor(cfg.n_val, args.ro)
                                 if args.transport == "tls-mux" else None))
    else:
        comm_cfg = CommConfig(self_id=args.replica, endpoints=eps)
    comm = create_communication(comm_cfg, args.transport)
    if args.s3_endpoint:
        import os as _os

        from tpubft.storage.s3 import S3ObjectStore
        store = S3ObjectStore(args.s3_endpoint, args.s3_bucket,
                              access_key=args.s3_access_key,
                              secret_key=_os.environ.get(
                                  args.s3_secret_key_env, ""))
    else:
        store = FsObjectStore(args.archive_dir) if args.archive_dir else None
    agg = Aggregator()
    ro = ReadOnlyReplica(cfg, keys, comm, object_store=store,
                         aggregator=agg, st_cfg=StConfig())
    metrics = UdpMetricsServer(agg, port=args.metrics_port)
    metrics.start()
    prom = None
    if args.prom_port is not None:
        from tpubft.utils.metrics import PrometheusEndpoint
        prom = PrometheusEndpoint(agg, port=args.prom_port,
                                  host=args.prom_host)
        prom.start()
    ro.start()
    prom_note = f", prom {prom.port}" if prom is not None else ""
    print(f"ro replica {args.replica} up (metrics {metrics.port}"
          f"{prom_note})", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        ro.stop()
        metrics.stop()
        if prom is not None:
            prom.stop()


if __name__ == "__main__":
    main()
