"""TesterClient — standalone random-workload checker process.

Rebuild of /root/reference/tests/simpleKVBC/TesterClient/: drives a live
SKVBC cluster with a concurrent randomized read/write workload, verifies
read-your-writes against a local model, and prints one JSON summary line
(ops, throughput, latency percentiles, check failures).

Run (against an skvbc_replica cluster sharing --base-port/--seed):
  python -m tpubft.apps.tester_client --f 1 --base-port 3710 \
      --ops 200 --concurrency 3 [--seed S] [--client-idx 0]
"""
from __future__ import annotations

import argparse
import json
import random
import statistics
import sys
import threading
import time

from tpubft.apps.simple_test import add_scheme_args, endpoint_table
from tpubft.apps.skvbc import SkvbcClient
from tpubft.bftclient import BftClient, ClientConfig
from tpubft.comm import CommConfig, PlainUdpCommunication
from tpubft.consensus.keys import ClusterKeys
from tpubft.utils.config import ReplicaConfig


def make_client(args, idx: int) -> SkvbcClient:
    cfg = ReplicaConfig(f_val=args.f, c_val=args.c,
                        num_of_client_proxies=args.clients,
                        threshold_scheme=args.threshold_scheme,
                        client_sig_scheme=args.client_sig_scheme)
    n = cfg.n_val
    client_id = n + args.client_idx + idx
    keys = ClusterKeys.generate(cfg, args.clients,
                                seed=args.seed.encode()).for_node(client_id)
    eps = endpoint_table(args.base_port, n, args.clients)
    comm = PlainUdpCommunication(CommConfig(self_id=client_id,
                                            endpoints=eps))
    cl = BftClient(ClientConfig(client_id=client_id, f_val=args.f,
                                c_val=args.c), keys, comm)
    cl.start()
    return SkvbcClient(cl)


def run_workload(args) -> dict:
    keys = [b"tk-%d" % i for i in range(args.keys)]
    model_lock = threading.Lock()
    model = {}                       # last value this process wrote per key
    lat, failures = [], []
    counts = [0] * args.concurrency

    def worker(w: int) -> None:
        rng = random.Random(args.workload_seed + w)
        kv = make_client(args, w)
        per = args.ops // args.concurrency
        for i in range(per):
            k = rng.choice(keys)
            try:
                if rng.random() < args.write_ratio:
                    if args.batch > 1:
                        # batched-workload mode: several independent
                        # write transactions on one wire message
                        # (ClientBatchRequestMsg)
                        kvs_payload = []
                        for j in range(args.batch):
                            bk = rng.choice(keys)
                            bv = b"%d-%d-%d-%d" % (w, i, j,
                                                   rng.randrange(1 << 30))
                            kvs_payload.append((bk, bv))
                        t0 = time.monotonic()
                        rs = kv.write_batch([[p] for p in kvs_payload],
                                            timeout_ms=args.timeout_ms)
                        lat.append(time.monotonic() - t0)
                        with model_lock:
                            for (bk, bv), r in zip(kvs_payload, rs):
                                if r.success:
                                    counts[w] += 1
                                    model[bk] = bv
                        continue
                    v = b"%d-%d-%d" % (w, i, rng.randrange(1 << 30))
                    t0 = time.monotonic()
                    r = kv.write([(k, v)], timeout_ms=args.timeout_ms)
                    lat.append(time.monotonic() - t0)
                    if r.success:
                        counts[w] += 1
                        with model_lock:
                            model[k] = v
                else:
                    t0 = time.monotonic()
                    got = kv.read([k], timeout_ms=args.timeout_ms)
                    lat.append(time.monotonic() - t0)
                    counts[w] += 1
                    with model_lock:
                        expect = model.get(k)
                    # read-your-writes: with concurrent writers the value
                    # may be NEWER than our model, never staler-than-none
                    if expect is not None and k not in got:
                        failures.append(f"key {k!r} vanished")
            except Exception as e:  # noqa: BLE001 — lossy clusters time out
                failures.append(f"op error: {type(e).__name__}")

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(args.concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    lat.sort()
    return {
        "ops_requested": args.ops, "ops_ok": sum(counts),
        "wall_s": round(wall, 2),
        "throughput_ops_sec": round(sum(counts) / wall, 1) if wall else 0,
        "mean_latency_ms": round(statistics.mean(lat) * 1e3, 2) if lat else None,
        "p99_latency_ms": round(lat[int(len(lat) * 0.99)] * 1e3, 2) if lat else None,
        "check_failures": failures[:10],
        "ok": not failures and sum(counts) > 0,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--f", type=int, default=1)
    ap.add_argument("--c", type=int, default=0)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--client-idx", type=int, default=0)
    ap.add_argument("--base-port", type=int, default=3710)
    ap.add_argument("--seed", default="tpubft-skvbc")
    ap.add_argument("--ops", type=int, default=100)
    ap.add_argument("--concurrency", type=int, default=2)
    ap.add_argument("--keys", type=int, default=16)
    ap.add_argument("--batch", type=int, default=1,
                    help=">1: each write op sends this many independent "
                         "transactions as one ClientBatchRequestMsg")
    ap.add_argument("--write-ratio", type=float, default=0.6)
    ap.add_argument("--timeout-ms", type=int, default=8000)
    ap.add_argument("--workload-seed", type=int, default=0xC11E47)
    add_scheme_args(ap)
    args = ap.parse_args()
    summary = run_workload(args)
    print(json.dumps(summary))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
