"""Counter state machine — the reference's simpleTest app.

Rebuild of /root/reference/tests/simpleTest/ (simple_test_replica.hpp):
the state is one signed 64-bit counter; writes add a delta and return the
new value; reads return the current value. Deterministic, so all replicas
agree on the state digest at every checkpoint.

Wire format: op byte 'A' (add) + i64 delta | 'R' (read). Replies: i64.
"""
from __future__ import annotations

import struct
from collections import OrderedDict

from tpubft.consensus.replica import IRequestsHandler
from tpubft.crypto.digest import digest as sha256
from tpubft.utils.racecheck import make_lock

_I64 = struct.Struct("<q")

# replay-idempotence records kept per client. Covers the committed suffix
# a WAL recovery can re-execute (bounded by the per-client in-flight cap,
# consensus.clients_manager.MAX_PENDING_PER_CLIENT = 128, plus slack).
_APPLIED_PER_CLIENT = 512


def encode_add(delta: int) -> bytes:
    return b"A" + _I64.pack(delta)


def encode_read() -> bytes:
    return b"R"


def decode_reply(reply: bytes) -> int:
    return _I64.unpack(reply)[0]


class CounterHandler(IRequestsHandler):
    def __init__(self) -> None:
        self._value = 0
        # client_id -> bounded set of applied req_seqs (membership, not a
        # watermark: requests execute out of seq order, so a lower seq is
        # not evidence of a replay)
        self._applied: dict = {}        # client_id -> OrderedDict[seq, None]
        self._applied_floor: dict = {}  # client_id -> highest evicted seq
        self._lock = make_lock("counter_app")

    def _was_applied(self, client_id: int, req_seq: int) -> bool:
        return (req_seq in self._applied.get(client_id, ())
                or req_seq <= self._applied_floor.get(client_id, 0))

    def _mark_applied(self, client_id: int, req_seq: int) -> None:
        seqs = self._applied.setdefault(client_id, OrderedDict())
        seqs[req_seq] = None
        while len(seqs) > _APPLIED_PER_CLIENT:
            evicted, _ = seqs.popitem(last=False)
            if evicted > self._applied_floor.get(client_id, 0):
                self._applied_floor[client_id] = evicted

    def _persist(self) -> None:
        pass

    @property
    def value(self) -> int:
        return self._value

    def execute(self, client_id: int, req_seq: int, flags: int,
                request: bytes) -> bytes:
        if request[:1] == b"A" and len(request) == 1 + _I64.size:
            delta = _I64.unpack(request[1:])[0]
            with self._lock:
                # replay idempotence: recovery re-executes the committed
                # suffix after the WAL's executed mark, which can trail
                # app state persisted mid-crash (the same reason kvbc
                # replays are keyed by block id — add_block of an
                # existing id is a no-op)
                if req_seq and self._was_applied(client_id, req_seq):
                    return _I64.pack(self._value)
                self._value += delta
                if req_seq:
                    self._mark_applied(client_id, req_seq)
                self._persist()
                return _I64.pack(self._value)
        if request[:1] == b"R":
            return _I64.pack(self._value)
        return b""

    def read(self, client_id: int, request: bytes) -> bytes:
        return _I64.pack(self._value)

    def state_digest(self) -> bytes:
        return sha256(b"counter" + _I64.pack(self._value))


class PersistentCounterHandler(CounterHandler):
    """Counter with durable state — the app-persistence role RocksDB plays
    in the reference (consensus metadata and app state are persisted
    separately; see kvbc/). Survives replica restart."""

    def __init__(self, path: str) -> None:
        super().__init__()
        self._path = path
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
            if len(raw) == _I64.size:       # legacy bare-i64 state file
                self._value = _I64.unpack(raw)[0]
            else:                           # current JSON format
                import json
                st = json.loads(raw)
                self._value = int(st["value"])
                for k, v in st.get("applied", {}).items():
                    if isinstance(v, list):
                        self._applied[int(k)] = OrderedDict(
                            (int(s), None) for s in v)
                    else:   # legacy watermark format: treat as floor
                        self._applied_floor[int(k)] = int(v)
                self._applied_floor.update(
                    {int(k): int(v)
                     for k, v in st.get("floor", {}).items()})
        except (OSError, ValueError, KeyError, struct.error):
            self._value = 0

    def _persist(self) -> None:
        """Value + per-client applied marks in ONE atomic replace: app
        state and its replay-idempotence index must never diverge."""
        import json
        import os
        tmp = self._path + ".tmp"
        applied = {c: list(seqs) for c, seqs in self._applied.items()}
        with open(tmp, "wb") as fh:
            fh.write(json.dumps({"value": self._value,
                                 "applied": applied,
                                 "floor": self._applied_floor}).encode())
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._path)
