"""Crashpoints — named kill-here hooks threaded through durability seams.

The recovery story of a BFT replica lives in the gaps between durable
writes: a crash *between* the ledger commit and the watermark persist,
or *between* persisting view-change state and broadcasting it, is where
exactly-once replay and view-change resumption are actually decided.
Apollo tortures those gaps with random process kills; random kills land
in the interesting window perhaps once in hundreds of runs. A
crashpoint makes the window a named, addressable place: the process
harness sets ``TPUBFT_CRASHPOINT=<name>`` (optionally ``<name>:<hit>``
to crash on the N-th arrival) and the replica process dies with
``CRASH_EXIT_CODE`` at *exactly* that seam; the recovery drill then
restarts it and asserts the invariants the seam is supposed to protect.

In-process clusters cannot ``os._exit`` (the test would die too), so the
same seams support *arming*: ``arm(name, rid=2)`` registers a callback
fired when replica 2 reaches the seam. The default callback parks the
calling thread forever — from the rest of the process's point of view
that replica stopped executing mid-seam, which is exactly what SIGKILL
looks like from the outside: no finally blocks, no flushes, no clean
shutdown. The drill then recovers from the on-disk state and asserts.

Every seam calls ``crashpoint("<name>", rid=...)``. The registry below
is the single source of truth; ``tools/check_crashpoints.py`` (tier-1)
verifies that every name used at a seam or referenced by a test exists
here, and that every registered name is actually threaded somewhere.
"""
from __future__ import annotations

import os
import threading
from typing import Callable, Dict, Optional, Tuple

# Exit code for an env-triggered crash: distinct from SIGKILL (-9),
# SIGTERM (-15) and python tracebacks (1), so a harness can assert "the
# replica died AT THE SEAM" rather than "the replica died".
CRASH_EXIT_CODE = 173

ENV_VAR = "TPUBFT_CRASHPOINT"

# name -> what crashing here must NOT be able to break (the invariant
# the recovery drill asserts)
REGISTRY: Dict[str, str] = {
    "exec.pre_apply": (
        "execution lane, after request execution, BEFORE the run's "
        "durable apply (ledger commit + reply pages): nothing of the run "
        "is durable — recovery replays the committed suffix from "
        "consensus metadata and re-executes it exactly once"),
    "exec.post_apply": (
        "execution lane, AFTER the run's durable apply but before any "
        "bookkeeping (reply cache, watermark, checkpoint vote): blocks "
        "and at-most-once markers are durable — recovery's replay must "
        "deduplicate against them (no double execution, no duplicate "
        "blocks, no ledger divergence)"),
    "exec.spec_seal": (
        "execution lane, speculative run fully commit-confirmed, BEFORE "
        "its durable apply (the seal's end_accumulation): nothing of "
        "the speculated run is durable — the staged overlay dies with "
        "the process, recovery replays the committed suffix from "
        "consensus metadata and re-executes it exactly once; a crash "
        "EARLIER (mid-speculation, commits not yet in) must leave no "
        "trace at all"),
    "vc.persist": (
        "view change, after persisting in_view_change/pending_view/"
        "evidence but BEFORE broadcasting the ViewChangeMsg: the restart "
        "must resume the view change from storage and retransmit an "
        "equivalent ViewChangeMsg, or a quorum counting on this replica "
        "wedges forever"),
    "vc.enter": (
        "view entry, after persisting the new view + restrictions but "
        "BEFORE the new primary re-proposes: the restart must re-issue "
        "the restricted PrePrepares (Replica.start's repropose path)"),
    "ckpt.stable": (
        "checkpoint stability, BEFORE persisting the window slide: the "
        "restart re-derives stability from peers' checkpoint messages; "
        "nothing already GC'd may be needed again"),
    "st.window_adopt": (
        "state transfer, after a fetched window's digests verified but "
        "BEFORE its blocks are committed to the ledger: recovery "
        "restarts the fetch — a half-adopted window must never leave "
        "blocks the digest chain does not cover"),
    "meta.watermark": (
        "dispatcher, AFTER persisting the last_executed watermark for an "
        "applied run but before replies/checkpoint votes go out: clients "
        "retry into the reply cache; peers' checkpoint quorum proceeds "
        "without our vote"),
    "dur.group_fsync": (
        "durability io thread, after the group's concatenated apply but "
        "BEFORE its fsync and watermark publication: every run of the "
        "group is executed and maybe-on-disk (the OS owns the buffers) "
        "but no reply went out and last_executed never advanced — "
        "recovery replays the committed suffix from consensus metadata "
        "and the reserved-pages at-most-once state deduplicates "
        "whatever did land (exactly-once, no ledger divergence)"),
}

_mu = threading.Lock()
# (name, rid|None) -> [hits_remaining, action]
_armed: Dict[Tuple[str, Optional[int]], list] = {}
_env_spec: Optional[Tuple[str, int]] = None
_env_hits = 0


def _load_env_spec() -> Optional[Tuple[str, int]]:
    raw = os.environ.get(ENV_VAR, "").strip()
    if not raw:
        return None
    name, _, hit = raw.partition(":")
    try:
        return name, max(1, int(hit)) if hit else 1
    except ValueError:
        return name, 1


_park_event = threading.Event()


def park() -> None:
    """Default in-process 'crash': the calling thread stops here and
    runs no further instruction until release_parked() (daemon threads —
    the test process exits fine even if never released). Identical to
    SIGKILL as observed by the on-disk state: whatever was not yet
    durable at the seam never becomes durable."""
    _park_event.wait()


_park_forever = park


def release_parked() -> None:
    """Unstick threads parked by park() — called at drill teardown so a
    parked exec-lane/dispatcher thread can observe its stop flag instead
    of making the owner's stop() eat a full join timeout. Future parks
    use a fresh event."""
    global _park_event
    old, _park_event = _park_event, threading.Event()
    old.set()


def crashpoint(name: str, rid: Optional[int] = None) -> None:
    """Durability-seam hook. No-op unless this exact point was requested
    via env (process mode → os._exit) or arm() (in-process mode)."""
    global _env_spec, _env_hits
    if name not in REGISTRY:
        raise AssertionError(f"unregistered crashpoint {name!r} "
                             f"(add it to crashpoints.REGISTRY)")
    spec = _env_spec if _env_spec is not None else _load_env_spec()
    _env_spec = spec or ("", 0)
    if spec and spec[0] == name:
        with _mu:
            _env_hits += 1
            due = _env_hits == spec[1]
        if due:
            # a real crash: no atexit, no finally, no flush
            os._exit(CRASH_EXIT_CODE)
    if not _armed:
        return
    with _mu:
        ent = _armed.get((name, rid)) or _armed.get((name, None))
        if ent is None or ent[0] <= 0:
            return
        ent[0] -= 1
        action = ent[1]
    (action or _park_forever)()


def arm(name: str, rid: Optional[int] = None, hits: int = 1,
        action: Optional[Callable[[], None]] = None) -> None:
    """In-process mode: fire `action` (default: park the thread forever,
    the SIGKILL analog) the next `hits` times replica `rid` (None = any)
    reaches seam `name`."""
    if name not in REGISTRY:
        raise AssertionError(f"unregistered crashpoint {name!r}")
    with _mu:
        _armed[(name, rid)] = [hits, action]


def disarm_all() -> None:
    with _mu:
        _armed.clear()


def reset_env_cache() -> None:
    """Re-read TPUBFT_CRASHPOINT on next hit (tests mutate the env)."""
    global _env_spec, _env_hits
    with _mu:
        _env_spec = None
        _env_hits = 0
