"""In-process multi-replica cluster over the loopback bus.

The unit/integration-test equivalent of the reference's in-process
multi-node fixtures (client/bftclient fake_comm.h quorum simulations +
tests/simpleTest in-proc mode): n replicas + clients share one LoopbackBus,
so byzantine hooks (drop/mutate) apply to the whole cluster.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from tpubft.bftclient import BftClient, ClientConfig
from tpubft.comm.loopback import LoopbackBus
from tpubft.consensus.keys import ClusterKeys
from tpubft.consensus.persistent import PersistentStorage
from tpubft.consensus.replica import IRequestsHandler, Replica
from tpubft.utils.config import ReplicaConfig
from tpubft.utils.metrics import Aggregator


class InProcessCluster:
    def __init__(self, f: int = 1, c: int = 0, num_clients: int = 2,
                 handler_factory: Optional[Callable[[], IRequestsHandler]] = None,
                 cfg_overrides: Optional[dict] = None,
                 storage_factory: Optional[Callable[[int], PersistentStorage]] = None,
                 seed: bytes = b"tpubft-test-cluster",
                 byzantine: Optional[Dict[int, str]] = None):
        from tpubft.apps.counter import CounterHandler
        self.handler_factory = handler_factory or CounterHandler
        base_cfg = ReplicaConfig(f_val=f, c_val=c,
                                 num_of_client_proxies=num_clients,
                                 **(cfg_overrides or {}))
        self.n = base_cfg.n_val
        # client ids start after any read-only replicas (reference id
        # convention: replicas, RO replicas, then clients)
        self.first_client_id = base_cfg.n_val + base_cfg.num_ro_replicas
        self.bus = LoopbackBus()
        self.keys = ClusterKeys.generate(base_cfg, num_clients, seed=seed)
        self.aggregators: Dict[int, Aggregator] = {}
        self.handlers: Dict[int, IRequestsHandler] = {}
        self.replicas: Dict[int, Replica] = {}
        self.storage_factory = storage_factory
        # replica_id -> byzantine strategy name (testing/byzantine.py):
        # that replica's transport is wrapped exactly like the tester
        # replica's --strategy flag, signer in hand for re-signing
        # strategies (equivocate)
        self.byzantine = dict(byzantine or {})
        self._pages_dbs: Dict[int, object] = {}
        self._cfg_overrides = cfg_overrides or {}
        self._num_clients = num_clients
        self.f, self.c = f, c
        for r in range(self.n):
            self._make_replica(r)
        self.clients: Dict[int, BftClient] = {}

    def _make_replica(self, r: int) -> Replica:
        cfg = ReplicaConfig(replica_id=r, f_val=self.f, c_val=self.c,
                            num_of_client_proxies=self._num_clients,
                            **self._cfg_overrides)
        agg = self.aggregators[r] = Aggregator()
        try:
            handler = self.handler_factory(r)   # id-aware factories
        except TypeError:
            handler = self.handler_factory()
        self.handlers[r] = handler
        storage = (self.storage_factory(r) if self.storage_factory else None)
        # reserved pages survive an in-process restart (deployed replicas
        # keep them in the ledger db): restart/crash tests must exercise
        # the page reload paths, not silently start from empty pages.
        # Blockchain-backed handlers share the LEDGER's db — the same
        # deliberate wiring as KvbcReplica, so the lane folds reply
        # pages into the run batch (atomic apply, and the durability
        # pipeline's deferred-seal path stays exercised in-process);
        # page persistence across restart then rides the handler db.
        from tpubft.consensus.reserved_pages import ReservedPages
        from tpubft.kvbc.blockchain import raw_base
        _bc = getattr(handler, "blockchain", None)
        _bc_db = raw_base(getattr(_bc, "_db", None)
                          if _bc is not None else None)
        if _bc_db is not None:
            pages = self._pages_dbs[r] = ReservedPages(_bc_db)
        else:
            pages = self._pages_dbs.get(r)
            if pages is None:
                from tpubft.storage.memorydb import MemoryDB
                pages = self._pages_dbs[r] = ReservedPages(MemoryDB())
        node_keys = self.keys.for_node(r)
        comm = self.bus.create(r)
        strategy = self.byzantine.get(r)
        if strategy:
            from tpubft.testing.byzantine import strategy_wrapper
            comm = strategy_wrapper(strategy)(
                comm, signer=node_keys.my_signer())
        rep = Replica(cfg, node_keys, comm,
                      handler, storage=storage, aggregator=agg,
                      reserved_pages=pages)
        # KVBC-backed handlers get a state-transfer manager, mirroring
        # KvbcReplica wiring (handlers expose .blockchain for this)
        bc = getattr(handler, "blockchain", None)
        if bc is not None:
            from tpubft.statetransfer import StateTransferManager
            from tpubft.statetransfer.manager import StConfig
            rep.set_state_transfer(StateTransferManager(
                r, bc, StConfig(retry_timeout_s=0.3),
                reserved_pages=rep.res_pages, aggregator=agg))
        from tpubft.reconfiguration.dispatcher import standard_dispatcher
        rep.set_reconfiguration(standard_dispatcher(blockchain=bc))
        self.replicas[r] = rep
        return rep

    def start(self) -> "InProcessCluster":
        for rep in self.replicas.values():
            rep.start()
        return self

    def stop(self) -> None:
        for cl in self.clients.values():
            cl.stop()
        for rep in self.replicas.values():
            rep.stop()
        self.bus.shutdown()

    def operator_client(self, **cfg_kw):
        """BFT client bound to the operator principal + reconfiguration
        command helpers."""
        from tpubft.reconfiguration import OperatorClient
        info = next(iter(self.replicas.values())).info
        op_id = info.operator_id
        cl = self.clients.get(op_id)
        if cl is None:
            cfg = ClientConfig(client_id=op_id, f_val=self.f,
                               c_val=self.c, **cfg_kw)
            cl = BftClient(cfg, self.keys.for_node(op_id),
                           self.bus.create(op_id))
            self.clients[op_id] = cl
        cl.start()
        return OperatorClient(cl)

    def client(self, idx: int = 0, **cfg_kw) -> BftClient:
        client_id = self.first_client_id + idx
        cl = self.clients.get(client_id)
        if cl is None:
            cfg = ClientConfig(client_id=client_id, f_val=self.f,
                               c_val=self.c, **cfg_kw)
            cl = BftClient(cfg, self.keys.for_node(client_id),
                           self.bus.create(client_id))
            self.clients[client_id] = cl
        return cl

    # ---- fault injection ----
    def kill(self, replica_id: int) -> None:
        self.replicas[replica_id].stop()

    def restart(self, replica_id: int) -> Replica:
        """Stop + recreate from persistent storage (crash recovery)."""
        self.kill(replica_id)
        rep = self._make_replica(replica_id)
        rep.start()
        return rep

    def crash(self, replica_id: int) -> Replica:
        """Crash-recover WITHOUT a clean stop: the old instance is
        abandoned exactly as it stands (its threads may be parked at a
        crashpoint seam), the loopback endpoint is rebound to a new
        replica restored from persistent storage — the in-process analog
        of SIGKILL + restart. Only state that reached storage (or the
        surviving reserved-pages db) is recovered."""
        old = self.replicas.pop(replica_id, None)  # no stop(): it crashed
        if old is not None:
            # mute the abandoned instance's transport (flag flip only —
            # no joins, no clean shutdown): a SIGKILLed process sends
            # nothing, and an old thread that is merely parked (or still
            # running) must not keep emitting with the recovered
            # replica's identity — that would be accidental equivocation
            old.comm.stop()
        rep = self._make_replica(replica_id)      # bus.create() rebinds
        rep.start()
        return rep

    def metric(self, replica_id: int, kind: str, name: str,
               component: str = "replica"):
        return self.aggregators[replica_id].get(component, kind, name)

    def __enter__(self) -> "InProcessCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
