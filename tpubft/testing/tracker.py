"""SkvbcTracker — linearizability oracle for concurrent KV histories.

Rebuild of the reference's correctness oracle
(/root/reference/tests/apollo/util/skvbc_history_tracker.py, 852 LoC):
clients log every operation with its real-time window; verification
exploits SKVBC's structure — every successful write reports the block id
it created, giving the ground-truth total order — and checks that

  1. block ids are unique and writes are consistent with them,
  2. every read returns a state reachable at SOME block within the
     read's real-time window (reads must not see the future, nor miss
     writes that completed before they started),
  3. conditional writes that failed really had a conflict (some readset
     key was written after the stated read_version).

Thread-safe: many client workers log concurrently.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


class LinearizabilityError(AssertionError):
    pass


@dataclass
class _WriteOp:
    start: float
    end: float
    writeset: Dict[bytes, bytes]
    readset: List[bytes]
    read_version: int
    success: bool
    block_id: Optional[int]   # reported by the reply (success only)


@dataclass
class _ReadOp:
    start: float
    end: float
    values: Dict[bytes, bytes]   # key -> value (missing = absent)
    keys: List[bytes]


class SkvbcTracker:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.writes: List[_WriteOp] = []
        self.reads: List[_ReadOp] = []

    # ---- client-side logging ----
    def start_op(self) -> float:
        return time.monotonic()

    def log_write(self, start: float, writeset: Sequence[Tuple[bytes, bytes]],
                  reply, readset: Sequence[bytes] = (),
                  read_version: int = 0) -> None:
        op = _WriteOp(start=start, end=time.monotonic(),
                      writeset=dict(writeset), readset=list(readset),
                      read_version=read_version, success=reply.success,
                      block_id=reply.latest_block if reply.success else None)
        with self._lock:
            self.writes.append(op)

    def log_read(self, start: float, keys: Sequence[bytes],
                 values: Dict[bytes, bytes]) -> None:
        op = _ReadOp(start=start, end=time.monotonic(),
                     values=dict(values), keys=list(keys))
        with self._lock:
            self.reads.append(op)

    # ---- verification ----
    def verify(self) -> None:
        with self._lock:
            writes = list(self.writes)
            reads = list(self.reads)

        # empty-writeset writes succeed without creating a block — their
        # reported latest_block belongs to someone else
        committed = [w for w in writes if w.success and w.writeset]
        by_block: Dict[int, _WriteOp] = {}
        for w in committed:
            if w.block_id in by_block:
                # two successful writes reporting the same created block
                other = by_block[w.block_id]
                if other.writeset != w.writeset:
                    raise LinearizabilityError(
                        f"two distinct writes claim block {w.block_id}")
            else:
                by_block[w.block_id] = w

        # ground-truth state history from the block order
        blocks = sorted(by_block)
        state_at: Dict[int, Dict[bytes, bytes]] = {}
        last_written: Dict[bytes, List[Tuple[int, bytes]]] = {}
        state: Dict[bytes, bytes] = {}
        prev = 0
        for b in blocks:
            state = dict(state)
            for k, v in by_block[b].writeset.items():
                state[k] = v
                last_written.setdefault(k, []).append((b, v))
            state_at[b] = state
            prev = b
        state_at[0] = {}

        def state_at_or_before(b: int) -> Dict[bytes, bytes]:
            candidates = [x for x in blocks if x <= b]
            return state_at[candidates[-1]] if candidates else {}

        # real-time bounds: a read starting after write w completed must
        # observe a block >= w.block_id; a read must not observe blocks
        # created after it finished
        for r in reads:
            lower = 0
            for w in committed:
                if w.end < r.start and w.block_id is not None:
                    lower = max(lower, w.block_id)
            upper = max([b for b in blocks
                         if by_block[b].start <= r.end] + [0])
            ok = False
            for b in range(lower, upper + 1):
                snap = state_at_or_before(b)
                if all(snap.get(k) == r.values.get(k) for k in r.keys):
                    ok = True
                    break
            if not ok:
                raise LinearizabilityError(
                    f"read {r.keys} -> {r.values} matches no state in "
                    f"blocks [{lower}, {upper}]")

        # failed conditional writes must have had a real conflict window:
        # some readset key was written in a block > read_version by an op
        # overlapping or preceding the failed write
        for w in writes:
            if w.success or not w.readset:
                continue
            conflict = any(
                any(b > w.read_version and ow.start <= w.end
                    for b, _v in last_written.get(k, [])
                    for ow in [by_block[b]])
                for k in w.readset)
            if not conflict:
                raise LinearizabilityError(
                    f"write conditioned on v{w.read_version} "
                    f"readset={w.readset} failed without any conflicting "
                    f"write")

    def summary(self) -> str:
        ok_writes = sum(1 for w in self.writes if w.success)
        return (f"{len(self.writes)} writes ({ok_writes} committed, "
                f"{len(self.writes) - ok_writes} rejected), "
                f"{len(self.reads)} reads")
